"""Byzantine campaign runner — seeded misbehavior under production load,
with machine-checked safety, accountability, and detection verdicts.

ISSUE 18: the chaos plane (loadgen/chaos.py) proved the net survives
crash-shaped faults; this module proves it survives LIES. Each
`ByzScenario` boots a fresh in-process localnet, arms the byzantine
adversary plane (consensus/byzantine.py, the TM_TPU_BYZ contract) so
one designated validator misbehaves on a seeded schedule, drives the
seeded tmload open-loop traffic gun at the net for the whole run, and
renders per-scenario verdicts, all machine-checked:

* **safety** — byte-identical stored block-ID hashes across ALL honest
  nodes at every common height (chaos.py's `_safety_check`, reused).
  With the victim at 10/40 voting power (f=1 < n/3) ANY divergence
  fails the scenario.
* **accountability** — every injected equivocation height yields a
  committed `DuplicateVoteEvidence` naming the victim within the
  scenario's `evidence_slo_s`, each evidence item committed exactly
  once, height-stamped via the flight recorder's `evidence_seen` /
  `evidence_committed` timeline events (consensus/timeline.py).
* **detection** — the `lightclient_fork` control scenario forges a
  2-of-4 coalition block (20/40 = 1/2 ≥ 1/3 of trusted power: enough
  to pass the light client's skipping-verification trust check) and
  serves it from a lying primary; the divergence detector must raise
  `DivergenceError` against the honest witness and report attack
  evidence to the providers.
* **double-sign protection** — the `double_sign_guard` arc SIGKILLs
  the victim between last-sign-state fsync and vote broadcast (the
  `privval.release` fault point, crypto/faults.py) on a sqlite-backed
  net, restarts it, and requires that NO duplicate-vote evidence
  naming the victim is ever committed: the persisted last-sign state
  is the double-sign guard, and the crash window must not defeat it.

Reproducibility is the PR-3/PR-18 plane contract end to end: byzantine
rules own a `random.Random(seed)` derived from the campaign seed, the
traffic arrival schedule is the seeded tmload schedule, and the forged
coalition signs with the localnet's seed-derived validator keys.

bench.py's `byz_smoke` row runs the shipped catalog in the banked
jax-free CPU block and persists the trajectory as BENCH_BYZ.json.
docs/resilience.md documents the scenario catalog and SLO policy.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..consensus import byzantine
from ..consensus.timeline import EV_EVIDENCE_COMMITTED, EV_EVIDENCE_SEEN
from ..crypto import faults
from ..crypto.ed25519 import PrivKeyEd25519
from ..libs.rng import subseed as _subseed
from ..light import Client, DivergenceError, LightStore, TrustOptions
from ..light.provider import LocalProvider
from ..store.kv import MemKV
from ..types.block_id import BlockID
from ..types.canonical import PRECOMMIT_TYPE
from ..types.evidence import DuplicateVoteEvidence
from ..types.light import LightBlock, SignedHeader
from ..types.part_set import PartSetHeader
from ..types.validator import Validator, ValidatorSet
from ..types.commit import Commit, CommitSig
from ..types.vote import Vote
from . import timeline as fleet_timeline
from .chaos import _heights, _safety_check, _wait_heights_above
from .driver import ClientPool, run_open_loop
from .localnet import Localnet, start_localnet
from .scenario import Scenario

__all__ = [
    "ByzScenario",
    "run_byz_campaign",
    "run_byz_scenario",
    "shipped_byz_scenarios",
]

_HOUR_NS = 3600 * 1_000_000_000
_VICTIM_IDX = 1  # load1: the adversary plane's default victim


@dataclass
class ByzScenario:
    """One byzantine arc. `kind` picks the machinery:

    behavior          spec is a TM_TPU_BYZ rule fragment (`{seed}` is
                      filled with the scenario seed) armed BEFORE the
                      localnet boots so node assembly installs the
                      harness on the victim; the run waits for the
                      fleet to clear the misbehavior height window,
                      then renders safety + (optionally) the evidence
                      accountability verdict
    lightclient_fork  no consensus misbehavior: a ≥1/3 coalition block
                      is forged at the provider layer and served by a
                      lying light-client primary against an honest
                      witness — the detection control scenario
    double_sign_guard no byzantine rules either: the PRODUCTION signer
                      is crashed between state fsync and broadcast
                      (privval.release fault point) and restarted on
                      sqlite stores; the verdict is the absence of
                      evidence naming the victim
    """

    name: str
    kind: str = "behavior"
    spec: str = ""  # TM_TPU_BYZ fragment, "{seed}" substituted
    h_lo: int = 4
    h_hi: int = 6
    evidence_slo_s: Optional[float] = None  # None = no evidence verdict
    expect_fired: bool = True  # require the rule to actually fire
    baseline_s: float = 1.0
    recovery_slo_s: float = 20.0
    # extra crypto/faults.py net rules armed only while the fleet is
    # inside the misbehavior window (amnesia needs round churn: vote
    # delay past timeout_prevote forces round > 0 so locks can form
    # and then be forgotten)
    net_rules: list = field(default_factory=list)

    def db_backend(self) -> str:
        # the restart arc needs stores that survive the node instance
        return "sqlite" if self.kind == "double_sign_guard" else "memdb"


def shipped_byz_scenarios() -> List[ByzScenario]:
    """The shipped catalog (4-node nets, victim load1 at 10/40 power;
    docs/resilience.md): duplicate-vote equivocation at both vote
    steps, conflicting proposals, amnesia under round churn, vote
    withholding, the ≥1/3 light-client fork control, and the
    crash-between-fsync-and-broadcast double-sign guard."""
    vote_ch = 0x22  # consensus VOTE_CHANNEL
    return [
        ByzScenario(
            name="equivocate_prevote",
            spec="equivocate:h=4..6:step=prevote:seed={seed}",
            h_lo=4,
            h_hi=6,
            evidence_slo_s=15.0,
        ),
        ByzScenario(
            name="equivocate_precommit",
            spec="equivocate:h=4..6:step=precommit:seed={seed}",
            h_lo=4,
            h_hi=6,
            evidence_slo_s=15.0,
        ),
        ByzScenario(
            # the victim proposes ~1 height in 4 (round-robin): the
            # window spans 8 heights so it is proposer at least once
            name="conflicting_proposal",
            spec="conflicting_proposal:h=4..11:seed={seed}",
            h_lo=4,
            h_hi=11,
        ),
        ByzScenario(
            # no duplicate-vote evidence exists across rounds — the
            # amnesia verdict is safety-only; fired count is recorded
            # but not required (a lock at round > 0 on the victim is
            # churn-dependent)
            name="amnesia",
            spec="amnesia:h=4..7:seed={seed}",
            h_lo=4,
            h_hi=7,
            expect_fired=False,
            net_rules=[
                {
                    "point": "p2p.send",
                    "mode": "delay",
                    "p": 0.3,
                    "delay_s": 1.1,
                    "ch": vote_ch,
                }
            ],
            recovery_slo_s=30.0,
        ),
        ByzScenario(
            # liveness pressure, never evidence: 30/40 honest power
            # still clears 2/3 so the chain must keep committing
            name="withhold",
            spec="withhold:h=4..6:seed={seed}",
            h_lo=4,
            h_hi=6,
        ),
        ByzScenario(
            name="lightclient_fork",
            kind="lightclient_fork",
        ),
        ByzScenario(
            name="double_sign_guard",
            kind="double_sign_guard",
            recovery_slo_s=30.0,
        ),
    ]


def _victim_address(scenario_seed: int, idx: int = _VICTIM_IDX) -> bytes:
    """The victim's validator address, recomputed from the localnet's
    seed-derived key schedule (loadgen/localnet.py)."""
    priv = PrivKeyEd25519.from_seed(
        scenario_seed.to_bytes(8, "big") + bytes([idx]) * 24
    )
    return priv.pub_key().address()


def _committed_evidence(
    ln: Localnet, victim_addr: bytes
) -> List[Tuple[int, DuplicateVoteEvidence]]:
    """(commit height, evidence) for every committed DuplicateVote-
    Evidence naming the victim, read from node 0's store (the safety
    check separately proves all stores hold identical blocks)."""
    out: List[Tuple[int, DuplicateVoteEvidence]] = []
    store = ln.nodes[0].block_store
    for h in range(1, store.height() + 1):
        block = store.load_block(h)
        if block is None:
            continue
        for ev in block.evidence:
            if (
                isinstance(ev, DuplicateVoteEvidence)
                and ev.vote_a.validator_address == victim_addr
            ):
                out.append((h, ev))
    return out


def _evidence_unique(ln: Localnet) -> bool:
    """Each evidence item must be committed exactly once chain-wide
    (the pool's committed-set must stop re-proposal and re-commit)."""
    seen: set = set()
    store = ln.nodes[0].block_store
    for h in range(1, store.height() + 1):
        block = store.load_block(h)
        if block is None:
            continue
        for ev in block.evidence:
            k = ev.hash()
            if k in seen:
                return False
            seen.add(k)
    return True


async def _wait_evidence(
    ln: Localnet,
    victim_addr: bytes,
    want_heights: set,
    timeout_s: float,
) -> Tuple[Optional[float], List[Tuple[int, DuplicateVoteEvidence]]]:
    """Poll node 0's store until committed duplicate-vote evidence
    covers every height in `want_heights`; returns (seconds it took or
    None on timeout, the rows found either way)."""
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    rows: List[Tuple[int, DuplicateVoteEvidence]] = []
    while time.monotonic() < deadline:
        rows = _committed_evidence(ln, victim_addr)
        if want_heights <= {ev.height() for _, ev in rows}:
            return time.monotonic() - t0, rows
        await asyncio.sleep(0.1)
    return None, rows


def _evidence_timeline(fleet: Dict[str, List[dict]]) -> dict:
    """The flight-recorder stamp of the evidence lifecycle: first
    detection tick, first commit tick, and the detect→commit latency
    those two pins give (all nodes share one wall clock — the
    in-process localnet's standing assumption)."""
    seen = [
        e
        for evs in fleet.values()
        for e in evs
        if e["kind"] == EV_EVIDENCE_SEEN
    ]
    committed = [
        e
        for evs in fleet.values()
        for e in evs
        if e["kind"] == EV_EVIDENCE_COMMITTED
    ]
    t_seen = min((e["t_wall_ns"] for e in seen), default=None)
    t_commit = min((e["t_wall_ns"] for e in committed), default=None)
    return {
        "evidence_seen_events": len(seen),
        "evidence_committed_events": len(committed),
        "evidence_seen_heights": sorted({e["height"] for e in seen}),
        "evidence_committed_at": sorted(
            {e["height"] for e in committed}
        ),
        "detect_to_commit_s": (
            round((t_commit - t_seen) / 1e9, 3)
            if t_seen is not None and t_commit is not None
            else None
        ),
    }


def _start_traffic(
    ln: Localnet, scenario_seed: int, rate: float, duration_s: float
) -> Tuple[asyncio.Future, List[ClientPool]]:
    scn = Scenario(
        seed=scenario_seed,
        mode="open",
        duration_s=duration_s,
        rate=rate,
        ramp_s=0.5,
        subscribers=0,
        max_inflight=32,
        timeout_s=3.0,
        mix=(("broadcast_tx_async", 3.0), ("status", 1.0)),
    ).validate()
    per_pool = max(1, scn.max_inflight // len(ln.rpc_addrs))
    pools = [
        ClientPool(a, size=per_pool, timeout_s=scn.timeout_s)
        for a in ln.rpc_addrs
    ]
    return asyncio.ensure_future(run_open_loop(scn, pools)), pools


async def run_byz_scenario(
    sc: ByzScenario,
    home: str,
    n_nodes: int = 4,
    seed: int = 2026,
    rate: float = 50.0,
) -> dict:
    """Boot a fresh localnet, run one byzantine arc under open-loop
    traffic, tear down, return the verdict row."""
    scenario_seed = _subseed(seed, sc.name)
    if sc.kind == "lightclient_fork":
        return await _run_lightclient_fork(
            sc, home, n_nodes, scenario_seed, rate
        )
    if sc.kind == "double_sign_guard":
        return await _run_double_sign_guard(
            sc, home, n_nodes, scenario_seed, rate
        )
    if sc.kind != "behavior":
        raise ValueError(f"unknown byzantine kind {sc.kind!r}")

    # arm BEFORE boot: hooks install at node assembly (byzantine.py)
    os.environ["TM_TPU_BYZ"] = sc.spec.format(seed=scenario_seed)
    byzantine.load_env()
    assert byzantine.armed(), sc.spec
    victim_addr = _victim_address(scenario_seed)
    ln = await start_localnet(
        n_nodes,
        os.path.join(home, sc.name),
        chain_id=f"byz-{sc.name}",
        seed=scenario_seed,
        db_backend=sc.db_backend(),
    )
    traffic: Optional[asyncio.Future] = None
    pools: List[ClientPool] = []
    try:
        traffic, pools = _start_traffic(
            ln, scenario_seed, rate, sc.baseline_s + 15.0
        )
        base_ok = await _wait_heights_above(
            ln, min(_heights(ln)), timeout_s=20.0
        )
        await asyncio.sleep(sc.baseline_s)

        # hold any riding net faults for the whole misbehavior window:
        # the fleet clearing h_hi means every armed height was played
        with contextlib.ExitStack() as stack:
            for i, r in enumerate(sc.net_rules):
                stack.enter_context(
                    faults.inject(
                        r["point"],
                        r["mode"],
                        p=r.get("p", 1.0),
                        seed=_subseed(scenario_seed, f"{sc.name}-net{i}"),
                        src=r.get("src"),
                        dst=r.get("dst"),
                        ch=r.get("ch"),
                        delay_s=r.get("delay_s", 0.05),
                    )
                )
            window_ok = await _wait_heights_above(
                ln, sc.h_hi, timeout_s=sc.recovery_slo_s * 2 + 10.0
            )

        fired = [
            f for h in byzantine.harnesses() for f in h.fired
        ]
        fired_heights = sorted({f[1] for f in fired})
        behavior = sc.spec.split(":", 1)[0]

        tte: Optional[float] = None
        ev_rows: List[Tuple[int, DuplicateVoteEvidence]] = []
        accountable = True
        if sc.evidence_slo_s is not None:
            want = {
                f[1] for f in fired if f[0] == "equivocate"
            }
            tte, ev_rows = await _wait_evidence(
                ln, victim_addr, want, timeout_s=sc.evidence_slo_s
            )
            accountable = bool(want) and tte is not None
        else:
            # misbehavior without conflicting signatures (or none at
            # all) must NEVER produce evidence against the victim
            ev_rows = _committed_evidence(ln, victim_addr)
            accountable = not ev_rows

        safety = _safety_check(ln)
        unique_ok = _evidence_unique(ln)
        stats, scheduled = await traffic
        traffic = None
        fleet = fleet_timeline.collect(ln)
        ev_tl = _evidence_timeline(fleet)
        fired_ok = bool(fired) if sc.expect_fired else True
        row = {
            "name": sc.name,
            "kind": sc.kind,
            "behavior": behavior,
            "seed": scenario_seed,
            "spec": os.environ.get("TM_TPU_BYZ", ""),
            "victim": f"load{_VICTIM_IDX}",
            "evidence_slo_s": sc.evidence_slo_s,
            "baseline_commit_ok": base_ok is not None,
            "window_cleared": window_ok is not None,
            "fired": len(fired),
            "fired_heights": fired_heights,
            "tte_evidence_commit_s": (
                round(tte, 3) if tte is not None else None
            ),
            "evidence_committed": len(ev_rows),
            "evidence_heights": sorted(
                {ev.height() for _, ev in ev_rows}
            ),
            "evidence_committed_at": sorted({h for h, _ in ev_rows}),
            "evidence_unique_ok": unique_ok,
            "accountable": accountable,
            **safety,
            "timeline": ev_tl,
            "requests_total": sum(st.count for st in stats.values()),
            "request_errors": sum(st.errors for st in stats.values()),
            "scheduled_arrivals": scheduled,
            "consults": byzantine.consults(),
            "passed": bool(
                safety["safety_ok"]
                and base_ok is not None
                and window_ok is not None
                and fired_ok
                and accountable
                and unique_ok
            ),
        }
        return row
    finally:
        os.environ.pop("TM_TPU_BYZ", None)
        byzantine.reset()
        faults.set_partition("")
        if traffic is not None:
            traffic.cancel()
            await asyncio.gather(traffic, return_exceptions=True)
        for p in pools:
            await p.close()
        await ln.stop()


# ---------------------------------------------------------------------------
# lightclient_fork: the ≥1/3 detection control


class _LyingPrimary(LocalProvider):
    """Serves the node's real chain everywhere EXCEPT the forged
    height — the minimal lying primary: its history verifies, so the
    only thing that can catch the fork is an honest witness."""

    def __init__(self, block_store, state_store, forged: LightBlock):
        super().__init__(block_store, state_store, id_="lying-primary")
        self.forged = forged

    async def light_block(self, height: int) -> LightBlock:
        if height == self.forged.height:
            return self.forged
        return await super().light_block(height)


def _forge_coalition_block(
    honest: LightBlock, chain_id: str, scenario_seed: int
) -> LightBlock:
    """A properly-signed conflicting block at `honest.height`, signed
    by a 2-of-4 coalition of the localnet's REAL validators (20/40 =
    1/2 of trusted power: past the light client's 1/3 trust level, and
    2/2 of the block's own declared set). Only app_hash and
    validators_hash differ from the honest header — the forgery an
    attacker with 1/3+ of stake can actually produce."""
    coalition_privs = [
        PrivKeyEd25519.from_seed(
            scenario_seed.to_bytes(8, "big") + bytes([i]) * 24
        )
        for i in range(2)
    ]
    pairs = [
        (Validator(pub_key=p.pub_key(), voting_power=10), p)
        for p in coalition_privs
    ]
    coalition = ValidatorSet([v for v, _ in pairs])
    by_addr = {v.address: p for v, p in pairs}
    header = dataclasses.replace(
        honest.signed_header.header,
        app_hash=b"\x66" * 32,
        validators_hash=coalition.hash(),
    )
    bid = BlockID(
        hash=header.hash(),
        part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32),
    )
    sigs = []
    for i, v in enumerate(coalition.validators):
        vote = Vote(
            type=PRECOMMIT_TYPE,
            height=header.height,
            round=0,
            block_id=bid,
            timestamp_ns=header.time_ns,
            validator_address=v.address,
            validator_index=i,
        )
        vote.signature = by_addr[v.address].sign(
            vote.sign_bytes(chain_id)
        )
        sigs.append(
            CommitSig.for_block(
                vote.signature, v.address, vote.timestamp_ns
            )
        )
    commit = Commit(
        height=header.height, round=0, block_id=bid, signatures=sigs
    )
    return LightBlock(
        signed_header=SignedHeader(header=header, commit=commit),
        validator_set=coalition,
    )


async def _run_lightclient_fork(
    sc: ByzScenario, home: str, n_nodes: int, scenario_seed: int,
    rate: float,
) -> dict:
    trust_h = 2
    ln = await start_localnet(
        n_nodes,
        os.path.join(home, sc.name),
        chain_id=f"byz-{sc.name}",
        seed=scenario_seed,
    )
    traffic: Optional[asyncio.Future] = None
    pools: List[ClientPool] = []
    try:
        traffic, pools = _start_traffic(
            ln, scenario_seed, rate, sc.baseline_s + 10.0
        )
        base_ok = await _wait_heights_above(
            ln, min(_heights(ln)), timeout_s=20.0
        )
        # the fork target must be non-adjacent to the trust root (the
        # skipping path is what the coalition's 1/3+ power defeats)
        # and fully stored everywhere (commit for h lands with h+1)
        await _wait_heights_above(ln, trust_h + 3, timeout_s=30.0)
        target = min(_heights(ln)) - 1

        witness = LocalProvider(
            ln.nodes[1].block_store,
            ln.nodes[1].state_store,
            id_="honest-witness",
        )
        honest = await witness.light_block(target)
        forged = _forge_coalition_block(
            honest, ln.chain_id, scenario_seed
        )
        assert forged.signed_header.hash() != honest.signed_header.hash()
        primary = _LyingPrimary(
            ln.nodes[0].block_store, ln.nodes[0].state_store, forged
        )
        root = await witness.light_block(trust_h)
        client = Client(
            ln.chain_id,
            TrustOptions(
                period_ns=200 * _HOUR_NS,
                height=trust_h,
                hash=root.signed_header.hash(),
            ),
            primary,
            [witness],
            LightStore(MemKV()),
        )
        t0 = time.monotonic()
        detected = False
        attack_evidence = 0
        try:
            await client.verify_light_block_at_height(target)
        except DivergenceError as e:
            detected = True
            attack_evidence = len(e.evidence)
        detect_tte_s = time.monotonic() - t0
        reported = len(witness.reported_evidence) + len(
            primary.reported_evidence
        )

        safety = _safety_check(ln)
        stats, scheduled = await traffic
        traffic = None
        row = {
            "name": sc.name,
            "kind": sc.kind,
            "seed": scenario_seed,
            "trust_height": trust_h,
            "fork_height": target,
            "coalition_power": 20,
            "total_power": n_nodes * 10,
            "baseline_commit_ok": base_ok is not None,
            "divergence_detected": detected,
            "attack_evidence": attack_evidence,
            "evidence_reported_to_providers": reported,
            "detect_tte_s": round(detect_tte_s, 3),
            **safety,
            "requests_total": sum(st.count for st in stats.values()),
            "request_errors": sum(st.errors for st in stats.values()),
            "scheduled_arrivals": scheduled,
            "passed": bool(
                safety["safety_ok"]
                and base_ok is not None
                and detected
                and attack_evidence > 0
                and reported > 0
            ),
        }
        return row
    finally:
        if traffic is not None:
            traffic.cancel()
            await asyncio.gather(traffic, return_exceptions=True)
        for p in pools:
            await p.close()
        await ln.stop()


# ---------------------------------------------------------------------------
# double_sign_guard: crash between last-sign-state fsync and broadcast


async def _run_double_sign_guard(
    sc: ByzScenario, home: str, n_nodes: int, scenario_seed: int,
    rate: float,
) -> dict:
    victim = _VICTIM_IDX
    victim_addr = _victim_address(scenario_seed, victim)
    ln = await start_localnet(
        n_nodes,
        os.path.join(home, sc.name),
        chain_id=f"byz-{sc.name}",
        seed=scenario_seed,
        db_backend="sqlite",
    )
    traffic: Optional[asyncio.Future] = None
    pools: List[ClientPool] = []
    try:
        traffic, pools = _start_traffic(
            ln, scenario_seed, rate, sc.baseline_s + 15.0
        )
        base_ok = await _wait_heights_above(
            ln, min(_heights(ln)), timeout_s=20.0
        )
        await asyncio.sleep(sc.baseline_s)

        # crash the victim's NEXT signature release: last-sign state
        # is fsynced, the signature never leaves the privval — the
        # exact SIGKILL-between-fsync-and-broadcast instant
        fault_fired = False
        with faults.inject(
            "privval.release", "raise", times=1,
            key=f"load{victim}",
        ) as rule:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if rule.fired >= 1:
                    fault_fired = True
                    break
                await asyncio.sleep(0.05)
            crash_height = max(_heights(ln))
            # the process dies holding a persisted HRS whose vote was
            # never sent; the restart must honor that state
            await ln.restart(victim)

        ttfc = await _wait_heights_above(
            ln, crash_height, timeout_s=sc.recovery_slo_s * 2 + 5.0
        )
        recovered = ttfc is not None and ttfc <= sc.recovery_slo_s
        # let the net commit a few more heights: any conflicting
        # signature the restarted victim produced would surface as
        # committed evidence here
        await _wait_heights_above(
            ln, crash_height + 3, timeout_s=sc.recovery_slo_s
        )

        ev_rows = _committed_evidence(ln, victim_addr)
        safety = _safety_check(ln)
        stats, scheduled = await traffic
        traffic = None
        row = {
            "name": sc.name,
            "kind": sc.kind,
            "seed": scenario_seed,
            "victim": f"load{victim}",
            "fault_point": "privval.release",
            "fault_fired": fault_fired,
            "crash_height": crash_height,
            "ttfc_after_restart_s": (
                round(ttfc, 3) if ttfc is not None else None
            ),
            "recovered_within_slo": recovered,
            "victim_evidence_committed": len(ev_rows),
            **safety,
            "requests_total": sum(st.count for st in stats.values()),
            "request_errors": sum(st.errors for st in stats.values()),
            "scheduled_arrivals": scheduled,
            "passed": bool(
                safety["safety_ok"]
                and base_ok is not None
                and fault_fired
                and recovered
                and not ev_rows  # the double-sign guard held
            ),
        }
        return row
    finally:
        faults.reset()
        if traffic is not None:
            traffic.cancel()
            await asyncio.gather(traffic, return_exceptions=True)
        for p in pools:
            await p.close()
        await ln.stop()


async def run_byz_campaign(
    home: str,
    scenarios: Optional[Sequence[ByzScenario]] = None,
    n_nodes: int = 4,
    seed: int = 2026,
    rate: float = 50.0,
) -> dict:
    """Run the catalog; returns the BENCH_BYZ.json document."""
    scenarios = (
        list(scenarios)
        if scenarios is not None
        else shipped_byz_scenarios()
    )
    rows = []
    for sc in scenarios:
        rows.append(
            await run_byz_scenario(
                sc, home, n_nodes=n_nodes, seed=seed, rate=rate
            )
        )
    by_name = {r["name"]: r for r in rows}
    # the gateable summary: bench_compare's flatten() skips lists, so
    # the per-scenario accountability/detection latencies are lifted
    # into a dict block — every leaf ends `_s` (lower-is-better) and a
    # scenario vanishing from a fresh run is a missing row = gate fail
    summary = {
        "tte_evidence_commit_s": {
            name: r.get("tte_evidence_commit_s")
            for name, r in by_name.items()
            if r.get("evidence_slo_s") is not None
        },
        "lightclient_detect_tte_s": by_name.get(
            "lightclient_fork", {}
        ).get("detect_tte_s"),
        "double_sign_ttfc_after_restart_s": by_name.get(
            "double_sign_guard", {}
        ).get("ttfc_after_restart_s"),
        "evidence_committed_hits": sum(
            r.get("evidence_committed", 0) for r in rows
        ),
    }
    return {
        "schema": "bench_byz/v1",
        "seed": seed,
        "nodes": n_nodes,
        "offered_rate_per_s": rate,
        "scenarios": rows,
        "summary": summary,
        "all_passed": all(r["passed"] for r in rows),
    }
