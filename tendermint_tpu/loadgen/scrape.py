"""Mid-run registry scrape loop.

While the drivers apply load, the scraper snapshots every node's
metrics registry (node._render_metrics(), the same document /metrics
serves) on a fixed interval, parses it, and keeps a bounded ring of
samples. The client-side sketches say how slow requests WERE; the
scrape series say WHY — mempool depth, eventbus fanout lag, websocket
queue depth, in-flight request counts — the saturation signals the
ROADMAP's follow-on work (async RPC, sharded CheckTx, fanout batching)
will be judged against.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Dict, List, Sequence

__all__ = ["Scraper", "parse_exposition"]


def parse_exposition(text: str) -> Dict[str, float]:
    """Prometheus text-format (0.0.4) → {series-with-sorted-labels:
    value}. Strict on data lines: a malformed scrape should fail the
    harness loudly, not silently drop the saturation signal."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        if not metric:
            raise ValueError(f"unparseable exposition line: {line!r}")
        if "{" in metric:
            name, _, rest = metric.partition("{")
            pairs = []
            for pair in rest.rstrip("}").split(","):
                k, _, v = pair.partition("=")
                pairs.append((k, v.strip('"')))
            key = (
                name
                + "{"
                + ",".join(f"{k}={v}" for k, v in sorted(pairs))
                + "}"
            )
        else:
            key = metric
        out[key] = (
            float("inf") if value == "+Inf" else float(value)
        )
    return out


_NS = "tendermint_tpu_"

# gauges tracked as run maxima (saturation peaks), by series prefix —
# label-bearing series (rpc_inflight_requests{route=...}) are summed
# per sample before the max
_MAX_GAUGES = (
    "mempool_size",
    "eventbus_fanout_lag",
    "eventbus_subscriptions",
    "rpc_ws_connections",
    "rpc_inflight_requests",
    # byzantine campaigns: peak verified-but-uncommitted evidence —
    # a sustained high-water mark means inclusion lags detection
    "evidence_pool_size",
)

# sketch p99s tracked as run maxima (worst window across nodes).
# lock_wait vs checktx is the mempool contention split: p99s moving
# together means CheckTx is lock-bound (consensus holds the pool
# across Commit+Update), lock_wait ≈ 0 means it is validation-bound.
_P99_SKETCHES = (
    "mempool_checktx_seconds",
    "mempool_lock_wait_seconds",
    # the other half of the consensus hold: per-block recheck of the
    # surviving pool under the epoch barrier — a climbing p99 here
    # with flat checktx means commit latency is pool-depth-bound
    "mempool_recheck_seconds",
)

# counters reported as whole-run deltas (first vs last sample)
_DELTA_COUNTERS = (
    "consensus_total_txs",
    "eventbus_deliveries_total",
    "eventbus_dropped_subscriptions_total",
    "rpc_ws_slow_clients_dropped_total",
    "mempool_failed_txs_total",
    # silent exits that eat offered load before it reaches a proposal
    # (labeled reason=expired|full children fold)
    "mempool_evicted_total",
    # the chaos plane's lifecycle signals (labeled children fold)
    "p2p_peer_disconnects_total",
    "p2p_send_queue_dropped_total",
    "p2p_net_faults_total",
    # the evidence lifecycle's terminal states (byzantine campaigns):
    # committed = accountability achieved, expired = accountability
    # window missed — a nonzero expired delta fails the verdict
    "evidence_committed_total",
    "evidence_expired_total",
)


class Scraper:
    """Samples every node's registry on `interval_s` until stopped."""

    def __init__(
        self,
        nodes: Sequence[object],
        interval_s: float = 0.5,
        keep: int = 256,
    ) -> None:
        self._nodes = list(nodes)
        self._interval = interval_s
        # tmlive: bounded= ring (deque maxlen=keep)
        self._samples: deque = deque(maxlen=keep)
        self.scrapes = 0

    def sample_once(self) -> List[Dict[str, float]]:
        """One parsed snapshot per node; also appended to the ring."""
        snap = [
            parse_exposition(n._render_metrics()) for n in self._nodes
        ]
        self._samples.append(snap)
        self.scrapes += 1
        return snap

    async def run(self, stop: asyncio.Event) -> None:
        while not stop.is_set():
            self.sample_once()
            try:
                await asyncio.wait_for(stop.wait(), self._interval)
            except asyncio.TimeoutError:
                pass
        self.sample_once()  # closing sample: the run's final state

    # -- aggregation --

    @staticmethod
    def _series_sum(parsed: Dict[str, float], name: str) -> float:
        """Sum of every series for `name` (labeled children fold)."""
        full = _NS + name
        total = 0.0
        seen = False
        for k, v in parsed.items():
            if k == full or k.startswith(full + "{"):
                total += v
                seen = True
        return total if seen else 0.0

    def saturation(self) -> Dict[str, float]:
        """Run maxima of the saturation gauges (summed across each
        node per sample, max over samples) plus whole-run counter
        deltas — the scrape-derived half of the BENCH_LOAD row."""
        out: Dict[str, float] = {}
        samples = list(self._samples)
        if not samples:
            return out
        for name in _MAX_GAUGES:
            out[name + "_max"] = max(
                sum(self._series_sum(p, name) for p in snap)
                for snap in samples
            )
        for name in _P99_SKETCHES:
            key = _NS + name + "{quantile=0.99}"
            out[name + "_p99_max"] = max(
                max((p.get(key, 0.0) for p in snap), default=0.0)
                for snap in samples
            )
        first, last = samples[0], samples[-1]
        for name in _DELTA_COUNTERS:
            # max across nodes: counters like consensus_total_txs move
            # together on a healthy net; max tolerates a lagging node
            out[name + "_delta"] = max(
                self._series_sum(lp, name) - self._series_sum(fp, name)
                for fp, lp in zip(first, last)
            )
        out["scrapes"] = float(self.scrapes)
        return out
