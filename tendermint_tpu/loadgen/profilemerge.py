"""The bottleneck ledger: merge the profiling plane into the tmload row.

Three planes each hold one third of the "why is it slow" story:

  * the **profiler** (libs/profiler.py) knows which *code* held the
    wall — per-subsystem sample shares and the hot folded stacks;
  * the **scraper** (loadgen/scrape.py) knows which *queues* were
    saturated — fanout lag, mempool depth, inflight requests;
  * the **flight recorder** (loadgen/timeline.py) knows whether the
    *consensus* half (proposal→polka→quorum→commit) or the *serving*
    half was the slow one.

`build_ledger` joins them on the subsystem name into one ranked table
— "where the next 10x is hiding" — that build_report banks into
BENCH_LOAD.json, so every future throughput PR states its attribution
shift with `scripts/bench_compare.py --ledger`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..libs import profiler

__all__ = [
    "NON_WORK_BUCKETS",
    "SERVING_BUCKETS",
    "UNATTRIBUTED_BUCKETS",
    "build_ledger",
    "capture_profile",
]

# buckets that are wall time but not *work* — reported as their own
# ledger fields, excluded from the ranked work table
NON_WORK_BUCKETS = frozenset(("idle", "wait"))

# buckets with no named subsystem home: the ledger's honesty meter
# (the acceptance bar keeps their joint share under 10%)
UNATTRIBUTED_BUCKETS = frozenset(("stdlib",))

# the serving half of the consensus-vs-serving split; everything else
# that is work belongs to the consensus/replication half
SERVING_BUCKETS = frozenset(("rpc", "eventbus", "serialization"))

# which scraper saturation keys corroborate which subsystem's share —
# a hot bucket WITH a saturated queue is a bottleneck, a hot bucket
# without one is merely busy
_SUBSYSTEM_SIGNALS: Dict[str, tuple] = {
    "mempool": (
        "mempool_size_max",
        "mempool_failed_txs_total_delta",
        "mempool_checktx_seconds_p99_max",
        "mempool_lock_wait_seconds_p99_max",
        "mempool_recheck_seconds_p99_max",
        "mempool_evicted_total_delta",
    ),
    "eventbus": (
        "eventbus_fanout_lag_max",
        "eventbus_subscriptions_max",
        "eventbus_deliveries_total_delta",
        "eventbus_dropped_subscriptions_total_delta",
    ),
    "rpc": (
        "rpc_inflight_requests_max",
        "rpc_ws_connections_max",
        "rpc_ws_slow_clients_dropped_total_delta",
    ),
    "p2p": (
        "p2p_peer_disconnects_total_delta",
        "p2p_send_queue_dropped_total_delta",
        "p2p_net_faults_total_delta",
    ),
    "consensus": ("consensus_total_txs_delta",),
}

_TOP_STACKS_KEPT = 40  # per banked profile block: the hot tail only


def capture_profile(
    counts_before: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """Snapshot the in-process profiler for the report's `profile`
    block: stats, per-subsystem counts (whole run AND the measured
    window when `counts_before` — a `profiler.subsystem_counts()`
    reading taken at window start — is given), and the top stacks."""
    counts = profiler.subsystem_counts()
    doc: Dict[str, Any] = {
        "stats": profiler.stats(),
        "subsystem_counts": counts,
        "subsystem_shares": profiler.subsystem_shares(),
        "stacks": profiler.snapshot(_TOP_STACKS_KEPT),
    }
    if counts_before is not None:
        window = {
            k: counts.get(k, 0) - counts_before.get(k, 0)
            for k in set(counts) | set(counts_before)
        }
        doc["window_counts"] = {
            k: v for k, v in sorted(window.items()) if v > 0
        }
    return doc


def _shares_of(counts: Dict[str, int]) -> Dict[str, float]:
    total = sum(counts.values())
    if total <= 0:
        return {}
    return {k: v / total for k, v in counts.items()}


def build_ledger(
    profile: Dict[str, Any],
    saturation: Optional[Dict[str, float]] = None,
    timeline: Optional[dict] = None,
) -> Dict[str, Any]:
    """The ranked bottleneck table. Uses the measured-window counts
    when the profile has them (warmup excluded), else the whole run."""
    counts: Dict[str, int] = dict(
        profile.get("window_counts")
        or profile.get("subsystem_counts")
        or {}
    )
    shares = _shares_of(counts)
    total = sum(counts.values())
    sat = saturation or {}

    idle = sum(shares.get(b, 0.0) for b in NON_WORK_BUCKETS)
    unattributed = sum(shares.get(b, 0.0) for b in UNATTRIBUTED_BUCKETS)
    work = {
        k: v
        for k, v in shares.items()
        if k not in NON_WORK_BUCKETS and k not in UNATTRIBUTED_BUCKETS
    }
    work_total = sum(work.values())

    entries = []
    for rank, (name, share) in enumerate(
        sorted(work.items(), key=lambda kv: (-kv[1], kv[0])), start=1
    ):
        signals = {
            key: sat[key]
            for key in _SUBSYSTEM_SIGNALS.get(name, ())
            if key in sat
        }
        entries.append(
            {
                "rank": rank,
                "subsystem": name,
                "share": round(share, 4),
                "work_share": (
                    round(share / work_total, 4) if work_total else 0.0
                ),
                "samples": counts.get(name, 0),
                "signals": signals,
            }
        )

    serving = sum(work.get(b, 0.0) for b in SERVING_BUCKETS)
    split: Dict[str, Any] = {
        "serving_share": round(serving, 4),
        "consensus_share": round(work_total - serving, 4),
    }
    if timeline is not None:
        # the flight recorder's stage attribution rides along so the
        # split is cross-checkable against consensus-internal timings
        split["timeline"] = {
            "heights_attributed": timeline.get("heights_attributed"),
            "rounds_burned_total": timeline.get("rounds_burned_total"),
            "timeouts_total": timeline.get("timeouts_total"),
            "proposal_to_polka": timeline.get("proposal_to_polka"),
            "polka_to_quorum": timeline.get("polka_to_quorum"),
            "commit_spread": timeline.get("commit_spread"),
        }

    return {
        "samples_total": total,
        "attributed_share": round(1.0 - unattributed, 4),
        "unattributed_share": round(unattributed, 4),
        "idle_share": round(idle, 4),
        "entries": entries,
        "consensus_vs_serving": split,
    }
