"""tmload — production load harness + per-route SLO measurement.

The serving path is statically proven stall-free and bounded (tmlive,
docs/static_analysis.md) and batch is the API (PR 11) — this package
measures what those guarantees buy under production-shaped traffic:
sustained txs/s, per-route p50/p99/p999 from the mergeable latency
sketch (libs/metrics.py LatencySketch), error/timeout counts, and how
many concurrent websocket subscribers a node holds, against a live
multi-node localnet. docs/load.md is the operator manual (scenario
spec, open- vs closed-loop semantics, coordinated-omission rationale,
SLO/exemplar policy); bench.py's `load_smoke` row emits the
BENCH_LOAD.json trajectory.

Layout:
    scenario.py  the declarative workload spec (rate, mix, duration,
                 ramp, subscriber count) — one seed reproduces one run
    localnet.py  in-process multi-validator net with live RPC listeners
    chaos.py     the chaos campaign runner (ISSUE 13): staged seeded
                 network-fault scenarios (partitions, asymmetric loss,
                 latency, crash-restarts, churn) under open-loop
                 traffic, with machine-checked safety + recovery
                 verdicts — BENCH_CHAOS.json is its trajectory
    byz.py       the byzantine campaign runner (ISSUE 18): seeded
                 misbehavior (equivocation, conflicting proposals,
                 amnesia, withholding), the ≥1/3 light-client fork
                 control, and the crash-window double-sign guard —
                 safety/accountability/detection verdicts banked as
                 BENCH_BYZ.json
    driver.py    open-loop (fixed/Poisson arrival, latency from the
                 *intended* send time) and closed-loop drivers, the
                 HTTP client pool, and the websocket subscriber pool
    scrape.py    mid-run registry snapshots from every node (mempool /
                 eventbus / inflight saturation)
    timeline.py  fleet flight-recorder merger (ISSUE 15): per-height
                 phase attribution + chaos TTFC recovery decomposition
                 from the per-node consensus timelines
    report.py    merge the per-worker sketches into the BENCH_LOAD row
    run.py       orchestration: run_scenario / run_localnet_scenario
"""

from .byz import (  # noqa: F401
    ByzScenario,
    run_byz_campaign,
    run_byz_scenario,
    shipped_byz_scenarios,
)
from .chaos import (  # noqa: F401
    ChaosScenario,
    run_campaign,
    run_chaos_scenario,
    shipped_scenarios,
)
from .driver import ClientPool, RouteStats, SubscriberPool  # noqa: F401
from .localnet import Localnet, start_localnet  # noqa: F401
from .report import build_report  # noqa: F401
from .run import run_localnet_scenario, run_scenario  # noqa: F401
from .scenario import OPS, Scenario  # noqa: F401
from .scrape import Scraper  # noqa: F401
from .timeline import (  # noqa: F401
    attribute_heights,
    collect,
    decompose_recovery,
    fleet_summary,
)

__all__ = [
    "OPS",
    "ByzScenario",
    "ChaosScenario",
    "ClientPool",
    "Localnet",
    "RouteStats",
    "Scenario",
    "Scraper",
    "SubscriberPool",
    "attribute_heights",
    "build_report",
    "collect",
    "decompose_recovery",
    "fleet_summary",
    "run_byz_campaign",
    "run_byz_scenario",
    "run_campaign",
    "run_chaos_scenario",
    "run_localnet_scenario",
    "run_scenario",
    "shipped_byz_scenarios",
    "shipped_scenarios",
    "start_localnet",
]
