"""Open/closed-loop traffic drivers, the client pool, the subscriber
pool, and the client-observed per-route statistics.

Latency accounting is the load-bearing design point (docs/load.md):

* closed-loop: each worker times its own request — `done - sent`.
* open-loop: requests arrive on a seeded schedule and latency is
  `done - INTENDED` arrival time. A server that stalls for a second
  does not pause the schedule; the requests that should have been sent
  during the stall are still issued and each carries the queueing
  delay it actually suffered. Measuring from the actual (delayed) send
  time instead — the coordinated-omission mistake — would report a
  stalled server as fast because the victim requests were never timed.

Every per-route observation lands in a mergeable LatencySketch
(libs/metrics.py): workers keep private sketches (no contended lock on
the hot path) and the report merges them, which is exactly the
cross-process shape a fleet-scale harness needs.
"""

from __future__ import annotations

import asyncio
import base64
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..libs import rng as tmrng
from ..libs.metrics import LatencySketch
from ..rpc.client import HTTPClient, RPCClientError, WSClient
from .scenario import Scenario

__all__ = [
    "ClientPool",
    "RouteStats",
    "SubscriberPool",
    "run_closed_loop",
    "run_open_loop",
]


class RouteStats:
    """Client-observed outcome of one route: latency sketch + result
    counters. Mergeable, like its sketch."""

    __slots__ = ("sketch", "ok", "errors", "timeouts")

    def __init__(self, relative_error: float = 0.01) -> None:
        self.sketch = LatencySketch(relative_error=relative_error)
        self.ok = 0
        self.errors = 0
        self.timeouts = 0

    def record(self, latency_s: float, outcome: str) -> None:
        self.sketch.record(latency_s)
        if outcome == "ok":
            self.ok += 1
        elif outcome == "timeout":
            self.timeouts += 1
        else:
            self.errors += 1

    def merge(self, other: "RouteStats") -> "RouteStats":
        self.sketch.merge(other.sketch)
        self.ok += other.ok
        self.errors += other.errors
        self.timeouts += other.timeouts
        return self

    @property
    def count(self) -> int:
        return self.ok + self.errors + self.timeouts

    def to_dict(self) -> dict:
        ms = 1e3
        return {
            "count": self.count,
            "ok": self.ok,
            "errors": self.errors,
            "timeouts": self.timeouts,
            "p50_ms": self.sketch.quantile(0.5) * ms,
            "p99_ms": self.sketch.quantile(0.99) * ms,
            "p999_ms": self.sketch.quantile(0.999) * ms,
            "max_ms": self.sketch.max * ms,
        }


def merge_route_stats(
    parts: Sequence[Dict[str, RouteStats]],
) -> Dict[str, RouteStats]:
    """Fold per-worker stat maps into one per-route map."""
    out: Dict[str, RouteStats] = {}
    for part in parts:
        for route, st in part.items():
            if route in out:
                out[route].merge(st)
            else:
                out[route] = st
    return out


class ClientPool:
    """N keep-alive HTTP connections to one node behind a free-list.

    HTTPClient serializes calls on its single connection; the pool is
    what turns `max_inflight` client-side concurrency into real
    parallel requests. Waiting for a free connection counts into the
    caller's latency window — for open-loop traffic that wait IS
    queueing delay and must be measured, not hidden."""

    def __init__(
        self, addr: str, size: int, timeout_s: float = 10.0
    ) -> None:
        self.addr = addr
        self._clients = [
            HTTPClient(addr, timeout=timeout_s) for _ in range(size)
        ]
        self._free: asyncio.Queue = asyncio.Queue()
        for c in self._clients:
            self._free.put_nowait(c)

    async def call(self, method: str, **params):
        c = await self._free.get()
        try:
            return await c.call(method, **params)
        finally:
            self._free.put_nowait(c)

    async def close(self) -> None:
        for c in self._clients:
            await c.close()


class _Workload:
    """Executes one op of the mix against a pool, with seeded payloads.

    Tx keys are unique per (seed, stream, sequence) so the mempool's
    dedup cache never silently absorbs the flood; queries read back
    keys the same run already wrote (a read mix that always misses
    measures the error path, not serving)."""

    def __init__(
        self, scn: Scenario, pools: Sequence[ClientPool], stream: int
    ) -> None:
        self._pools = pools
        self._stream = stream
        self._seq = 0
        self._rng = tmrng.derive(scn.seed, f"payload-{stream}")
        self._value = b"v" * max(1, scn.tx_value_bytes)
        self._last_key: Optional[bytes] = None
        self._pick = 0
        self._seed = scn.seed

    def _pool(self) -> ClientPool:
        # round-robin across nodes: every node serves its share
        self._pick += 1
        return self._pools[self._pick % len(self._pools)]

    def _next_key(self) -> bytes:
        self._seq += 1
        return b"ld-%d-%d-%d" % (self._seed, self._stream, self._seq)

    def _tx_b64(self) -> str:
        key = self._next_key()
        self._last_key = key
        return base64.b64encode(key + b"=" + self._value).decode()

    async def do(self, op: str):
        pool = self._pool()
        if op == "broadcast_tx_sync":
            return await pool.call("broadcast_tx_sync", tx=self._tx_b64())
        if op == "broadcast_tx_async":
            return await pool.call("broadcast_tx_async", tx=self._tx_b64())
        if op == "abci_query":
            key = self._last_key or b"ld-none"
            return await pool.call("abci_query", data=key.hex())
        if op == "block":
            return await pool.call("block")  # latest
        if op == "light_blocks":
            return await pool.call("light_blocks", max_blocks=10)
        if op == "tx_proofs":
            # latest block, empty index list: exercises the held
            # merkle-tree build + cache (the stateless serving cost)
            # without depending on how many txs the block carries
            return await pool.call("tx_proofs", indices=[])
        if op == "status":
            return await pool.call("status")
        raise ValueError(f"unknown op {op!r}")


def _pick_op(scn: Scenario, r) -> Callable[[], str]:
    ops = [op for op, _ in scn.mix]
    weights = [w for _, w in scn.mix]
    total = sum(weights)
    cum = []
    acc = 0.0
    for w in weights:
        acc += w
        cum.append(acc / total)

    def pick() -> str:
        x = r.random()
        for op, edge in zip(ops, cum):
            if x <= edge:
                return op
        return ops[-1]

    return pick


async def _timed_op(
    work: _Workload,
    op: str,
    stats: Dict[str, RouteStats],
    t_intended: float,
    sem: Optional[asyncio.Semaphore] = None,
) -> None:
    """One measured request. `t_intended` is the perf_counter instant
    the request was SCHEDULED to leave; open-loop passes the schedule
    slot, closed-loop passes now. Semaphore wait (connection budget)
    happens inside the window by design."""
    outcome = "ok"
    try:
        if sem is not None:
            async with sem:
                await work.do(op)
        else:
            await work.do(op)
    except asyncio.TimeoutError:
        outcome = "timeout"
    except (RPCClientError, ConnectionError, OSError):
        outcome = "error"
    st = stats.get(op)
    if st is None:
        st = stats[op] = RouteStats()
    st.record(time.perf_counter() - t_intended, outcome)


async def run_closed_loop(
    scn: Scenario,
    pools: Sequence[ClientPool],
    stop: asyncio.Event,
    stream_base: int = 0,
) -> Dict[str, RouteStats]:
    """`concurrency` workers issuing back-to-back requests until
    `stop`. Returns the merged per-route stats. `stream_base` keeps
    concurrent phases (warmup vs measurement) on disjoint tx-key
    streams — overlapping streams replay keys into the mempool dedup
    cache and the "load" measures rejections."""

    async def worker(i: int) -> Dict[str, RouteStats]:
        stats: Dict[str, RouteStats] = {}
        work = _Workload(scn, pools, stream=stream_base + i)
        pick = _pick_op(scn, tmrng.derive(scn.seed, f"mix-{i}"))
        while not stop.is_set():
            await _timed_op(work, pick(), stats, time.perf_counter())
        return stats

    parts = await asyncio.gather(
        *(worker(i) for i in range(scn.concurrency))
    )
    return merge_route_stats(parts)


def arrival_offsets(scn: Scenario) -> List[float]:
    """The seeded open-loop schedule: request offsets (seconds from
    run start) over `duration_s`. Poisson draws exponential gaps at
    the instantaneous rate; "fixed" spaces them evenly. A linear ramp
    scales the rate from ~0 to `rate` over `ramp_s`."""
    r = tmrng.derive(scn.seed, "arrivals")
    offsets: List[float] = []
    t = 0.0
    while True:
        frac = 1.0 if scn.ramp_s <= 0 else min(1.0, t / scn.ramp_s)
        # the ramp floors at 10% of the target rate: a floor near zero
        # makes the FIRST gap huge (mean 1/rate(0)) and the schedule
        # starts with a dead window instead of a ramp
        inst_rate = max(scn.rate * frac, scn.rate * 0.1)
        if scn.arrival == "poisson":
            t += r.expovariate(inst_rate)
        else:
            t += 1.0 / inst_rate
        if t >= scn.duration_s:
            return offsets
        offsets.append(t)


async def run_open_loop(
    scn: Scenario,
    pools: Sequence[ClientPool],
) -> Tuple[Dict[str, RouteStats], int]:
    """Issue the seeded arrival schedule; every request is timed from
    its intended arrival instant. Returns (per-route stats, number of
    scheduled arrivals). The dispatcher never blocks on the server:
    when the connection budget is exhausted, requests queue inside
    their own measurement window."""
    stats: Dict[str, RouteStats] = {}
    work = _Workload(scn, pools, stream=0)
    pick = _pick_op(scn, tmrng.derive(scn.seed, "mix"))
    sem = asyncio.Semaphore(scn.max_inflight)
    offsets = arrival_offsets(scn)
    t0 = time.perf_counter()
    pending: set = set()
    for off in offsets:
        delay = (t0 + off) - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        task = asyncio.ensure_future(
            _timed_op(work, pick(), stats, t0 + off, sem=sem)
        )
        pending.add(task)
        task.add_done_callback(pending.discard)
    if pending:
        await asyncio.gather(*pending, return_exceptions=True)
    return stats, len(offsets)


class SubscriberPool:
    """N concurrent websocket subscribers held for the run.

    Each subscriber is a real WSClient on its own TCP connection,
    subscribed to `scn.subscribe_query`, draining pushed events. The
    node-side saturation signals (`rpc_ws_send_queue_depth`,
    `eventbus_fanout_lag`) are what the scrape loop reads while these
    hold their connections; the pool itself reports how many
    subscribers connected, how many survived the run, and how many
    events they drained."""

    def __init__(self, scn: Scenario, addrs: Sequence[str]) -> None:
        self._scn = scn
        self._addrs = list(addrs)
        # tmlive: bounded= at most scn.subscribers entries (start()'s
        # loop bound); drained and cleared by stop()
        self._clients: List[WSClient] = []
        # tmlive: bounded= one drain task per connected subscriber
        self._drains: List[asyncio.Task] = []
        self.connected = 0
        self.events = 0

    async def start(self) -> None:
        for i in range(self._scn.subscribers):
            ws = WSClient(
                self._addrs[i % len(self._addrs)],
                timeout=self._scn.timeout_s,
            )
            try:
                await ws.connect()
                await ws.call(
                    "subscribe", query=self._scn.subscribe_query
                )
            except (
                RPCClientError,
                ConnectionError,
                OSError,
                asyncio.TimeoutError,
            ):
                await ws.close()
                continue
            self._clients.append(ws)
            self.connected += 1
            self._drains.append(
                asyncio.ensure_future(self._drain(ws))
            )

    async def _drain(self, ws: WSClient) -> None:
        try:
            while True:
                await ws.next_event(timeout=60.0)
                self.events += 1
        except (
            RPCClientError,
            ConnectionError,
            asyncio.TimeoutError,
            asyncio.CancelledError,
        ):
            pass

    def held(self) -> int:
        """Subscribers still draining (not dead) right now."""
        return sum(1 for t in self._drains if not t.done())

    async def stop(self) -> Tuple[int, int]:
        held = self.held()
        for t in self._drains:
            t.cancel()
        if self._drains:
            await asyncio.gather(*self._drains, return_exceptions=True)
        for ws in self._clients:
            await ws.close()
        return held, self.events
