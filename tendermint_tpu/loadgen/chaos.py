"""Chaos campaign runner — staged network faults under production load,
with machine-checked safety and recovery verdicts.

ISSUE 13: PR 12 built the traffic gun; this module points it at a net
that is actively being partitioned, delayed and crash-restarted. Each
`ChaosScenario` boots a FRESH in-process localnet (loadgen/localnet.py
— real RPC listeners, per-node registries), drives seeded open-loop
traffic at it for the whole run, and walks one fault arc:

    baseline → arm faults → hold → heal → measure recovery

and then renders two verdicts, both machine-checked:

* **safety** — at every height the nodes have in common, the stored
  block-ID hashes are byte-identical across ALL nodes (read straight
  from each node's block store, not over RPC). ANY divergence fails
  the scenario: "tolerates up to 1/3 Byzantine voting power" means the
  chain may stall under a partition, but two correct nodes must never
  commit different blocks at the same height.
* **recovery** — after the heal instant, the SLOWEST node commits a
  block past the heal-time network height within the scenario's SLO;
  the time-to-first-commit-after-heal is recorded either way.

Reproducibility rides the PR-3 fault-plane contract: every per-message
rule owns a `random.Random(seed)` derived from the campaign seed
(`crypto/faults.py` — whether consult k fires is a pure function of
(seed, k)), partitions are deterministic set specs, and the traffic
arrival schedule is the seeded tmload open-loop schedule. Re-running a
scenario with the same seed re-arms the identical fault schedule.

bench.py's `chaos_smoke` row runs the shipped catalog in the banked
jax-free CPU block and persists the full trajectory as
BENCH_CHAOS.json. docs/resilience.md documents the scenario catalog
and the SLO policy.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..crypto import faults
from ..libs.rng import subseed as _subseed
from . import timeline as fleet_timeline
from .driver import ClientPool, run_open_loop
from .localnet import Localnet, start_localnet
from .scenario import Scenario
from .scrape import parse_exposition

__all__ = [
    "ChaosScenario",
    "run_campaign",
    "run_chaos_scenario",
    "shipped_scenarios",
]


@dataclass
class ChaosScenario:
    """One staged fault arc. `kind` picks the arm/heal machinery:

    partition  spec={"isolate": [node indexes]} — the named minority
               (or half) is cut from the rest via TM_TPU_PARTITION-
               style sets for `fault_s`, then healed
    rules      spec={"rules": [ {point, mode, p, src, dst, ch,
               delay_s, dup} ]} — seeded per-message rules (asymmetric
               loss, latency) armed for `fault_s`; src/dst name node
               monikers (load0, load1, ...)
    crash      spec={"victims": [indexes], "gap_s": s} — rolling
               crash-restarts (sqlite stores survive, like a SIGKILL'd
               process); heal = the last victim back up
    flap       spec={"victims": [indexes], "hold_s": s} — churn: each
               victim is isolated for hold_s then healed, in turn
    """

    name: str
    kind: str
    fault_s: float = 3.0
    recovery_slo_s: float = 15.0
    baseline_s: float = 2.0
    spec: dict = field(default_factory=dict)

    def db_backend(self) -> str:
        # crash-restarts need stores that survive the node instance
        return "sqlite" if self.kind == "crash" else "memdb"


def shipped_scenarios() -> List[ChaosScenario]:
    """The shipped catalog (4-node nets; docs/resilience.md): minority
    and majority partitions with heal, asymmetric link loss on the
    vote channel, high-latency links, rolling crash-restarts, and
    partition churn."""
    vote_ch = 0x22  # consensus VOTE_CHANNEL
    return [
        ChaosScenario(
            name="minority_partition",
            kind="partition",
            spec={"isolate": [3]},
            fault_s=3.0,
            recovery_slo_s=15.0,
        ),
        ChaosScenario(
            name="majority_partition",
            kind="partition",
            # 2|2: NEITHER side holds 2/3 — the whole chain must stall
            # (safety) and resume after heal (recovery)
            spec={"isolate": [0, 1]},
            fault_s=3.0,
            recovery_slo_s=20.0,
        ),
        ChaosScenario(
            name="asym_link_loss",
            kind="rules",
            spec={
                "rules": [
                    # one DIRECTION of one link loses 60% of votes —
                    # the asymmetric case a symmetric partition model
                    # cannot express
                    {
                        "point": "p2p.send",
                        "mode": "drop",
                        "p": 0.6,
                        "src": "load0",
                        "dst": "load1",
                        "ch": vote_ch,
                    },
                    {
                        "point": "p2p.recv",
                        "mode": "drop",
                        "p": 0.4,
                        "src": "load2",
                        "dst": "load3",
                    },
                ]
            },
            fault_s=4.0,
            recovery_slo_s=15.0,
        ),
        ChaosScenario(
            name="high_latency",
            kind="rules",
            spec={
                "rules": [
                    {
                        "point": "p2p.send",
                        "mode": "delay",
                        "p": 0.5,
                        "delay_s": 0.05,
                    },
                    {
                        "point": "p2p.recv",
                        "mode": "delay",
                        "p": 0.3,
                        "delay_s": 0.05,
                    },
                    # gossip echo + adjacent swaps ride along
                    {"point": "p2p.recv", "mode": "duplicate", "p": 0.2},
                    {"point": "p2p.send", "mode": "reorder", "p": 0.2},
                ]
            },
            fault_s=4.0,
            recovery_slo_s=15.0,
        ),
        ChaosScenario(
            name="rolling_crash",
            kind="crash",
            spec={"victims": [1, 2], "gap_s": 1.0},
            fault_s=0.0,  # the restarts ARE the fault stage
            recovery_slo_s=30.0,
        ),
        ChaosScenario(
            name="churn",
            kind="flap",
            spec={"victims": [1, 2, 3], "hold_s": 0.8},
            fault_s=0.0,  # the flap loop is the fault stage
            recovery_slo_s=15.0,
        ),
    ]


def _partition_spec(ln: Localnet, isolate: Sequence[int]) -> str:
    monikers = ln.monikers()
    a = [monikers[i] for i in isolate]
    b = [m for i, m in enumerate(monikers) if i not in set(isolate)]
    return ",".join(a) + "|" + ",".join(b)


def _heights(ln: Localnet) -> List[int]:
    return [n.block_store.height() for n in ln.nodes]


async def _wait_heights_above(
    ln: Localnet, floor: int, timeout_s: float
) -> Optional[float]:
    """Poll until EVERY node's stored height exceeds `floor`; returns
    the wall seconds it took, or None on timeout."""
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    while time.monotonic() < deadline:
        if min(_heights(ln)) > floor:
            return time.monotonic() - t0
        await asyncio.sleep(0.1)
    return None


def _safety_check(ln: Localnet) -> Dict:
    """Byte-identical stored block-ID hashes at every common height.
    Divergence = a fork between correct nodes = hard fail."""
    heights = _heights(ln)
    common = min(heights)
    divergences: List[Dict] = []
    for h in range(1, common + 1):
        hashes = []
        for n in ln.nodes:
            meta = n.block_store.load_block_meta(h)
            hashes.append(meta.block_id.hash if meta is not None else None)
        ref = hashes[0]
        if any(x != ref for x in hashes[1:]):
            divergences.append(
                {
                    "height": h,
                    "hashes": [
                        x.hex() if x is not None else None for x in hashes
                    ],
                }
            )
    return {
        "safety_ok": not divergences and common >= 1,
        "heights_checked": common,
        "node_heights": heights,
        "divergences": divergences,
    }


def _p2p_counters(ln: Localnet, prefix: str) -> Dict[str, float]:
    """Sum a labeled p2p counter family across nodes, keyed by its
    label suffix — the lifecycle evidence in each scenario row."""
    out: Dict[str, float] = {}
    for n in ln.nodes:
        parsed = parse_exposition(n._render_metrics())
        for k, v in parsed.items():
            if k.startswith(prefix):
                label = k[len(prefix):].strip("{}")
                out[label] = out.get(label, 0.0) + v
    return out


async def _arm_and_heal(cs: ChaosScenario, ln: Localnet, seed: int):
    """Run the scenario's fault stage; returns when the net is healed.
    (The caller stamps the heal instant immediately after.)"""
    if cs.kind == "partition":
        faults.set_partition(_partition_spec(ln, cs.spec["isolate"]))
        try:
            await asyncio.sleep(cs.fault_s)
        finally:
            faults.set_partition("")
    elif cs.kind == "rules":
        with contextlib.ExitStack() as stack:
            for i, r in enumerate(cs.spec["rules"]):
                stack.enter_context(
                    faults.inject(
                        r["point"],
                        r["mode"],
                        p=r.get("p", 1.0),
                        seed=_subseed(seed, f"{cs.name}-rule{i}"),
                        src=r.get("src"),
                        dst=r.get("dst"),
                        ch=r.get("ch"),
                        delay_s=r.get("delay_s", 0.05),
                        dup=r.get("dup", 1),
                    )
                )
            await asyncio.sleep(cs.fault_s)
    elif cs.kind == "crash":
        for idx in cs.spec["victims"]:
            await ln.restart(idx)
            await asyncio.sleep(cs.spec.get("gap_s", 1.0))
    elif cs.kind == "flap":
        for idx in cs.spec["victims"]:
            faults.set_partition(_partition_spec(ln, [idx]))
            try:
                await asyncio.sleep(cs.spec.get("hold_s", 0.8))
            finally:
                faults.set_partition("")
            await asyncio.sleep(0.3)
    else:
        raise ValueError(f"unknown chaos kind {cs.kind!r}")


async def run_chaos_scenario(
    cs: ChaosScenario,
    home: str,
    n_nodes: int = 4,
    seed: int = 2026,
    rate: float = 50.0,
) -> dict:
    """Boot a fresh localnet, run the scenario arc under open-loop
    traffic, tear down, return the verdict row."""
    scenario_seed = _subseed(seed, cs.name)
    ln = await start_localnet(
        n_nodes,
        os.path.join(home, cs.name),
        chain_id=f"chaos-{cs.name}",
        seed=scenario_seed,
        db_backend=cs.db_backend(),
    )
    traffic: Optional[asyncio.Future] = None
    pools: List[ClientPool] = []
    try:
        # traffic covers baseline + fault + the early recovery window;
        # the verdict never waits for it longer than that
        duration = cs.baseline_s + cs.fault_s + 6.0
        scn = Scenario(
            seed=scenario_seed,
            mode="open",
            duration_s=duration,
            rate=rate,
            ramp_s=0.5,
            subscribers=0,
            max_inflight=32,
            timeout_s=3.0,
            mix=(("broadcast_tx_async", 3.0), ("status", 1.0)),
        ).validate()
        per_pool = max(1, scn.max_inflight // len(ln.rpc_addrs))
        pools = [
            ClientPool(a, size=per_pool, timeout_s=scn.timeout_s)
            for a in ln.rpc_addrs
        ]
        traffic = asyncio.ensure_future(run_open_loop(scn, pools))

        # baseline: the chain must be committing before we break it
        base_ok = await _wait_heights_above(
            ln, min(_heights(ln)), timeout_s=20.0
        )
        await asyncio.sleep(cs.baseline_s)

        await _arm_and_heal(cs, ln, seed)
        heal_wall_ns = time.time_ns()
        heal_height = max(_heights(ln))

        ttfc = await _wait_heights_above(
            ln, heal_height, timeout_s=cs.recovery_slo_s * 2 + 5.0
        )
        recovered = ttfc is not None and ttfc <= cs.recovery_slo_s

        stats, scheduled = await traffic
        traffic = None
        safety = _safety_check(ln)
        # the flight-recorder artifact: the TTFC number above,
        # decomposed into named recovery phases from the merged
        # per-node timelines, plus the per-height attribution tail
        # (loadgen/timeline.py; docs/observability.md)
        fleet = fleet_timeline.collect(ln)
        attribution = fleet_timeline.attribute_heights(fleet)
        tl_artifact = fleet_timeline.decompose_recovery(
            fleet, heal_wall_ns, heal_height
        )
        tl_artifact["heights_attributed"] = len(attribution)
        tl_artifact["attribution_tail"] = attribution[-5:]
        row = {
            "name": cs.name,
            "kind": cs.kind,
            "seed": scenario_seed,
            "fault_s": cs.fault_s,
            "recovery_slo_s": cs.recovery_slo_s,
            "baseline_commit_ok": base_ok is not None,
            "heal_height": heal_height,
            "ttfc_after_heal_s": (
                round(ttfc, 3) if ttfc is not None else None
            ),
            "recovered_within_slo": recovered,
            **safety,
            "requests_total": sum(st.count for st in stats.values()),
            "request_errors": sum(st.errors for st in stats.values()),
            "request_timeouts": sum(
                st.timeouts for st in stats.values()
            ),
            "scheduled_arrivals": scheduled,
            "timeline": tl_artifact,
            "p2p_disconnects": _p2p_counters(
                ln, "tendermint_tpu_p2p_peer_disconnects_total"
            ),
            "net_faults_applied": _p2p_counters(
                ln, "tendermint_tpu_p2p_net_faults_total"
            ),
            "passed": bool(
                safety["safety_ok"]
                and base_ok is not None
                and recovered
            ),
        }
        return row
    finally:
        # the plane must be disarmed before teardown even when a stage
        # raised mid-arc — a leaked partition would wedge the NEXT
        # scenario's boot
        faults.set_partition("")
        if traffic is not None:
            traffic.cancel()
            await asyncio.gather(traffic, return_exceptions=True)
        for p in pools:
            await p.close()
        await ln.stop()


async def run_campaign(
    home: str,
    scenarios: Optional[Sequence[ChaosScenario]] = None,
    n_nodes: int = 4,
    seed: int = 2026,
    rate: float = 50.0,
) -> dict:
    """Run the catalog; returns the BENCH_CHAOS.json document."""
    scenarios = (
        list(scenarios) if scenarios is not None else shipped_scenarios()
    )
    rows = []
    for cs in scenarios:
        rows.append(
            await run_chaos_scenario(
                cs, home, n_nodes=n_nodes, seed=seed, rate=rate
            )
        )
    return {
        "schema": "bench_chaos/v1",
        "seed": seed,
        "nodes": n_nodes,
        "offered_rate_per_s": rate,
        "scenarios": rows,
        "all_passed": all(r["passed"] for r in rows),
    }
