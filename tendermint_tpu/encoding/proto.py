"""Deterministic protobuf wire-format encoding.

The reference derives all consensus-critical byte strings (vote sign-bytes,
header field hashes, validator-set hashes) from gogo-protobuf marshalling of
canonical messages (reference: types/canonical.go, proto/tendermint/types/
canonical.proto, types/block.go:448 Header.Hash). Rather than depending on a
protobuf runtime whose output could drift, we implement the wire format
directly: encoding is deterministic by construction (fields written in
ascending tag order, no unknown fields, default values omitted exactly like
proto3).

Wire types: 0 = varint, 1 = fixed64, 2 = length-delimited, 5 = fixed32.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple

__all__ = [
    "ProtoWriter",
    "encode_varint",
    "decode_varint",
    "encode_zigzag",
    "decode_zigzag",
    "length_prefixed",
    "read_length_prefixed",
    "iter_fields",
]


# one-byte varints (values < 128) cover almost every tag and
# length-prefix the codec emits; interning them removes the encode
# loop and a bytes() allocation from the hottest path (measured: the
# pure-Python varint loop was the top non-crypto cost of light-client
# block saves)
_VARINT1 = [bytes([i]) for i in range(0x80)]


def encode_varint(value: int) -> bytes:
    """Encode an unsigned integer as a base-128 varint (LSB first)."""
    if value < 0:
        # proto3 int64 negative values are encoded as 10-byte two's complement
        value &= (1 << 64) - 1
    elif value < 0x80:
        return _VARINT1[value]
    elif value < 0x4000:
        return bytes((value & 0x7F | 0x80, value >> 7))
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, offset: int = 0) -> Tuple[int, int]:
    """Decode a varint; returns (value, new_offset)."""
    # single-byte fast path: the overwhelmingly common case for tags
    # and small lengths (mirror of encode_varint's interned table).
    # TypeError covers hostile type confusion (an int smuggled where
    # bytes belong by a wire-type flip): parse errors are ValueError,
    # the sanctioned decode-failure contract.
    try:
        b = data[offset]
    except IndexError:
        raise ValueError("truncated varint") from None
    except TypeError:
        raise ValueError("varint input is not bytes") from None
    if not b & 0x80:
        return b, offset + 1
    # seed the loop with the byte already fetched
    result = b & 0x7F
    shift = 7
    offset += 1
    while True:
        if offset >= len(data):
            raise ValueError("truncated varint")
        b = data[offset]
        offset += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 64:
                raise ValueError("varint overflows 64 bits")
            return result, offset
        shift += 7
        if shift >= 70:
            # protobuf varints are at most 10 bytes
            raise ValueError("varint too long")


def encode_zigzag(value: int) -> int:
    return (value << 1) ^ (value >> 63) if value < 0 else value << 1


def decode_zigzag(value: int) -> int:
    return (value >> 1) ^ -(value & 1)


class ProtoWriter:
    """Append-only deterministic protobuf message writer.

    Callers must write fields in ascending field-number order to stay
    canonical; this is asserted.
    """

    __slots__ = ("_buf", "_last_field")

    def __init__(self) -> None:
        self._buf = bytearray()
        self._last_field = 0

    def _tag(self, field: int, wire_type: int) -> None:
        if field <= 0:
            raise ValueError("field numbers start at 1")
        if field < self._last_field:
            raise ValueError(
                f"non-canonical field order: {field} after {self._last_field}"
            )
        self._last_field = field
        tag = (field << 3) | wire_type
        if tag < 0x80:  # fields 1-15: single-byte tag, no varint call
            self._buf.append(tag)
        else:
            self._buf += encode_varint(tag)

    # -- scalar writers (proto3 semantics: zero values are omitted) --

    def uint(self, field: int, value: int) -> None:
        if value:
            self._tag(field, 0)
            self._buf += encode_varint(value)

    def int(self, field: int, value: int) -> None:
        if value:
            self._tag(field, 0)
            self._buf += encode_varint(value)

    def sint(self, field: int, value: int) -> None:
        if value:
            self._tag(field, 0)
            self._buf += encode_varint(encode_zigzag(value))

    def bool(self, field: int, value: bool) -> None:
        if value:
            self._tag(field, 0)
            self._buf += b"\x01"

    def sfixed64(self, field: int, value: int) -> None:
        if value:
            self._tag(field, 1)
            self._buf += struct.pack("<q", value)

    def fixed64(self, field: int, value: int) -> None:
        if value:
            self._tag(field, 1)
            self._buf += struct.pack("<Q", value)

    def sfixed32(self, field: int, value: int) -> None:
        if value:
            self._tag(field, 5)
            self._buf += struct.pack("<i", value)

    def double(self, field: int, value: float) -> None:
        if value:
            self._tag(field, 1)
            self._buf += struct.pack("<d", value)

    def bytes(self, field: int, value: bytes) -> None:
        if value:
            self._tag(field, 2)
            n = len(value)
            if n < 0x80:
                self._buf.append(n)
            else:
                self._buf += encode_varint(n)
            self._buf += value

    def string(self, field: int, value: str) -> None:
        if value:
            self.bytes(field, value.encode("utf-8"))

    def message(self, field: int, value: "bytes | ProtoWriter | None") -> None:
        """Write an embedded message. None is omitted; empty messages are
        WRITTEN (an empty message is distinct from an absent one, matching
        gogoproto nullable=false semantics)."""
        if value is None:
            return
        body = value.finish() if isinstance(value, ProtoWriter) else value
        self._tag(field, 2)
        n = len(body)
        if n < 0x80:
            self._buf.append(n)
        else:
            self._buf += encode_varint(n)
        self._buf += body

    # always-write variants, for non-nullable embedded use where zero must
    # still appear (rare; sfixed64 height=0 in canonical votes is omitted by
    # gogoproto as well, so the default writers above match the reference).

    def finish(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)


def length_prefixed(msg: bytes) -> bytes:
    """Varint length-prefix a message (protoio.MarshalDelimited semantics,
    used for vote/proposal sign-bytes; reference: types/vote.go:93)."""
    return encode_varint(len(msg)) + msg


def read_length_prefixed(data: bytes, offset: int = 0) -> Tuple[bytes, int]:
    n, offset = decode_varint(data, offset)
    if offset + n > len(data):
        raise ValueError("truncated length-prefixed message")
    return data[offset : offset + n], offset + n


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, "int | bytes"]]:
    """Iterate (field_number, wire_type, value) over an encoded message.

    Varint/fixed fields yield ints; length-delimited yield bytes.
    """
    if not isinstance(data, (bytes, bytearray, memoryview)):
        # a nested decoder handed a wire-type-confused value (int where
        # a submessage's bytes belong): sanctioned parse error, not a
        # TypeError three frames later
        raise ValueError(
            f"message input is not bytes (got {type(data).__name__})"
        )
    offset = 0
    while offset < len(data):
        key, offset = decode_varint(data, offset)
        field, wire_type = key >> 3, key & 7
        if wire_type == 0:
            value, offset = decode_varint(data, offset)
        elif wire_type == 1:
            if offset + 8 > len(data):
                raise ValueError("truncated fixed64 field")
            (value,) = struct.unpack_from("<Q", data, offset)
            offset += 8
        elif wire_type == 2:
            value, offset = read_length_prefixed(data, offset)
        elif wire_type == 5:
            if offset + 4 > len(data):
                raise ValueError("truncated fixed32 field")
            (value,) = struct.unpack_from("<I", data, offset)
            offset += 4
        else:
            raise ValueError(f"unsupported wire type {wire_type}")
        yield field, wire_type, value


class FieldReader:
    """Random-access view over a single encoded message's fields.

    The typed accessors ENFORCE the wire type: a peer that sends field
    N as a varint where the schema says length-delimited (or vice
    versa) gets a ValueError from the accessor, not an int leaking
    into code that calls `.decode()`/`len()` on it and dies with an
    AttributeError three frames later. This is the sanctioned-error
    contract the WAL corruption handler, the RPC error mapper and the
    decoder fuzzer (tests/test_decoder_fuzz.py) rely on: malformed
    wire input fails as a *parse error*, never as a type confusion.
    `get`/`get_all` stay raw for callers that handle both shapes
    (packed-vs-unpacked repeated fields, nested submessage bytes)."""

    def __init__(self, data: bytes) -> None:
        self._fields: dict[int, list] = {}
        for field, _wt, value in iter_fields(data):
            self._fields.setdefault(field, []).append(value)

    def get(self, field: int, default=None):
        vals = self._fields.get(field)
        return vals[-1] if vals else default

    def get_all(self, field: int) -> List:
        return self._fields.get(field, [])

    def uint(self, field: int, default: int = 0) -> int:
        vals = self._fields.get(field)
        if not vals:
            return default
        v = vals[-1]
        if not isinstance(v, int):
            raise ValueError(
                f"field {field}: expected varint, got length-delimited"
            )
        return int(v)

    def int64(self, field: int, default: int = 0) -> int:
        vals = self._fields.get(field)
        if not vals:
            return default
        v = vals[-1]
        if not isinstance(v, int):
            raise ValueError(
                f"field {field}: expected varint, got length-delimited"
            )
        v = int(v)
        return v - (1 << 64) if v >= 1 << 63 else v

    def sfixed64(self, field: int, default: int = 0) -> int:
        v = self.get(field)
        if v is None:
            return default
        if not isinstance(v, int):
            raise ValueError(
                f"field {field}: expected fixed64, got length-delimited"
            )
        return v - (1 << 64) if v >= 1 << 63 else v

    def bytes(self, field: int, default: bytes = b"") -> bytes:
        vals = self._fields.get(field)
        if not vals:
            return default
        v = vals[-1]
        if not isinstance(v, (bytes, bytearray, memoryview)):
            raise ValueError(
                f"field {field}: expected length-delimited, got varint"
            )
        return v

    def string(self, field: int, default: str = "") -> str:
        v = self.get(field)
        if v is None:
            return default
        if not isinstance(v, (bytes, bytearray, memoryview)):
            raise ValueError(
                f"field {field}: expected length-delimited, got varint"
            )
        return bytes(v).decode("utf-8")

    def bool(self, field: int) -> bool:
        vals = self._fields.get(field)
        if not vals:
            return False
        v = vals[-1]
        if not isinstance(v, int):
            raise ValueError(
                f"field {field}: expected varint, got length-delimited"
            )
        return bool(v)
