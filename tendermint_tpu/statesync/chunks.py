"""On-disk snapshot chunk queue.

Fetched chunks spool to one file per index in a private temp directory,
so a restore's peak memory is bounded by a single chunk rather than the
whole snapshot — a multi-GB snapshot restores in O(chunk) RAM
(reference: internal/statesync/chunks.go:33-54 NewChunkQueue spooling
to a tempdir, :88 Add writing per-index files, Discard/Retry :178-214).
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Optional, Set

__all__ = ["ChunkQueue"]


class ChunkQueue:
    """Per-index chunk spool for one snapshot restore.

    put() writes the chunk to disk and remembers which peer sent it;
    get() reads it back; discard() deletes the file (after a successful
    apply, or when the app asks for a re-fetch). close() removes the
    whole directory — always call it, success or failure.
    """

    def __init__(self, total: int, dir: Optional[str] = None) -> None:
        if total < 0:
            raise ValueError("negative chunk count")
        self.total = total
        self._dir = tempfile.mkdtemp(prefix="tm-statesync-chunks-", dir=dir)
        self._have: Set[int] = set()
        self._returned: Set[int] = set()  # applied (ACCEPTed) indexes
        self._senders: dict = {}
        self._closed = False

    def _path(self, index: int) -> str:
        return os.path.join(self._dir, f"{index:06d}")

    def _check(self, index: int) -> None:
        if self._closed:
            raise RuntimeError("chunk queue is closed")
        if not 0 <= index < self.total:
            raise IndexError(f"chunk index {index} out of range")

    def put(self, index: int, chunk: bytes, sender: str = "") -> bool:
        """Spool one chunk; returns False if the index is already
        present (first responder wins, reference chunks.go Add)."""
        self._check(index)
        if index in self._have:
            return False
        tmp = self._path(index) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(chunk)
        os.replace(tmp, self._path(index))
        self._have.add(index)
        self._senders[index] = sender
        return True

    def has(self, index: int) -> bool:
        self._check(index)
        return index in self._have

    def get(self, index: int) -> bytes:
        self._check(index)
        if index not in self._have:
            raise KeyError(f"chunk {index} not in queue")
        with open(self._path(index), "rb") as f:
            return f.read()

    def sender(self, index: int) -> str:
        return self._senders.get(index, "")

    def discard(self, index: int) -> None:
        """Drop a chunk so it can be re-fetched (reference chunks.go
        Discard :160-185): deletes the backing file and clears the
        returned flag, so the apply cursor naturally rewinds to it once
        the re-fetch lands."""
        self._check(index)
        if index in self._have:
            os.remove(self._path(index))
            self._have.discard(index)
            self._senders.pop(index, None)
        self._returned.discard(index)

    # -- apply-cursor bookkeeping (reference chunks.go Next/Retry) --

    def next_up(self) -> Optional[int]:
        """Lowest index not yet applied, or None when every chunk has
        been returned (reference chunks.go nextUp :288-300)."""
        for i in range(self.total):
            if i not in self._returned:
                return i
        return None

    def mark_returned(self, index: int) -> None:
        self._check(index)
        self._returned.add(index)

    def is_returned(self, index: int) -> bool:
        """True once the chunk has been handed to the app and not since
        discarded/retried (the apply cursor skips returned chunks)."""
        return index in self._returned

    def retry(self, index: int) -> None:
        """Schedule a re-apply WITHOUT refetching (reference chunks.go
        Retry :303-308)."""
        self._check(index)
        self._returned.discard(index)

    def missing(self) -> Set[int]:
        return set(range(self.total)) - self._have

    def __len__(self) -> int:
        return len(self._have)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            shutil.rmtree(self._dir, ignore_errors=True)
