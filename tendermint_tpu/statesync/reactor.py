"""State sync — bootstrap a fresh node from an application snapshot.

reference: internal/statesync/reactor.go (channels :36-45), syncer.go
(:159-552: discovery → selection → OfferSnapshot → parallel chunk fetch →
ApplySnapshotChunk → verifyApp), stateprovider.go (trusted state via
light blocks over the LightBlock channel), chunks.go, snapshots.go.

Trust model: state sync requires an operator-supplied trust root
(``trust_height`` + ``trust_hash``, reference config.go:811-895) and
verifies snapshot light blocks through an embedded light client
(sequential/skipping bisection from the pinned root, reference
stateprovider.go:33-51) whose providers fetch over the LightBlock
channel from the snapshot peers. Consecutive fetched headers are
additionally checked for hash linkage and next-validators-hash
chaining.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from ..config import StateSyncConfig
from ..libs import rng
from ..libs.log import get_logger
from ..libs.service import Service
from ..p2p.channel import Channel
from ..p2p.peermanager import PeerStatus
from ..p2p.types import ChannelDescriptor, Envelope, PeerError
from ..state.types import State
from ..types.block_id import BlockID
from ..types.light import LightBlock, SignedHeader
from ..types.params import ConsensusParams
from ..light.errors import LightClientError
from ..types.validation import verify_commit_light
from .chunks import ChunkQueue
from .msgs import (
    ChunkRequestMessage,
    ChunkResponseMessage,
    LightBlockRequestMessage,
    LightBlockResponseMessage,
    ParamsRequestMessage,
    ParamsResponseMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    StatesyncCodec,
)

__all__ = [
    "StatesyncReactor",
    "SNAPSHOT_CHANNEL",
    "CHUNK_CHANNEL",
    "LIGHT_BLOCK_CHANNEL",
    "PARAMS_CHANNEL",
    "statesync_channel_descriptors",
    "SyncError",
]

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
LIGHT_BLOCK_CHANNEL = 0x62
PARAMS_CHANNEL = 0x63

_RECENT_SNAPSHOTS = 10  # serve at most N (reference: reactor.go:56)
_CHUNK_TIMEOUT = 10.0
_LIGHT_BLOCK_TIMEOUT = 5.0


class SyncError(Exception):
    pass


def statesync_channel_descriptors():
    """reference: reactor.go:36-45."""
    return {
        cid: ChannelDescriptor(
            channel_id=cid,
            message_type=StatesyncCodec,
            priority=p,
            send_queue_capacity=cap,
            recv_buffer_capacity=128,
            name=name,
        )
        for cid, p, cap, name in (
            (SNAPSHOT_CHANNEL, 6, 10, "snapshot"),
            (CHUNK_CHANNEL, 3, 4, "chunk"),
            (LIGHT_BLOCK_CHANNEL, 2, 10, "lightblock"),
            (PARAMS_CHANNEL, 2, 10, "params"),
        )
    }


@dataclass
class _Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes
    peers: Set[str] = field(default_factory=set)

    def key(self) -> Tuple[int, int, bytes]:
        return (self.height, self.format, self.hash)


class StatesyncReactor(Service):
    def __init__(
        self,
        chain_id: str,
        initial_state: State,
        app_client,  # snapshot connection
        state_store,
        block_store,
        channels: Dict[int, Channel],
        peer_updates: asyncio.Queue,
        cfg: Optional[StateSyncConfig] = None,
    ) -> None:
        super().__init__(name="statesync", logger=get_logger("statesync"))
        self.chain_id = chain_id
        self.initial_state = initial_state
        self.app = app_client
        self.state_store = state_store
        self.block_store = block_store
        self.snapshot_ch = channels[SNAPSHOT_CHANNEL]
        self.chunk_ch = channels[CHUNK_CHANNEL]
        self.light_ch = channels[LIGHT_BLOCK_CHANNEL]
        self.params_ch = channels[PARAMS_CHANNEL]
        self.peer_updates = peer_updates
        self.cfg = cfg or StateSyncConfig()
        self.peers: Set[str] = set()
        # discovery pool
        self._snapshots: Dict[Tuple[int, int, bytes], _Snapshot] = {}
        self._rejected: Set[Tuple[int, int, bytes]] = set()
        # peers the app flagged via ResponseApplySnapshotChunk
        # .reject_senders — excluded from chunk fetches for the rest of
        # the restore (reference: syncer.go:431-441)
        self._rejected_senders: Set[str] = set()
        # in-flight response routing, keyed by (sender_peer, request key)
        self._chunk_waiters: Dict[Tuple, asyncio.Future] = {}
        self._light_waiters: Dict[Tuple[str, int], asyncio.Future] = {}
        self._params_waiters: Dict[Tuple[str, int], asyncio.Future] = {}
        self.synced_state: Optional[State] = None

    async def on_start(self) -> None:
        self.spawn(self._peer_update_routine(), "peer-updates")
        self.spawn(self._recv(self.snapshot_ch, self._on_snapshot_msg), "recv-snap")
        self.spawn(self._recv(self.chunk_ch, self._on_chunk_msg), "recv-chunk")
        self.spawn(self._recv(self.light_ch, self._on_light_msg), "recv-light")
        self.spawn(self._recv(self.params_ch, self._on_params_msg), "recv-params")

    # ------------------------------------------------------------------
    # serving side (every node serves; reference: reactor.go handle*)

    async def _recv(self, channel: Channel, handler) -> None:
        async for envelope in channel:
            try:
                await handler(envelope)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.error(
                    "statesync message failed", ch=channel.name, err=str(e)
                )

    async def _peer_update_routine(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP:
                self.peers.add(update.node_id)
            else:
                self.peers.discard(update.node_id)
                for snap in self._snapshots.values():
                    snap.peers.discard(update.node_id)

    async def _on_snapshot_msg(self, envelope: Envelope) -> None:
        msg = envelope.message
        if isinstance(msg, SnapshotsRequestMessage):
            res = await self.app.list_snapshots(abci.RequestListSnapshots())
            for snap in sorted(
                res.snapshots, key=lambda s: s.height, reverse=True
            )[:_RECENT_SNAPSHOTS]:
                self.snapshot_ch.try_send(
                    Envelope(
                        message=SnapshotsResponseMessage(
                            height=snap.height,
                            format=snap.format,
                            chunks=snap.chunks,
                            hash=snap.hash,
                            metadata=snap.metadata,
                        ),
                        to=envelope.from_peer,
                    )
                )
        elif isinstance(msg, SnapshotsResponseMessage):
            key = (msg.height, msg.format, msg.hash)
            if key in self._rejected:
                return
            snap = self._snapshots.get(key)
            if snap is None:
                snap = _Snapshot(
                    height=msg.height, format=msg.format, chunks=msg.chunks,
                    hash=msg.hash, metadata=msg.metadata,
                )
                self._snapshots[key] = snap
            snap.peers.add(envelope.from_peer)

    async def _on_chunk_msg(self, envelope: Envelope) -> None:
        msg = envelope.message
        if isinstance(msg, ChunkRequestMessage):
            res = await self.app.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(
                    height=msg.height, format=msg.format, chunk=msg.index
                )
            )
            self.chunk_ch.try_send(
                Envelope(
                    message=ChunkResponseMessage(
                        height=msg.height,
                        format=msg.format,
                        index=msg.index,
                        chunk=res.chunk,
                        missing=not res.chunk,
                    ),
                    to=envelope.from_peer,
                )
            )
        elif isinstance(msg, ChunkResponseMessage):
            # sender-keyed: a third peer can't poison the future of a
            # request we sent to someone else
            fut = self._chunk_waiters.pop(
                (envelope.from_peer, msg.height, msg.format, msg.index),
                None,
            )
            if fut is not None and not fut.done():
                fut.set_result(msg)

    async def _on_light_msg(self, envelope: Envelope) -> None:
        msg = envelope.message
        if isinstance(msg, LightBlockRequestMessage):
            lb = await self._load_light_block(msg.height)
            self.light_ch.try_send(
                Envelope(
                    message=LightBlockResponseMessage(light_block=lb),
                    to=envelope.from_peer,
                )
            )
        elif isinstance(msg, LightBlockResponseMessage):
            if msg.light_block is None or msg.light_block.signed_header is None:
                return
            h = msg.light_block.signed_header.header.height
            # a tip request is keyed (peer, 0); the response carries
            # the actual height
            fut = self._light_waiters.pop(
                (envelope.from_peer, h), None
            ) or self._light_waiters.pop((envelope.from_peer, 0), None)
            if fut is not None and not fut.done():
                fut.set_result(msg.light_block)

    async def _on_params_msg(self, envelope: Envelope) -> None:
        msg = envelope.message
        if isinstance(msg, ParamsRequestMessage):
            params = self.state_store.load_params(msg.height)
            if params is None:
                state = self.state_store.load()
                params = state.consensus_params if state else None
            if params is not None:
                self.params_ch.try_send(
                    Envelope(
                        message=ParamsResponseMessage(
                            height=msg.height,
                            # tmcost: cost-recompute-ok — ConsensusParams
                            # is a fixed handful of ints; its encode is
                            # O(1), not content-proportional, so a
                            # per-block cache entry would cost more than
                            # the work it saves
                            consensus_params=params.to_proto(),
                        ),
                        to=envelope.from_peer,
                    )
                )
        elif isinstance(msg, ParamsResponseMessage):
            fut = self._params_waiters.pop(
                (envelope.from_peer, msg.height), None
            )
            if fut is not None and not fut.done():
                fut.set_result(msg.consensus_params)

    async def _load_light_block(self, height: int) -> Optional[LightBlock]:
        """reference: statesync/reactor.go handleLightBlockMessage.
        Serving delegates to the same LocalProvider logic the light
        proxy uses (0 = tip, seen-commit fallback at the tip)."""
        from ..light.errors import LightBlockNotFoundError
        from ..light.provider import LocalProvider

        provider = LocalProvider(self.block_store, self.state_store)
        try:
            return await provider.light_block(height)
        except LightBlockNotFoundError:
            return None

    # ------------------------------------------------------------------
    # sync side (reference: syncer.go SyncAny :159)

    async def sync(self) -> State:
        """Discover snapshots, restore the best one, return the
        bootstrapped State. Raises SyncError if no snapshot worked.

        Requires the operator trust root (reference: config.go:811-895
        — TrustHeight/TrustHash are mandatory for state sync)."""
        if self.cfg.trust_height <= 0 or not self.cfg.trust_hash:
            raise SyncError(
                "state sync requires statesync.trust_height and "
                "statesync.trust_hash (obtain them out-of-band from a "
                "trusted source)"
            )
        try:
            trust_hash = bytes.fromhex(self.cfg.trust_hash)
        except ValueError as e:
            raise SyncError(f"invalid statesync.trust_hash: {e}") from e
        if len(trust_hash) != 32:
            raise SyncError(
                f"statesync.trust_hash must be 32 hex bytes, got "
                f"{len(trust_hash)}"
            )
        self.logger.info(
            "discovering snapshots",
            seconds=self.cfg.discovery_time,
        )
        self.snapshot_ch.try_send(
            Envelope(message=SnapshotsRequestMessage(), broadcast=True)
        )
        await asyncio.sleep(self.cfg.discovery_time)

        light_client = self._make_light_client(trust_hash)
        # pin the trust root up front: a root failure is an operator
        # config / provider problem, NOT a reason to reject snapshots
        try:
            await light_client.initialize()
        except LightClientError as e:
            raise SyncError(f"trust root verification failed: {e}") from e

        discovery_rounds = 0
        while True:
            snapshot = self._best_snapshot()
            if snapshot is None:
                # providers prune old snapshots while the chain moves;
                # a one-shot discovery pool can empty out after a slow
                # chunk round. Re-discover a few times before giving up
                # (reference: syncer.go SyncAny's discovery retry loop).
                discovery_rounds += 1
                if discovery_rounds > 3:
                    raise SyncError("no viable snapshots discovered")
                self.logger.info(
                    "re-discovering snapshots", attempt=discovery_rounds
                )
                # transiently-rejected snapshots (e.g. light blocks at
                # h+1/h+2 didn't exist yet) may verify now that the
                # chain has advanced; the bounded round count keeps a
                # permanently-bad snapshot from looping forever
                self._rejected.clear()
                self.snapshot_ch.try_send(
                    Envelope(
                        message=SnapshotsRequestMessage(), broadcast=True
                    )
                )
                await asyncio.sleep(self.cfg.discovery_time)
                continue
            try:
                state = await self._sync_snapshot(snapshot, light_client)
                self.synced_state = state
                return state
            except (SyncError, LightClientError) as e:
                self.logger.error(
                    "snapshot restore failed; trying next",
                    height=snapshot.height,
                    err=str(e),
                )
                self._rejected.add(snapshot.key())
                self._snapshots.pop(snapshot.key(), None)

    def _make_light_client(self, trust_hash: bytes):
        """Embedded light client over the snapshot peers (reference:
        stateprovider.go:33-51 — trusted state via light client over
        the LightBlock channel)."""
        from ..light import Client, LightStore, P2PProvider, TrustOptions
        from ..store.kv import MemKV

        providers = [
            P2PProvider(peer, self._fetch_light_block_from)
            for peer in sorted(self.peers)
        ]
        if not providers:
            raise SyncError("no peers to serve light blocks")
        return Client(
            self.chain_id,
            TrustOptions(
                period_ns=int(self.cfg.trust_period * 1e9),
                height=self.cfg.trust_height,
                hash=trust_hash,
            ),
            providers[0],
            providers[1:],
            LightStore(MemKV()),
        )

    def _best_snapshot(self) -> Optional[_Snapshot]:
        """Highest height, then most peers (reference: snapshots.go
        snapshotPool.Best ranking)."""
        candidates = [
            s for s in self._snapshots.values()
            if s.peers and s.key() not in self._rejected
            # can't anchor trust for snapshots below the trust height
            and s.height >= self.cfg.trust_height
        ]
        if not candidates:
            return None
        return max(candidates, key=lambda s: (s.height, len(s.peers)))

    async def _sync_snapshot(
        self, snapshot: _Snapshot, light_client
    ) -> State:
        """reference: syncer.go Sync :263-460."""
        h = snapshot.height
        self._rejected_senders.clear()  # per-restore, like the syncer's
        self.logger.info(
            "restoring snapshot", height=h, format=snapshot.format,
            chunks=snapshot.chunks,
        )
        # 1. trusted state info from light blocks at h, h+1, h+2 —
        # each verified from the operator trust root by the embedded
        # light client (bisection through validator churn)
        lb_h = await light_client.verify_light_block_at_height(h)
        lb_h1 = await light_client.verify_light_block_at_height(h + 1)
        lb_h2 = await light_client.verify_light_block_at_height(h + 2)

        # cross-height linkage: headers must chain by hash and by
        # next-validators-hash (defense in depth over the light
        # client's commit checks)
        for older, newer in ((lb_h, lb_h1), (lb_h1, lb_h2)):
            oh, nh = older.signed_header.header, newer.signed_header.header
            if nh.last_block_id.hash != oh.hash():
                raise SyncError(
                    f"light block at {nh.height} does not link to header "
                    f"at {oh.height}"
                )
            if oh.next_validators_hash != newer.validator_set.hash():
                raise SyncError(
                    f"validator set at {nh.height} does not match "
                    f"next_validators_hash at {oh.height}"
                )
        app_hash = lb_h1.signed_header.header.app_hash

        # 2. offer to the app
        offer = await self.app.offer_snapshot(
            abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=app_hash,
            )
        )
        if offer.result != abci.OFFER_SNAPSHOT_ACCEPT:
            raise SyncError(f"snapshot rejected by app: {offer.result}")

        # 3. fetch chunks in parallel into the on-disk queue, apply in
        # order reading one chunk at a time — restore memory is bounded
        # by a single chunk, not the snapshot (reference: chunks.go
        # tempdir spool; syncer.go applyChunks :403-460)
        queue = ChunkQueue(snapshot.chunks)
        try:
            await self._fetch_chunks(snapshot, queue)
            await self._apply_chunks(snapshot, queue)
        finally:
            queue.close()

        # 4. verify the app landed on the trusted hash
        info = await self.app.info(abci.RequestInfo())
        if info.last_block_height != h:
            raise SyncError(
                f"app restored to height {info.last_block_height}, "
                f"expected {h}"
            )
        if info.last_block_app_hash != app_hash:
            raise SyncError(
                f"app hash mismatch after restore: "
                f"{info.last_block_app_hash.hex()[:16]} != "
                f"{app_hash.hex()[:16]}"
            )

        # 5. build + persist the trusted state
        params = await self._fetch_params(h + 1, snapshot.peers)
        state = self._build_state(lb_h, lb_h1, lb_h2, params)
        self.state_store.bootstrap(state)
        self.block_store.save_signed_header(
            lb_h.signed_header,
            lb_h1.signed_header.header.last_block_id,
        )
        self.logger.info("snapshot restored", height=h)
        return state

    async def _fetch_light_block_from(
        self, height: int, peer: str
    ) -> Optional[LightBlock]:
        """Raw per-peer fetch for the embedded light client's
        P2PProviders; verification is the client's job. height 0 asks
        for the peer's tip."""
        fut = asyncio.get_event_loop().create_future()
        self._light_waiters[(peer, height)] = fut
        try:
            self.light_ch.try_send(
                Envelope(
                    message=LightBlockRequestMessage(height=height), to=peer
                )
            )
            return await asyncio.wait_for(fut, timeout=_LIGHT_BLOCK_TIMEOUT)
        except asyncio.TimeoutError:
            return None
        finally:
            self._light_waiters.pop((peer, height), None)

    async def _fetch_chunks(
        self, snapshot: _Snapshot, queue: ChunkQueue, indexes=None
    ) -> None:
        """Parallel chunk fetch with per-chunk retry over providers,
        spooling straight to the on-disk queue (reference: syncer.go
        fetchChunks :464-520, chunks.go). `indexes` limits the fetch to
        a subset — the re-fetch path after the app discards chunks."""
        sem = asyncio.Semaphore(self.cfg.fetchers)

        async def fetch(index: int) -> None:
            async with sem:
                for attempt in range(4):
                    providers = sorted(
                        p for p in snapshot.peers
                        if p not in self._rejected_senders
                    )
                    if not providers:
                        # all providers disconnected mid-fetch (or the
                        # app rejected every remaining sender)
                        raise SyncError("no remaining snapshot providers")
                    peer = rng.choice(providers)
                    fut = asyncio.get_event_loop().create_future()
                    self._chunk_waiters[
                        (peer, snapshot.height, snapshot.format, index)
                    ] = fut
                    self.chunk_ch.try_send(
                        Envelope(
                            message=ChunkRequestMessage(
                                height=snapshot.height,
                                format=snapshot.format,
                                index=index,
                            ),
                            to=peer,
                        )
                    )
                    try:
                        res = await asyncio.wait_for(
                            fut, timeout=self.cfg.chunk_request_timeout
                        )
                    except asyncio.TimeoutError:
                        continue
                    if res.missing:
                        continue
                    queue.put(index, res.chunk, sender=peer)
                    return
                raise SyncError(f"failed to fetch chunk {index}")

        todo = list(indexes) if indexes is not None else list(
            range(snapshot.chunks)
        )
        tasks = [asyncio.ensure_future(fetch(i)) for i in todo]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            # one chunk failing must not leave sibling fetches running:
            # they would later put() into a closed (deleted) queue and
            # die as never-retrieved task exceptions
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise

    async def _apply_chunks(
        self, snapshot: _Snapshot, queue: ChunkQueue
    ) -> None:
        """Feed queued chunks to the app in index order, honoring the
        app's control results (reference: syncer.go applyChunks
        :403-460): ACCEPT marks the chunk returned and the cursor moves
        to the lowest unreturned index; refetch_chunks are discarded
        (file deleted + returned flag cleared, so the cursor rewinds to
        them) and re-fetched from providers; RETRY clears the returned
        flag without refetching; ABORT/RETRY_SNAPSHOT/REJECT_SNAPSHOT
        fail this restore. Chunk files persist until the queue closes —
        disk, not RAM, bounds the restore."""
        steps = 0
        while True:
            index = queue.next_up()
            if index is None:
                return
            steps += 1
            if steps > 4 * snapshot.chunks + 16:
                raise SyncError("app keeps retrying/refetching chunks")
            if not queue.has(index):
                # a hole left by a rejected sender's discarded chunks:
                # refetch from the remaining (non-rejected) providers
                await self._fetch_chunks(snapshot, queue, indexes=[index])
            res = await self.app.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(
                    index=index,
                    chunk=queue.get(index),
                    sender=queue.sender(index),
                )
            )
            queue.mark_returned(index)
            # senders the app flagged as bad: ban them from further
            # fetches this restore and drop their not-yet-applied
            # chunks so re-fetches come from someone else (reference:
            # syncer.go:431-441 rejectSenders)
            for bad in res.reject_senders:
                if not bad:
                    continue
                self._rejected_senders.add(bad)
                for i in range(snapshot.chunks):
                    if (
                        queue.has(i)
                        and queue.sender(i) == bad
                        and not queue.is_returned(i)
                    ):
                        queue.discard(i)
            # validate refetch indexes BEFORE acting on them: a
            # misbehaving app must fail the restore as a SyncError,
            # not crash the reactor with a bare IndexError
            refetch = []
            for r in res.refetch_chunks:
                if not 0 <= r < snapshot.chunks:
                    raise SyncError(
                        f"app requested refetch of out-of-range "
                        f"chunk {r} (snapshot has {snapshot.chunks})"
                    )
                refetch.append(r)
            for r in refetch:
                queue.discard(r)
            # terminal results first: an ABORT/REJECT must not trigger
            # a round of network fetches that gets thrown away
            if res.result not in (
                abci.APPLY_CHUNK_ACCEPT, abci.APPLY_CHUNK_RETRY
            ):
                raise SyncError(f"chunk {index} rejected: {res.result}")
            if refetch:
                await self._fetch_chunks(snapshot, queue, indexes=refetch)
            if res.result == abci.APPLY_CHUNK_RETRY:
                queue.retry(index)

    async def _fetch_light_block(
        self, height: int, peers: Set[str]
    ) -> LightBlock:
        """Fetch + verify a light block from snapshot providers
        (reference: stateprovider.go P2P provider)."""
        candidates = list(dict.fromkeys(list(peers) + list(self.peers)))
        for peer in candidates:
            lb = await self._fetch_light_block_from(height, peer)
            if lb is None:
                continue
            try:
                self._verify_light_block(lb, height)
            except Exception as e:
                self.logger.info(
                    "peer sent invalid light block", peer=peer[:12],
                    err=str(e),
                )
                continue
            return lb
        raise SyncError(f"could not fetch light block at height {height}")

    def _verify_light_block(self, lb: LightBlock, height: int) -> None:
        """Internal-consistency verification (see module docstring)."""
        sh = lb.signed_header
        if sh.header.height != height:
            raise ValueError("wrong height")
        if sh.header.chain_id != self.chain_id:
            raise ValueError("wrong chain id")
        if lb.validator_set.hash() != sh.header.validators_hash:
            raise ValueError("validator set doesn't match header")
        if sh.commit.block_id.hash != sh.header.hash():
            raise ValueError("commit is for a different block")
        # 2/3 of the set signed — one batched device verify
        verify_commit_light(
            self.chain_id,
            lb.validator_set,
            sh.commit.block_id,
            height,
            sh.commit,
        )

    async def _fetch_params(
        self, height: int, peers: Set[str]
    ) -> ConsensusParams:
        for peer in list(peers) + list(self.peers):
            fut = asyncio.get_event_loop().create_future()
            self._params_waiters[(peer, height)] = fut
            self.params_ch.try_send(
                Envelope(
                    message=ParamsRequestMessage(height=height), to=peer
                )
            )
            try:
                raw = await asyncio.wait_for(fut, timeout=_LIGHT_BLOCK_TIMEOUT)
            except asyncio.TimeoutError:
                continue
            return ConsensusParams.from_proto(raw)
        raise SyncError(f"could not fetch consensus params at {height}")

    def _build_state(
        self,
        lb_h: LightBlock,
        lb_h1: LightBlock,
        lb_h2: LightBlock,
        params: ConsensusParams,
    ) -> State:
        """reference: stateprovider.go State() :150-200."""
        h = lb_h.signed_header.header.height
        state = self.initial_state.copy()
        state.last_block_height = h
        state.last_block_time_ns = lb_h.signed_header.header.time_ns
        state.last_block_id = lb_h.signed_header.commit.block_id
        state.app_hash = lb_h1.signed_header.header.app_hash
        state.last_results_hash = lb_h1.signed_header.header.last_results_hash
        state.last_validators = lb_h.validator_set
        state.validators = lb_h1.validator_set
        state.next_validators = lb_h2.validator_set
        state.last_height_validators_changed = h + 1
        state.consensus_params = params
        state.last_height_consensus_params_changed = h + 1
        return state

    # ------------------------------------------------------------------
    # backfill (reference: reactor.go:341-363, ADR-068)

    async def backfill(self, state: State) -> int:
        """Fetch and store verified signed headers backward from the sync
        base to the evidence window; returns how many were stored."""
        max_age = state.consensus_params.evidence.max_age_num_blocks
        stop_height = max(state.initial_height, state.last_block_height - max_age)
        height = self.block_store.base() - 1
        stored = 0
        prev_header = None
        meta = self.block_store.load_block_meta(self.block_store.base())
        if meta is not None:
            prev_header = meta.header
        while height >= stop_height and prev_header is not None:
            try:
                lb = await self._fetch_light_block(height, self.peers)
            except SyncError:
                break
            # linkage: the newer header must point at this block
            if prev_header.last_block_id.hash != lb.signed_header.header.hash():
                self.logger.error(
                    "backfill light block does not link", height=height
                )
                break
            self.block_store.save_signed_header(
                lb.signed_header, prev_header.last_block_id
            )
            self.state_store.save_validators(height, lb.validator_set)
            prev_header = lb.signed_header.header
            height -= 1
            stored += 1
        return stored
