"""State sync — bootstrap from application snapshots.

reference: internal/statesync/.
"""

from .msgs import (
    ChunkRequestMessage,
    ChunkResponseMessage,
    LightBlockRequestMessage,
    LightBlockResponseMessage,
    ParamsRequestMessage,
    ParamsResponseMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    StatesyncCodec,
)
from .reactor import (
    CHUNK_CHANNEL,
    LIGHT_BLOCK_CHANNEL,
    PARAMS_CHANNEL,
    SNAPSHOT_CHANNEL,
    StatesyncReactor,
    SyncError,
    statesync_channel_descriptors,
)

__all__ = [
    "CHUNK_CHANNEL",
    "ChunkRequestMessage",
    "ChunkResponseMessage",
    "LIGHT_BLOCK_CHANNEL",
    "LightBlockRequestMessage",
    "LightBlockResponseMessage",
    "PARAMS_CHANNEL",
    "ParamsRequestMessage",
    "ParamsResponseMessage",
    "SNAPSHOT_CHANNEL",
    "SnapshotsRequestMessage",
    "SnapshotsResponseMessage",
    "StatesyncCodec",
    "StatesyncReactor",
    "SyncError",
    "statesync_channel_descriptors",
]
