"""State sync wire messages (channels 0x60-0x63).

reference: proto/tendermint/statesync/types.pb.go — Message oneof:
snapshots_request=1, snapshots_response=2, chunk_request=3,
chunk_response=4, light_block_request=5, light_block_response=6,
params_request=7, params_response=8.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..encoding.proto import FieldReader, ProtoWriter
from ..types.light import LightBlock

__all__ = [
    "SnapshotsRequestMessage",
    "SnapshotsResponseMessage",
    "ChunkRequestMessage",
    "ChunkResponseMessage",
    "LightBlockRequestMessage",
    "LightBlockResponseMessage",
    "ParamsRequestMessage",
    "ParamsResponseMessage",
    "StatesyncCodec",
]


@dataclass
class SnapshotsRequestMessage:
    def to_proto(self) -> bytes:
        return b""

    @classmethod
    def from_proto(cls, data: bytes) -> "SnapshotsRequestMessage":
        return cls()


@dataclass
class SnapshotsResponseMessage:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.height)
        w.uint(2, self.format)
        w.uint(3, self.chunks)
        w.bytes(4, self.hash)
        w.bytes(5, self.metadata)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "SnapshotsResponseMessage":
        r = FieldReader(data)
        return cls(
            height=r.uint(1), format=r.uint(2), chunks=r.uint(3),
            hash=r.bytes(4), metadata=r.bytes(5),
        )


@dataclass
class ChunkRequestMessage:
    height: int = 0
    format: int = 0
    index: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.height)
        w.uint(2, self.format)
        w.uint(3, self.index)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ChunkRequestMessage":
        r = FieldReader(data)
        return cls(height=r.uint(1), format=r.uint(2), index=r.uint(3))


@dataclass
class ChunkResponseMessage:
    height: int = 0
    format: int = 0
    index: int = 0
    chunk: bytes = b""
    missing: bool = False

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.height)
        w.uint(2, self.format)
        w.uint(3, self.index)
        w.bytes(4, self.chunk)
        w.bool(5, self.missing)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ChunkResponseMessage":
        r = FieldReader(data)
        return cls(
            height=r.uint(1), format=r.uint(2), index=r.uint(3),
            chunk=r.bytes(4), missing=r.bool(5),
        )


@dataclass
class LightBlockRequestMessage:
    height: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.height)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightBlockRequestMessage":
        return cls(height=FieldReader(data).uint(1))


@dataclass
class LightBlockResponseMessage:
    light_block: Optional[LightBlock] = None

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(
            1, self.light_block.to_proto() if self.light_block else None
        )
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightBlockResponseMessage":
        b = FieldReader(data).get(1)
        return cls(
            light_block=LightBlock.from_proto(b) if b else None
        )


@dataclass
class ParamsRequestMessage:
    height: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.height)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ParamsRequestMessage":
        return cls(height=FieldReader(data).uint(1))


@dataclass
class ParamsResponseMessage:
    height: int = 0
    consensus_params: bytes = b""  # proto-encoded ConsensusParams

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.uint(1, self.height)
        w.message(2, self.consensus_params)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ParamsResponseMessage":
        r = FieldReader(data)
        return cls(height=r.uint(1), consensus_params=r.get(2) or b"")


_FIELDS = {
    1: SnapshotsRequestMessage,
    2: SnapshotsResponseMessage,
    3: ChunkRequestMessage,
    4: ChunkResponseMessage,
    5: LightBlockRequestMessage,
    6: LightBlockResponseMessage,
    7: ParamsRequestMessage,
    8: ParamsResponseMessage,
}
_FIELD_OF = {cls: num for num, cls in _FIELDS.items()}


class StatesyncCodec:
    @staticmethod
    def encode(msg) -> bytes:
        num = _FIELD_OF.get(type(msg))
        if num is None:
            raise TypeError(f"unknown statesync message {type(msg).__name__}")
        w = ProtoWriter()
        w.message(num, msg.to_proto())
        return w.finish()

    @staticmethod
    def decode(data: bytes):
        r = FieldReader(data)
        for num, cls in _FIELDS.items():
            body = r.get(num)
            if body is not None:
                return cls.from_proto(body)
        raise ValueError("empty or unknown statesync Message envelope")
