"""KVStore — the canonical test application.

reference: abci/example/kvstore/kvstore.go (+ persistent_kvstore.go for
validator updates). Transactions are `key=value` byte strings (a bare tx
`t` is stored as `t=t`); validator-update txs are
`val:<hex pubkey>!<power>` (reference: persistent_kvstore.go:190-209).

The app hash is the SHA-256 merkle root over the sorted (key, value)
pairs — a real commitment (the reference's kvstore hashes only its size;
ours lets light-client / query proofs be exercised end-to-end).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..crypto.merkle import hash_from_byte_slices
from . import types as T

__all__ = ["KVStoreApplication"]

VALIDATOR_TX_PREFIX = "val:"
_SNAPSHOT_CHUNK = 1 << 16


class KVStoreApplication(T.Application):
    def __init__(
        self, retain_blocks: int = 0, snapshot_interval: int = 0
    ) -> None:
        self.state: Dict[bytes, bytes] = {}
        self.height = 0
        self.app_hash = b""
        self.retain_blocks = retain_blocks
        # >0: advertise a state-sync snapshot every N heights (the
        # reference's e2e app shape, test/e2e/app/snapshots.go)
        self.snapshot_interval = snapshot_interval
        self.validator_set: Dict[str, T.ValidatorUpdate] = {}  # hex(pk) → update
        self._staged_updates: List[T.ValidatorUpdate] = []
        self._snapshots: Dict[Tuple[int, int], bytes] = {}  # (height, format)
        self._restoring: Optional[bytearray] = None
        self._restore_chunks_expected = 0
        self._restore_chunks_applied = 0

    # -- deterministic commitment --

    def _compute_app_hash(self) -> bytes:
        if not self.state and not self.validator_set:
            return b""
        leaves = [k + b"=" + v for k, v in sorted(self.state.items())]
        leaves += [
            f"val:{pk}!{vu.power}".encode()
            for pk, vu in sorted(self.validator_set.items())
        ]
        return hash_from_byte_slices(leaves)

    # -- Info/Query --

    def info(self, req: T.RequestInfo) -> T.ResponseInfo:
        return T.ResponseInfo(
            data=json.dumps({"size": len(self.state)}),
            version="kvstore/1",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def query(self, req: T.RequestQuery) -> T.ResponseQuery:
        if req.path == "/val":
            vu = self.validator_set.get(req.data.decode(), None)
            power = vu.power if vu else 0
            return T.ResponseQuery(key=req.data, value=str(power).encode())
        value = self.state.get(req.data)
        if value is None:
            return T.ResponseQuery(key=req.data, log="does not exist")
        return T.ResponseQuery(key=req.data, value=value, log="exists")

    # -- Mempool --

    def check_tx(self, req: T.RequestCheckTx) -> T.ResponseCheckTx:
        tx = req.tx
        if tx.startswith(VALIDATOR_TX_PREFIX.encode()):
            ok, err = _parse_validator_tx(tx)
            if ok is None:
                return T.ResponseCheckTx(code=1, log=err)
        return T.ResponseCheckTx(gas_wanted=1)

    # -- Consensus --

    def init_chain(self, req: T.RequestInitChain) -> T.ResponseInitChain:
        for vu in req.validators:
            self.validator_set[vu.pub_key.data.hex()] = vu
        return T.ResponseInitChain(app_hash=self._compute_app_hash())

    def begin_block(self, req: T.RequestBeginBlock) -> T.ResponseBeginBlock:
        self._staged_updates = []
        return T.ResponseBeginBlock()

    def deliver_tx(self, req: T.RequestDeliverTx) -> T.ResponseDeliverTx:
        tx = req.tx
        if tx.startswith(VALIDATOR_TX_PREFIX.encode()):
            vu, err = _parse_validator_tx(tx)
            if vu is None:
                return T.ResponseDeliverTx(code=1, log=err)
            self._staged_updates.append(vu)
            if vu.power == 0:
                self.validator_set.pop(vu.pub_key.data.hex(), None)
            else:
                self.validator_set[vu.pub_key.data.hex()] = vu
            return T.ResponseDeliverTx(
                events=(
                    T.Event(
                        type="val_update",
                        attributes=(
                            T.EventAttribute(
                                b"pubkey", vu.pub_key.data.hex().encode(), True
                            ),
                        ),
                    ),
                )
            )
        key, sep, value = tx.partition(b"=")
        if not sep:
            value = key
        self.state[key] = value
        return T.ResponseDeliverTx(
            events=(
                T.Event(
                    type="app",
                    attributes=(
                        T.EventAttribute(b"creator", b"kvstore", True),
                        T.EventAttribute(b"key", key, True),
                    ),
                ),
            )
        )

    def end_block(self, req: T.RequestEndBlock) -> T.ResponseEndBlock:
        return T.ResponseEndBlock(validator_updates=tuple(self._staged_updates))

    def commit(self) -> T.ResponseCommit:
        self.height += 1
        self.app_hash = self._compute_app_hash()
        if (
            self.snapshot_interval
            and self.height % self.snapshot_interval == 0
        ):
            self.take_snapshot()
        retain = 0
        if self.retain_blocks and self.height >= self.retain_blocks:
            retain = self.height - self.retain_blocks + 1
        return T.ResponseCommit(data=self.app_hash, retain_height=retain)

    # -- State sync --

    def take_snapshot(self) -> T.Snapshot:
        """Serialize current state into chunks, advertise it."""
        blob = json.dumps(
            {
                "height": self.height,
                "state": {k.hex(): v.hex() for k, v in sorted(self.state.items())},
                "vals": {
                    pk: vu.power for pk, vu in sorted(self.validator_set.items())
                },
            },
            sort_keys=True,
        ).encode()
        chunks = max(1, (len(blob) + _SNAPSHOT_CHUNK - 1) // _SNAPSHOT_CHUNK)
        self._snapshots[(self.height, 1)] = blob
        while len(self._snapshots) > 4:  # bounded retention
            del self._snapshots[min(self._snapshots)]
        return T.Snapshot(
            height=self.height,
            format=1,
            chunks=chunks,
            hash=hash_from_byte_slices([blob]),
        )

    def list_snapshots(self, req: T.RequestListSnapshots) -> T.ResponseListSnapshots:
        snaps = []
        for (height, fmt), blob in sorted(self._snapshots.items()):
            chunks = max(1, (len(blob) + _SNAPSHOT_CHUNK - 1) // _SNAPSHOT_CHUNK)
            snaps.append(
                T.Snapshot(
                    height=height,
                    format=fmt,
                    chunks=chunks,
                    hash=hash_from_byte_slices([blob]),
                )
            )
        return T.ResponseListSnapshots(snapshots=tuple(snaps))

    def offer_snapshot(self, req: T.RequestOfferSnapshot) -> T.ResponseOfferSnapshot:
        if req.snapshot is None or req.snapshot.format != 1:
            return T.ResponseOfferSnapshot(result=T.OFFER_SNAPSHOT_REJECT_FORMAT)
        self._restoring = bytearray()
        self._restore_chunks_expected = req.snapshot.chunks
        self._restore_chunks_applied = 0
        return T.ResponseOfferSnapshot(result=T.OFFER_SNAPSHOT_ACCEPT)

    def load_snapshot_chunk(
        self, req: T.RequestLoadSnapshotChunk
    ) -> T.ResponseLoadSnapshotChunk:
        blob = self._snapshots.get((req.height, req.format))
        if blob is None:
            return T.ResponseLoadSnapshotChunk()
        start = req.chunk * _SNAPSHOT_CHUNK
        return T.ResponseLoadSnapshotChunk(chunk=blob[start : start + _SNAPSHOT_CHUNK])

    def apply_snapshot_chunk(
        self, req: T.RequestApplySnapshotChunk
    ) -> T.ResponseApplySnapshotChunk:
        if self._restoring is None:
            return T.ResponseApplySnapshotChunk(result=T.APPLY_CHUNK_ABORT)
        self._restoring += req.chunk
        self._restore_chunks_applied += 1
        try:
            doc = json.loads(bytes(self._restoring))
        except ValueError:
            if self._restore_chunks_applied >= self._restore_chunks_expected:
                # all chunks in but the blob won't parse — corrupt snapshot
                self._restoring = None
                return T.ResponseApplySnapshotChunk(
                    result=T.APPLY_CHUNK_REJECT_SNAPSHOT
                )
            return T.ResponseApplySnapshotChunk(result=T.APPLY_CHUNK_ACCEPT)
        # full blob assembled
        self.height = doc["height"]
        self.state = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in doc["state"].items()
        }
        self.validator_set = {
            pk: T.ValidatorUpdate(
                pub_key=T.PubKey("ed25519", bytes.fromhex(pk)), power=power
            )
            for pk, power in doc["vals"].items()
        }
        self.app_hash = self._compute_app_hash()
        self._restoring = None
        return T.ResponseApplySnapshotChunk(result=T.APPLY_CHUNK_ACCEPT)


def _parse_validator_tx(tx: bytes):
    """`val:<hex pubkey>!<power>` → (ValidatorUpdate, "") or (None, err)."""
    body = tx[len(VALIDATOR_TX_PREFIX) :].decode(errors="replace")
    pk_hex, sep, power_s = body.partition("!")
    if not sep:
        return None, "expected val:<pubkey>!<power>"
    try:
        pk = bytes.fromhex(pk_hex)
    except ValueError:
        return None, f"pubkey {pk_hex!r} is not hex"
    try:
        power = int(power_s)
    except ValueError:
        return None, f"power {power_s!r} is not an int"
    if power < 0:
        return None, "power must be >= 0"
    return T.ValidatorUpdate(pub_key=T.PubKey("ed25519", pk), power=power), ""
