"""Proxy mux — four logical ABCI connections to one application.

reference: internal/proxy/multi_app_conn.go:24-60. The four connections
(consensus, mempool, query, snapshot) are the concurrency boundary between
subsystems: the mempool can CheckTx while consensus delivers a block, each
on its own serialized connection.
"""

from __future__ import annotations

from ..libs.service import Service
from .client import ABCIClient, ClientCreator

__all__ = ["AppConns"]


class AppConns(Service):
    """Owns the four clients; start/stop as a unit
    (reference: internal/proxy/multi_app_conn.go:52-55, OnStart :86)."""

    def __init__(self, creator: ClientCreator) -> None:
        super().__init__(name="proxy")
        self.consensus: ABCIClient = creator()
        self.mempool: ABCIClient = creator()
        self.query: ABCIClient = creator()
        self.snapshot: ABCIClient = creator()

    async def on_start(self) -> None:
        for conn in (self.query, self.snapshot, self.mempool, self.consensus):
            await conn.start()
        # liveness check, mirroring proxy's Echo on start
        await self.query.echo("ping")

    async def on_stop(self) -> None:
        for conn in (self.consensus, self.mempool, self.snapshot, self.query):
            if conn.is_running:
                await conn.stop()
