"""ABCI clients — local (in-process) and socket (out-of-process).

reference: abci/client/client.go (Client iface), local_client.go
(mutex-serialized direct calls), socket_client.go (varint-framed async
request pipeline with FIFO response matching), creators.go:12-36.

All clients are asyncio-native: every method is a coroutine so the node's
reactors can await app calls without blocking the event loop; the local
client runs the (synchronous, deterministic) application inline under a
lock, mirroring the reference's mutex-serialized local client.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from ..encoding.proto import decode_varint, encode_varint
from ..libs.log import get_logger
from ..libs.service import Service
from . import types as T
from .codec import decode_response, encode_request

__all__ = ["ABCIClient", "LocalClient", "SocketClient", "ClientCreator"]


class ABCIClientError(Exception):
    pass


class ABCIClient(Service):
    """Async mirror of the Application interface plus echo/flush
    (reference: abci/client/client.go:24-54)."""

    async def echo(self, message: str) -> T.ResponseEcho:
        raise NotImplementedError

    async def flush(self) -> None:
        raise NotImplementedError

    async def info(self, req: T.RequestInfo) -> T.ResponseInfo:
        raise NotImplementedError

    async def query(self, req: T.RequestQuery) -> T.ResponseQuery:
        raise NotImplementedError

    async def check_tx(self, req: T.RequestCheckTx) -> T.ResponseCheckTx:
        raise NotImplementedError

    async def check_tx_batch(
        self, reqs: "list[T.RequestCheckTx]"
    ) -> "list[T.ResponseCheckTx]":
        """Validate a batch with one client round. Default: sequential
        awaits (any transport works); LocalClient folds the batch into
        one lock hold, SocketClient pipelines all frames before awaiting
        — the FPGA-verifier shape (batch, pipeline) applied to the
        admission path."""
        return [await self.check_tx(r) for r in reqs]

    async def init_chain(self, req: T.RequestInitChain) -> T.ResponseInitChain:
        raise NotImplementedError

    async def begin_block(self, req: T.RequestBeginBlock) -> T.ResponseBeginBlock:
        raise NotImplementedError

    async def deliver_tx(self, req: T.RequestDeliverTx) -> T.ResponseDeliverTx:
        raise NotImplementedError

    async def end_block(self, req: T.RequestEndBlock) -> T.ResponseEndBlock:
        raise NotImplementedError

    async def commit(self) -> T.ResponseCommit:
        raise NotImplementedError

    async def list_snapshots(
        self, req: T.RequestListSnapshots
    ) -> T.ResponseListSnapshots:
        raise NotImplementedError

    async def offer_snapshot(
        self, req: T.RequestOfferSnapshot
    ) -> T.ResponseOfferSnapshot:
        raise NotImplementedError

    async def load_snapshot_chunk(
        self, req: T.RequestLoadSnapshotChunk
    ) -> T.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    async def apply_snapshot_chunk(
        self, req: T.RequestApplySnapshotChunk
    ) -> T.ResponseApplySnapshotChunk:
        raise NotImplementedError


class _RequestForwardingClient(ABCIClient):
    """Per-method wrappers shared by clients that funnel every call
    through one async ``_request(req)`` (socket and gRPC transports) —
    a new ABCI method is added here once, not per transport."""

    async def _request(self, req):
        raise NotImplementedError

    async def echo(self, message: str) -> T.ResponseEcho:
        return await self._request(T.RequestEcho(message=message))

    async def flush(self) -> None:
        await self._request(T.RequestFlush())

    async def info(self, req):
        return await self._request(req)

    async def query(self, req):
        return await self._request(req)

    async def check_tx(self, req):
        return await self._request(req)

    async def check_tx_batch(self, reqs):
        return await self._request_batch(reqs)

    async def _request_batch(self, reqs):
        """Pipelined fallback: issue every request before awaiting any
        response. FIFO transports (socket) override to cork the writes."""
        return list(
            await asyncio.gather(*(self._request(r) for r in reqs))
        )

    async def init_chain(self, req):
        return await self._request(req)

    async def begin_block(self, req):
        return await self._request(req)

    async def deliver_tx(self, req):
        return await self._request(req)

    async def end_block(self, req):
        return await self._request(req)

    async def commit(self):
        return await self._request(T.RequestCommit())

    async def list_snapshots(self, req):
        return await self._request(req)

    async def offer_snapshot(self, req):
        return await self._request(req)

    async def load_snapshot_chunk(self, req):
        return await self._request(req)

    async def apply_snapshot_chunk(self, req):
        return await self._request(req)


class LocalClient(ABCIClient):
    """In-process client: direct calls serialized by one lock
    (reference: abci/client/local_client.go)."""

    def __init__(self, app: T.Application) -> None:
        super().__init__(name="abci.local")
        self.app = app
        self._lock = asyncio.Lock()

    async def _call(self, fn, *args):
        async with self._lock:
            return fn(*args)

    async def echo(self, message: str) -> T.ResponseEcho:
        return T.ResponseEcho(message=message)

    async def flush(self) -> None:
        return None

    async def info(self, req):
        return await self._call(self.app.info, req)

    async def query(self, req):
        return await self._call(self.app.query, req)

    async def check_tx(self, req):
        return await self._call(self.app.check_tx, req)

    async def check_tx_batch(self, reqs):
        # one lock acquisition for the whole batch: under high ingest
        # the per-call acquire/release (and the event-loop hop each one
        # implies) dominates the synchronous app work itself
        async with self._lock:
            return [self.app.check_tx(r) for r in reqs]

    async def init_chain(self, req):
        return await self._call(self.app.init_chain, req)

    async def begin_block(self, req):
        return await self._call(self.app.begin_block, req)

    async def deliver_tx(self, req):
        return await self._call(self.app.deliver_tx, req)

    async def end_block(self, req):
        return await self._call(self.app.end_block, req)

    async def commit(self):
        return await self._call(self.app.commit)

    async def list_snapshots(self, req):
        return await self._call(self.app.list_snapshots, req)

    async def offer_snapshot(self, req):
        return await self._call(self.app.offer_snapshot, req)

    async def load_snapshot_chunk(self, req):
        return await self._call(self.app.load_snapshot_chunk, req)

    async def apply_snapshot_chunk(self, req):
        return await self._call(self.app.apply_snapshot_chunk, req)


class SocketClient(_RequestForwardingClient):
    """Out-of-process client over a varint-framed byte stream.

    Requests are written in order; the server answers in order, so
    responses are matched FIFO (reference: abci/client/socket_client.go —
    reqQueue + reqSent matching, :118-180).
    """

    def __init__(self, address: str, must_connect: bool = True) -> None:
        super().__init__(name="abci.socket")
        self.address = address
        self.must_connect = must_connect
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: asyncio.Queue = asyncio.Queue()
        self._write_lock = asyncio.Lock()
        self._err: Optional[Exception] = None

    async def on_start(self) -> None:
        delay = 0.2
        while True:
            try:
                self._reader, self._writer = await _open(self.address)
                break
            except OSError as e:
                if self.must_connect:
                    raise
                self.logger.info("abci.socket dial failed; retrying", err=str(e))
                await asyncio.sleep(delay)
                delay = min(delay * 2, 3.0)
        self.spawn(self._recv_loop())

    async def on_stop(self) -> None:
        if self._writer is not None:
            self._writer.close()

    async def _recv_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                msg = await _read_delimited(self._reader)
                resp = decode_response(msg)
                if isinstance(resp, T.ResponseException):
                    raise ABCIClientError(f"abci app exception: {resp.error}")
                fut: asyncio.Future = await self._pending.get()
                if not fut.done():
                    fut.set_result(resp)
        except asyncio.CancelledError:
            self._err = ABCIClientError("client stopped")
            self._drain_pending(self._err)
            raise
        except Exception as e:  # any stream/codec failure kills the conn
            self._err = e
            # _request enqueues futures under _write_lock and re-checks _err
            # there, so taking the lock here closes the drain race.
            async with self._write_lock:
                self._drain_pending(e)

    def _drain_pending(self, err: Exception) -> None:
        while not self._pending.empty():
            fut = self._pending.get_nowait()
            if not fut.done():
                fut.set_exception(ABCIClientError(str(err)))

    async def _request(self, req):
        if self._writer is None:
            raise ABCIClientError("socket client not started")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._write_lock:
            if self._err is not None:
                raise ABCIClientError(str(self._err))
            await self._pending.put(fut)
            body = encode_request(req)
            self._writer.write(encode_varint(len(body)) + body)
            await self._writer.drain()
        return await fut

    async def _request_batch(self, reqs):
        """Cork the batch: all frames written (and their futures
        enqueued) under one _write_lock hold, one drain — the server
        sees a contiguous pipeline instead of lock-interleaved singles
        (reference: socket_client.go queues requests the same way)."""
        if not reqs:
            return []
        if self._writer is None:
            raise ABCIClientError("socket client not started")
        loop = asyncio.get_running_loop()
        futs: list[asyncio.Future] = []
        async with self._write_lock:
            if self._err is not None:
                raise ABCIClientError(str(self._err))
            buf = bytearray()
            for req in reqs:
                fut = loop.create_future()
                await self._pending.put(fut)
                futs.append(fut)
                body = encode_request(req)
                buf += encode_varint(len(body)) + body
            self._writer.write(bytes(buf))
            await self._writer.drain()
        return list(await asyncio.gather(*futs))


async def _open(address: str):
    """Dial `tcp://host:port` or `unix://path`."""
    if address.startswith("unix://"):
        return await asyncio.open_unix_connection(address[len("unix://") :])
    hostport = address[len("tcp://") :] if address.startswith("tcp://") else address
    host, _, port = hostport.rpartition(":")
    return await asyncio.open_connection(host or "127.0.0.1", int(port))


async def _read_delimited(reader: asyncio.StreamReader) -> bytes:
    """Read one varint-length-delimited message."""
    shift = 0
    n = 0
    while True:
        b = (await reader.readexactly(1))[0]
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ABCIClientError("varint overflow")
    if n > 64 * 1024 * 1024:
        raise ABCIClientError(f"message too large: {n}")
    return await reader.readexactly(n)


# reference: abci/client/creators.go:12-36
ClientCreator = Callable[[], ABCIClient]


def local_creator(app: T.Application) -> ClientCreator:
    return lambda: LocalClient(app)


def socket_creator(address: str, must_connect: bool = False) -> ClientCreator:
    return lambda: SocketClient(address, must_connect=must_connect)
