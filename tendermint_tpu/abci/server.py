"""ABCI socket server — serves an Application to out-of-process nodes.

reference: abci/server/socket_server.go (varint-framed request loop per
connection) and abci/server/server.go (NewServer switch on transport).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..encoding.proto import encode_varint
from ..libs.service import Service
from . import types as T
from .client import _read_delimited
from .codec import decode_request, encode_response

__all__ = ["SocketServer"]


class SocketServer(Service):
    def __init__(self, address: str, app: T.Application) -> None:
        super().__init__(name="abci.server")
        self.address = address
        self.app = app
        self._server: Optional[asyncio.base_events.Server] = None
        # One lock for the app across all connections: the reference's apps
        # guard internal state themselves; here the server is the guard.
        self._app_lock = asyncio.Lock()

    async def on_start(self) -> None:
        if self.address.startswith("unix://"):
            self._server = await asyncio.start_unix_server(
                self._handle, self.address[len("unix://") :]
            )
        else:
            hostport = (
                self.address[len("tcp://") :]
                if self.address.startswith("tcp://")
                else self.address
            )
            host, _, port = hostport.rpartition(":")
            self._server = await asyncio.start_server(
                self._handle, host or "127.0.0.1", int(port)
            )

    @property
    def listen_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                msg = await _read_delimited(reader)
                req = decode_request(msg)
                try:
                    resp = await self._dispatch(req)
                except Exception as e:  # app bug → exception response
                    self.logger.exception("abci app raised")
                    resp = T.ResponseException(error=str(e))
                body = encode_response(resp)
                writer.write(encode_varint(len(body)) + body)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, req):
        if isinstance(req, T.RequestEcho):
            return T.ResponseEcho(message=req.message)
        if isinstance(req, T.RequestFlush):
            return T.ResponseFlush()
        async with self._app_lock:
            return dispatch_to_app(self.app, req)


def dispatch_to_app(app: T.Application, req):
    """Application method dispatch shared by the socket and gRPC
    servers (echo/flush are transport-level and stay in each server)."""
    if isinstance(req, T.RequestInfo):
        return app.info(req)
    if isinstance(req, T.RequestQuery):
        return app.query(req)
    if isinstance(req, T.RequestCheckTx):
        return app.check_tx(req)
    if isinstance(req, T.RequestInitChain):
        return app.init_chain(req)
    if isinstance(req, T.RequestBeginBlock):
        return app.begin_block(req)
    if isinstance(req, T.RequestDeliverTx):
        return app.deliver_tx(req)
    if isinstance(req, T.RequestEndBlock):
        return app.end_block(req)
    if isinstance(req, T.RequestCommit):
        return app.commit()
    if isinstance(req, T.RequestListSnapshots):
        return app.list_snapshots(req)
    if isinstance(req, T.RequestOfferSnapshot):
        return app.offer_snapshot(req)
    if isinstance(req, T.RequestLoadSnapshotChunk):
        return app.load_snapshot_chunk(req)
    if isinstance(req, T.RequestApplySnapshotChunk):
        return app.apply_snapshot_chunk(req)
    raise ValueError(f"unknown ABCI request {type(req).__name__}")
