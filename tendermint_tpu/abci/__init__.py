"""ABCI — the application blockchain interface (reference: abci/)."""

from . import types  # noqa: F401
from .client import (  # noqa: F401
    ABCIClient,
    LocalClient,
    SocketClient,
    local_creator,
    socket_creator,
)
from .kvstore import KVStoreApplication  # noqa: F401
from .proxy import AppConns  # noqa: F401
from .server import SocketServer  # noqa: F401
from .types import Application, BaseApplication  # noqa: F401
