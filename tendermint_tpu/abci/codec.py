"""ABCI wire codec — deterministic protobuf encoding of Request/Response.

Oneof field numbers mirror the reference's generated types
(reference: abci/types/types.pb.go:218-261 Request, :1226-1262 Response) so
the socket protocol keeps the same envelope layout: varint-length-delimited
Request/Response messages, each a oneof over the method payloads
(reference: abci/client/socket_client.go, abci/server/socket_server.go).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from ..encoding.proto import FieldReader, ProtoWriter, iter_fields
from ..types.params import ConsensusParams
from . import types as T

__all__ = ["encode_request", "decode_request", "encode_response", "decode_response"]


# ---------------------------------------------------------------------------
# Payload encoders (inner messages)


def _enc_echo(msg) -> bytes:
    w = ProtoWriter()
    w.string(1, msg.message)
    return w.finish()


def _enc_empty(_msg) -> bytes:
    return b""


def _enc_event_attr(a: T.EventAttribute) -> bytes:
    w = ProtoWriter()
    w.bytes(1, a.key)
    w.bytes(2, a.value)
    w.bool(3, a.index)
    return w.finish()


def _enc_event(e: T.Event) -> bytes:
    w = ProtoWriter()
    w.string(1, e.type)
    for a in e.attributes:
        w.message(2, _enc_event_attr(a))
    return w.finish()


def _dec_event(data: bytes) -> T.Event:
    etype = ""
    attrs = []
    for f, _wt, v in iter_fields(data):
        if f == 1:
            if not isinstance(v, bytes):
                # wire-type flip: sanctioned parse error, not an
                # AttributeError escaping the handler stack
                raise ValueError("Event.type: expected length-delimited")
            etype = v.decode()
        elif f == 2:
            r = FieldReader(v)
            attrs.append(
                T.EventAttribute(
                    key=r.bytes(1), value=r.bytes(2), index=bool(r.uint(3))
                )
            )
    return T.Event(type=etype, attributes=tuple(attrs))


def _enc_pub_key(pk: T.PubKey) -> bytes:
    # oneof sum — ed25519=1, secp256k1=2, sr25519=3
    # (reference: proto/tendermint/crypto/keys.pb.go)
    w = ProtoWriter()
    fieldno = {"ed25519": 1, "secp256k1": 2, "sr25519": 3}[pk.key_type]
    w.bytes(fieldno, pk.data)
    return w.finish()


def _dec_pub_key(data: bytes) -> T.PubKey:
    names = {1: "ed25519", 2: "secp256k1", 3: "sr25519"}
    for f, _wt, v in iter_fields(data):
        if f in names:
            return T.PubKey(key_type=names[f], data=v)
    raise ValueError("empty ABCI PubKey")


def _enc_val_update(vu: T.ValidatorUpdate) -> bytes:
    w = ProtoWriter()
    w.message(1, _enc_pub_key(vu.pub_key))
    w.int(2, vu.power)
    return w.finish()


def _dec_val_update(data: bytes) -> T.ValidatorUpdate:
    r = FieldReader(data)
    return T.ValidatorUpdate(
        pub_key=_dec_pub_key(r.bytes(1)), power=r.int64(2)
    )


def _enc_validator(v: T.Validator) -> bytes:
    w = ProtoWriter()
    w.bytes(1, v.address)
    w.int(3, v.power)  # field 2 unused, matching reference Validator
    return w.finish()


def _dec_validator(data: bytes) -> T.Validator:
    r = FieldReader(data)
    return T.Validator(address=r.bytes(1), power=r.int64(3))


def _enc_vote_info(vi: T.VoteInfo) -> bytes:
    w = ProtoWriter()
    w.message(1, _enc_validator(vi.validator))
    w.bool(2, vi.signed_last_block)
    return w.finish()


def _enc_commit_info(ci: T.LastCommitInfo) -> bytes:
    w = ProtoWriter()
    w.int(1, ci.round)
    for vi in ci.votes:
        w.message(2, _enc_vote_info(vi))
    return w.finish()


def _dec_commit_info(data: bytes) -> T.LastCommitInfo:
    rnd = 0
    votes = []
    for f, _wt, v in iter_fields(data):
        if f == 1:
            rnd = int(v)
        elif f == 2:
            r = FieldReader(v)
            votes.append(
                T.VoteInfo(
                    validator=_dec_validator(r.bytes(1)),
                    signed_last_block=bool(r.uint(2)),
                )
            )
    return T.LastCommitInfo(round=rnd, votes=tuple(votes))


def _enc_misbehavior(m: T.Misbehavior) -> bytes:
    w = ProtoWriter()
    w.int(1, m.kind)
    w.message(2, _enc_validator(m.validator))
    w.int(3, m.height)
    w.sfixed64(4, m.time_ns)
    w.int(5, m.total_voting_power)
    return w.finish()


def _dec_misbehavior(data: bytes) -> T.Misbehavior:
    r = FieldReader(data)
    return T.Misbehavior(
        kind=r.int64(1),
        validator=_dec_validator(r.bytes(2, b"")),
        height=r.int64(3),
        time_ns=r.sfixed64(4),
        total_voting_power=r.int64(5),
    )


def _enc_snapshot(s: T.Snapshot) -> bytes:
    w = ProtoWriter()
    w.uint(1, s.height)
    w.uint(2, s.format)
    w.uint(3, s.chunks)
    w.bytes(4, s.hash)
    w.bytes(5, s.metadata)
    return w.finish()


def _dec_snapshot(data: bytes) -> T.Snapshot:
    r = FieldReader(data)
    return T.Snapshot(
        height=r.uint(1),
        format=r.uint(2),
        chunks=r.uint(3),
        hash=r.bytes(4),
        metadata=r.bytes(5),
    )


# -- requests --


def _enc_req_info(m: T.RequestInfo) -> bytes:
    w = ProtoWriter()
    w.string(1, m.version)
    w.uint(2, m.block_version)
    w.uint(3, m.p2p_version)
    w.string(4, m.abci_version)
    return w.finish()


def _dec_req_info(data: bytes) -> T.RequestInfo:
    r = FieldReader(data)
    return T.RequestInfo(
        version=r.bytes(1, b"").decode(),
        block_version=r.uint(2),
        p2p_version=r.uint(3),
        abci_version=r.bytes(4, b"").decode(),
    )


def _enc_req_init_chain(m: T.RequestInitChain) -> bytes:
    w = ProtoWriter()
    w.sfixed64(1, m.time_ns)
    w.string(2, m.chain_id)
    if m.consensus_params is not None:
        w.message(3, m.consensus_params.to_proto())
    for vu in m.validators:
        w.message(4, _enc_val_update(vu))
    w.bytes(5, m.app_state_bytes)
    w.int(6, m.initial_height)
    return w.finish()


def _dec_req_init_chain(data: bytes) -> T.RequestInitChain:
    params = None
    vals = []
    r = FieldReader(data)
    if r.get(3) is not None:
        params = ConsensusParams.from_proto(r.bytes(3))
    for v in r.get_all(4):
        vals.append(_dec_val_update(v))
    return T.RequestInitChain(
        time_ns=r.sfixed64(1),
        chain_id=r.bytes(2, b"").decode(),
        consensus_params=params,
        validators=tuple(vals),
        app_state_bytes=r.bytes(5),
        initial_height=r.int64(6),
    )


def _enc_req_query(m: T.RequestQuery) -> bytes:
    w = ProtoWriter()
    w.bytes(1, m.data)
    w.string(2, m.path)
    w.int(3, m.height)
    w.bool(4, m.prove)
    return w.finish()


def _dec_req_query(data: bytes) -> T.RequestQuery:
    r = FieldReader(data)
    return T.RequestQuery(
        data=r.bytes(1),
        path=r.bytes(2, b"").decode(),
        height=r.int64(3),
        prove=bool(r.uint(4)),
    )


def _enc_req_begin_block(m: T.RequestBeginBlock) -> bytes:
    w = ProtoWriter()
    w.bytes(1, m.hash)
    w.message(2, m.header_bytes)
    w.message(3, _enc_commit_info(m.last_commit_info))
    for ev in m.byzantine_validators:
        w.message(4, _enc_misbehavior(ev))
    return w.finish()


def _dec_req_begin_block(data: bytes) -> T.RequestBeginBlock:
    r = FieldReader(data)
    return T.RequestBeginBlock(
        hash=r.bytes(1),
        header_bytes=r.bytes(2),
        last_commit_info=_dec_commit_info(r.bytes(3)),
        byzantine_validators=tuple(
            _dec_misbehavior(v) for v in r.get_all(4)
        ),
    )


def _enc_req_check_tx(m: T.RequestCheckTx) -> bytes:
    w = ProtoWriter()
    w.bytes(1, m.tx)
    w.int(2, m.type)
    return w.finish()


def _dec_req_check_tx(data: bytes) -> T.RequestCheckTx:
    r = FieldReader(data)
    return T.RequestCheckTx(tx=r.bytes(1), type=r.int64(2))


def _enc_req_deliver_tx(m: T.RequestDeliverTx) -> bytes:
    w = ProtoWriter()
    w.bytes(1, m.tx)
    return w.finish()


def _dec_req_deliver_tx(data: bytes) -> T.RequestDeliverTx:
    return T.RequestDeliverTx(tx=FieldReader(data).bytes(1))


def _enc_req_end_block(m: T.RequestEndBlock) -> bytes:
    w = ProtoWriter()
    w.int(1, m.height)
    return w.finish()


def _dec_req_end_block(data: bytes) -> T.RequestEndBlock:
    return T.RequestEndBlock(height=FieldReader(data).int64(1))


def _enc_req_offer_snapshot(m: T.RequestOfferSnapshot) -> bytes:
    w = ProtoWriter()
    if m.snapshot is not None:
        w.message(1, _enc_snapshot(m.snapshot))
    w.bytes(2, m.app_hash)
    return w.finish()


def _dec_req_offer_snapshot(data: bytes) -> T.RequestOfferSnapshot:
    r = FieldReader(data)
    snap = None
    if r.get(1) is not None:
        snap = _dec_snapshot(r.bytes(1))
    return T.RequestOfferSnapshot(snapshot=snap, app_hash=r.bytes(2))


def _enc_req_load_chunk(m: T.RequestLoadSnapshotChunk) -> bytes:
    w = ProtoWriter()
    w.uint(1, m.height)
    w.uint(2, m.format)
    w.uint(3, m.chunk)
    return w.finish()


def _dec_req_load_chunk(data: bytes) -> T.RequestLoadSnapshotChunk:
    r = FieldReader(data)
    return T.RequestLoadSnapshotChunk(
        height=r.uint(1), format=r.uint(2), chunk=r.uint(3)
    )


def _enc_req_apply_chunk(m: T.RequestApplySnapshotChunk) -> bytes:
    w = ProtoWriter()
    w.uint(1, m.index)
    w.bytes(2, m.chunk)
    w.string(3, m.sender)
    return w.finish()


def _dec_req_apply_chunk(data: bytes) -> T.RequestApplySnapshotChunk:
    r = FieldReader(data)
    return T.RequestApplySnapshotChunk(
        index=r.uint(1), chunk=r.bytes(2), sender=r.bytes(3, b"").decode()
    )


# -- responses --


def _enc_resp_exception(m: T.ResponseException) -> bytes:
    w = ProtoWriter()
    w.string(1, m.error)
    return w.finish()


def _enc_resp_info(m: T.ResponseInfo) -> bytes:
    w = ProtoWriter()
    w.string(1, m.data)
    w.string(2, m.version)
    w.uint(3, m.app_version)
    w.int(4, m.last_block_height)
    w.bytes(5, m.last_block_app_hash)
    return w.finish()


def _dec_resp_info(data: bytes) -> T.ResponseInfo:
    r = FieldReader(data)
    return T.ResponseInfo(
        data=r.bytes(1, b"").decode(),
        version=r.bytes(2, b"").decode(),
        app_version=r.uint(3),
        last_block_height=r.int64(4),
        last_block_app_hash=r.bytes(5),
    )


def _enc_resp_init_chain(m: T.ResponseInitChain) -> bytes:
    w = ProtoWriter()
    if m.consensus_params is not None:
        w.message(1, m.consensus_params.to_proto())
    for vu in m.validators:
        w.message(2, _enc_val_update(vu))
    w.bytes(3, m.app_hash)
    return w.finish()


def _dec_resp_init_chain(data: bytes) -> T.ResponseInitChain:
    r = FieldReader(data)
    params = None
    if r.get(1) is not None:
        params = ConsensusParams.from_proto(r.bytes(1))
    return T.ResponseInitChain(
        consensus_params=params,
        validators=tuple(_dec_val_update(v) for v in r.get_all(2)),
        app_hash=r.bytes(3),
    )


def _enc_resp_query(m: T.ResponseQuery) -> bytes:
    w = ProtoWriter()
    w.uint(1, m.code)
    w.string(3, m.log)
    w.string(4, m.info)
    w.int(5, m.index)
    w.bytes(6, m.key)
    w.bytes(7, m.value)
    # field 8 proof_ops omitted from wire for now (host-local clients pass
    # the object through; socket apps requiring proofs encode their own)
    w.int(9, m.height)
    w.string(10, m.codespace)
    return w.finish()


def _dec_resp_query(data: bytes) -> T.ResponseQuery:
    r = FieldReader(data)
    return T.ResponseQuery(
        code=r.uint(1),
        log=r.bytes(3, b"").decode(),
        info=r.bytes(4, b"").decode(),
        index=r.int64(5),
        key=r.bytes(6),
        value=r.bytes(7),
        height=r.int64(9),
        codespace=r.bytes(10, b"").decode(),
    )


def _enc_resp_begin_block(m: T.ResponseBeginBlock) -> bytes:
    w = ProtoWriter()
    for e in m.events:
        w.message(1, _enc_event(e))
    return w.finish()


def _dec_resp_begin_block(data: bytes) -> T.ResponseBeginBlock:
    return T.ResponseBeginBlock(
        events=tuple(_dec_event(v) for _f, _wt, v in iter_fields(data) if _f == 1)
    )


def _enc_resp_check_tx(m: T.ResponseCheckTx) -> bytes:
    w = ProtoWriter()
    w.uint(1, m.code)
    w.bytes(2, m.data)
    w.string(3, m.log)
    w.string(4, m.info)
    w.int(5, m.gas_wanted)
    w.int(6, m.gas_used)
    for e in m.events:
        w.message(7, _enc_event(e))
    w.string(8, m.codespace)
    w.string(9, m.sender)
    w.int(10, m.priority)
    w.string(11, m.mempool_error)
    return w.finish()


def _dec_resp_check_tx(data: bytes) -> T.ResponseCheckTx:
    r = FieldReader(data)
    return T.ResponseCheckTx(
        code=r.uint(1),
        data=r.bytes(2),
        log=r.bytes(3, b"").decode(),
        info=r.bytes(4, b"").decode(),
        gas_wanted=r.int64(5),
        gas_used=r.int64(6),
        events=tuple(_dec_event(v) for v in r.get_all(7)),
        codespace=r.bytes(8, b"").decode(),
        sender=r.bytes(9, b"").decode(),
        priority=r.int64(10),
        mempool_error=r.bytes(11, b"").decode(),
    )


def _enc_resp_deliver_tx(m: T.ResponseDeliverTx) -> bytes:
    w = ProtoWriter()
    w.uint(1, m.code)
    w.bytes(2, m.data)
    w.string(3, m.log)
    w.string(4, m.info)
    w.int(5, m.gas_wanted)
    w.int(6, m.gas_used)
    for e in m.events:
        w.message(7, _enc_event(e))
    w.string(8, m.codespace)
    return w.finish()


def _dec_resp_deliver_tx(data: bytes) -> T.ResponseDeliverTx:
    r = FieldReader(data)
    return T.ResponseDeliverTx(
        code=r.uint(1),
        data=r.bytes(2),
        log=r.bytes(3, b"").decode(),
        info=r.bytes(4, b"").decode(),
        gas_wanted=r.int64(5),
        gas_used=r.int64(6),
        events=tuple(_dec_event(v) for v in r.get_all(7)),
        codespace=r.bytes(8, b"").decode(),
    )


def _enc_resp_end_block(m: T.ResponseEndBlock) -> bytes:
    w = ProtoWriter()
    for vu in m.validator_updates:
        w.message(1, _enc_val_update(vu))
    if m.consensus_param_updates is not None:
        w.message(2, m.consensus_param_updates.to_proto())
    for e in m.events:
        w.message(3, _enc_event(e))
    return w.finish()


def _dec_resp_end_block(data: bytes) -> T.ResponseEndBlock:
    r = FieldReader(data)
    params = None
    if r.get(2) is not None:
        params = ConsensusParams.from_proto(r.bytes(2))
    return T.ResponseEndBlock(
        validator_updates=tuple(_dec_val_update(v) for v in r.get_all(1)),
        consensus_param_updates=params,
        events=tuple(_dec_event(v) for v in r.get_all(3)),
    )


def _enc_resp_commit(m: T.ResponseCommit) -> bytes:
    w = ProtoWriter()
    w.bytes(2, m.data)
    w.int(3, m.retain_height)
    return w.finish()


def _dec_resp_commit(data: bytes) -> T.ResponseCommit:
    r = FieldReader(data)
    return T.ResponseCommit(data=r.bytes(2), retain_height=r.int64(3))


def _enc_resp_list_snapshots(m: T.ResponseListSnapshots) -> bytes:
    w = ProtoWriter()
    for s in m.snapshots:
        w.message(1, _enc_snapshot(s))
    return w.finish()


def _dec_resp_list_snapshots(data: bytes) -> T.ResponseListSnapshots:
    return T.ResponseListSnapshots(
        snapshots=tuple(
            _dec_snapshot(v) for f, _wt, v in iter_fields(data) if f == 1
        )
    )


def _enc_resp_offer_snapshot(m: T.ResponseOfferSnapshot) -> bytes:
    w = ProtoWriter()
    w.int(1, m.result)
    return w.finish()


def _dec_resp_offer_snapshot(data: bytes) -> T.ResponseOfferSnapshot:
    return T.ResponseOfferSnapshot(result=FieldReader(data).int64(1))


def _enc_resp_load_chunk(m: T.ResponseLoadSnapshotChunk) -> bytes:
    w = ProtoWriter()
    w.bytes(1, m.chunk)
    return w.finish()


def _dec_resp_load_chunk(data: bytes) -> T.ResponseLoadSnapshotChunk:
    return T.ResponseLoadSnapshotChunk(chunk=FieldReader(data).bytes(1))


def _enc_resp_apply_chunk(m: T.ResponseApplySnapshotChunk) -> bytes:
    from ..encoding.proto import encode_varint

    w = ProtoWriter()
    w.int(1, m.result)
    if m.refetch_chunks:  # packed repeated uint64 (zero indices must survive)
        w.bytes(2, b"".join(encode_varint(c) for c in m.refetch_chunks))
    for s in m.reject_senders:
        w.string(3, s)
    return w.finish()


def _dec_resp_apply_chunk(data: bytes) -> T.ResponseApplySnapshotChunk:
    from ..encoding.proto import decode_varint

    result = 0
    refetch = []
    reject = []
    for f, wt, v in iter_fields(data):
        if f == 1:
            result = int(v)
        elif f == 2:
            if wt == 2:  # packed
                off = 0
                while off < len(v):
                    c, off = decode_varint(v, off)
                    refetch.append(c)
            else:
                refetch.append(int(v))
        elif f == 3:
            reject.append(v.decode())
    return T.ResponseApplySnapshotChunk(
        result=result, refetch_chunks=tuple(refetch), reject_senders=tuple(reject)
    )


# ---------------------------------------------------------------------------
# Oneof envelope (field numbers: reference abci/types/types.pb.go)

_REQ_TABLE: Dict[type, Tuple[int, Callable]] = {
    T.RequestEcho: (1, _enc_echo),
    T.RequestFlush: (2, _enc_empty),
    T.RequestInfo: (3, _enc_req_info),
    T.RequestInitChain: (4, _enc_req_init_chain),
    T.RequestQuery: (5, _enc_req_query),
    T.RequestBeginBlock: (6, _enc_req_begin_block),
    T.RequestCheckTx: (7, _enc_req_check_tx),
    T.RequestDeliverTx: (8, _enc_req_deliver_tx),
    T.RequestEndBlock: (9, _enc_req_end_block),
    T.RequestCommit: (10, _enc_empty),
    T.RequestListSnapshots: (11, _enc_empty),
    T.RequestOfferSnapshot: (12, _enc_req_offer_snapshot),
    T.RequestLoadSnapshotChunk: (13, _enc_req_load_chunk),
    T.RequestApplySnapshotChunk: (14, _enc_req_apply_chunk),
}

_REQ_DECODE: Dict[int, Callable] = {
    1: lambda d: T.RequestEcho(message=FieldReader(d).bytes(1, b"").decode()),
    2: lambda d: T.RequestFlush(),
    3: _dec_req_info,
    4: _dec_req_init_chain,
    5: _dec_req_query,
    6: _dec_req_begin_block,
    7: _dec_req_check_tx,
    8: _dec_req_deliver_tx,
    9: _dec_req_end_block,
    10: lambda d: T.RequestCommit(),
    11: lambda d: T.RequestListSnapshots(),
    12: _dec_req_offer_snapshot,
    13: _dec_req_load_chunk,
    14: _dec_req_apply_chunk,
}

_RESP_TABLE: Dict[type, Tuple[int, Callable]] = {
    T.ResponseException: (1, _enc_resp_exception),
    T.ResponseEcho: (2, _enc_echo),
    T.ResponseFlush: (3, _enc_empty),
    T.ResponseInfo: (4, _enc_resp_info),
    T.ResponseInitChain: (5, _enc_resp_init_chain),
    T.ResponseQuery: (6, _enc_resp_query),
    T.ResponseBeginBlock: (7, _enc_resp_begin_block),
    T.ResponseCheckTx: (8, _enc_resp_check_tx),
    T.ResponseDeliverTx: (9, _enc_resp_deliver_tx),
    T.ResponseEndBlock: (10, _enc_resp_end_block),
    T.ResponseCommit: (11, _enc_resp_commit),
    T.ResponseListSnapshots: (12, _enc_resp_list_snapshots),
    T.ResponseOfferSnapshot: (13, _enc_resp_offer_snapshot),
    T.ResponseLoadSnapshotChunk: (14, _enc_resp_load_chunk),
    T.ResponseApplySnapshotChunk: (15, _enc_resp_apply_chunk),
}

_RESP_DECODE: Dict[int, Callable] = {
    1: lambda d: T.ResponseException(error=FieldReader(d).bytes(1, b"").decode()),
    2: lambda d: T.ResponseEcho(message=FieldReader(d).bytes(1, b"").decode()),
    3: lambda d: T.ResponseFlush(),
    4: _dec_resp_info,
    5: _dec_resp_init_chain,
    6: _dec_resp_query,
    7: _dec_resp_begin_block,
    8: _dec_resp_check_tx,
    9: _dec_resp_deliver_tx,
    10: _dec_resp_end_block,
    11: _dec_resp_commit,
    12: _dec_resp_list_snapshots,
    13: _dec_resp_offer_snapshot,
    14: _dec_resp_load_chunk,
    15: _dec_resp_apply_chunk,
}


def _encode_oneof(msg, table: Dict[type, Tuple[int, Callable]]) -> bytes:
    entry = table.get(type(msg))
    if entry is None:
        raise TypeError(f"not an ABCI oneof payload: {type(msg).__name__}")
    fieldno, enc = entry
    w = ProtoWriter()
    w.message(fieldno, enc(msg))
    return w.finish()


def _decode_oneof(data: bytes, table: Dict[int, Callable]):
    for f, _wt, v in iter_fields(data):
        dec = table.get(f)
        if dec is not None:
            return dec(v)
    raise ValueError("empty/unknown ABCI envelope")


def encode_request(msg) -> bytes:
    return _encode_oneof(msg, _REQ_TABLE)


def decode_request(data: bytes):
    return _decode_oneof(data, _REQ_DECODE)


def encode_response(msg) -> bytes:
    return _encode_oneof(msg, _RESP_TABLE)


def decode_response(data: bytes):
    return _decode_oneof(data, _RESP_DECODE)
