"""gRPC ABCI transport — the reference's third client/server variant
(reference: abci/client/grpc_client.go, abci/server/grpc_server.go).

One unary-unary method, ``/tendermint_tpu.abci.ABCI/Process``, carries
the same deterministic request/response envelopes the socket transport
frames (abci/codec.py encode_request/encode_response), so no generated
stubs are needed: both ends register the method with identity
(de)serializers and speak raw envelope bytes. Semantics match the
socket pair — requests answered in order per connection, the
application guarded by one lock (the 4-connection proxy provides the
cross-subsystem concurrency boundary, abci/proxy.py).
"""

from __future__ import annotations

import asyncio
from typing import Optional

import grpc
from grpc import aio as grpc_aio

from ..libs.log import get_logger
from ..libs.service import Service
from . import codec
from . import types as T
from .client import (
    ABCIClientError,
    ClientCreator,
    _RequestForwardingClient,
)
from .server import dispatch_to_app

__all__ = ["GRPCServer", "GRPCClient", "grpc_creator"]

_SERVICE = "tendermint_tpu.abci.ABCI"
_METHOD = "Process"


def _strip_scheme(address: str) -> str:
    for scheme in ("grpc://", "tcp://"):
        if address.startswith(scheme):
            return address[len(scheme):]
    return address


class GRPCServer(Service):
    """Serve an Application over gRPC (reference:
    abci/server/grpc_server.go)."""

    def __init__(self, address: str, app: T.Application) -> None:
        super().__init__(name="abci.grpc.server",
                         logger=get_logger("abci.grpc"))
        self.address = _strip_scheme(address)
        self.app = app
        self._app_lock = asyncio.Lock()
        self._server: Optional[grpc_aio.Server] = None
        self.bound_port: int = 0

    async def on_start(self) -> None:
        self._server = grpc_aio.server()
        rpc = grpc.unary_unary_rpc_method_handler(
            self._process,
            request_deserializer=None,  # raw envelope bytes
            response_serializer=None,
        )
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    _SERVICE, {_METHOD: rpc}
                ),
            )
        )
        self.bound_port = self._server.add_insecure_port(self.address)
        if self.bound_port == 0:
            raise OSError(f"failed to bind gRPC server to {self.address}")
        await self._server.start()
        self.logger.info(
            "abci grpc server listening",
            addr=self.address,
            port=self.bound_port,
        )

    async def on_stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None

    async def _process(self, request: bytes, context) -> bytes:
        req = codec.decode_request(request)
        if isinstance(req, T.RequestEcho):
            resp = T.ResponseEcho(message=req.message)
        elif isinstance(req, T.RequestFlush):
            resp = T.ResponseFlush()
        else:
            try:
                async with self._app_lock:
                    resp = dispatch_to_app(self.app, req)
            except Exception as e:
                # same error contract as the socket server: the app
                # exception rides back as ResponseException
                self.logger.error("abci app raised", err=repr(e))
                resp = T.ResponseException(error=repr(e))
        return codec.encode_response(resp)


class GRPCClient(_RequestForwardingClient):
    """Out-of-process client over gRPC (reference:
    abci/client/grpc_client.go). Per-call request/response — gRPC
    provides the stream multiplexing the socket client hand-rolls."""

    def __init__(self, address: str, must_connect: bool = True) -> None:
        super().__init__(name="abci.grpc.client")
        self.address = _strip_scheme(address)
        self.must_connect = must_connect
        self._channel: Optional[grpc_aio.Channel] = None
        self._call = None
        # gRPC unary calls have no cross-call ordering; the socket
        # transport's FIFO write/response matching is part of the ABCI
        # connection contract (mempool recheck vs new check_tx must
        # reach the app in submission order), so serialize requests.
        self._order_lock = asyncio.Lock()

    async def on_start(self) -> None:
        self._channel = grpc_aio.insecure_channel(self.address)
        self._call = self._channel.unary_unary(
            f"/{_SERVICE}/{_METHOD}",
            request_serializer=None,
            response_deserializer=None,
        )
        if self.must_connect:
            try:
                await self.echo("connected")
            except BaseException:
                # a failed start never reaches on_stop: close the
                # channel here or it leaks its background sockets
                await self._channel.close()
                self._channel = None
                self._call = None
                raise

    async def on_stop(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._call = None

    async def _request(self, req):
        if self._call is None:
            raise ABCIClientError("grpc client not started")
        payload = codec.encode_request(req)
        try:
            async with self._order_lock:
                data = await self._call(payload)
        except grpc_aio.AioRpcError as e:
            raise ABCIClientError(
                f"grpc: {e.code().name}: {e.details()}"
            ) from e
        resp = codec.decode_response(data)
        if isinstance(resp, T.ResponseException):
            # same contract as the socket client (client.py recv loop)
            raise ABCIClientError(f"abci app exception: {resp.error}")
        return resp


def grpc_creator(address: str, must_connect: bool = True) -> ClientCreator:
    return lambda: GRPCClient(address, must_connect=must_connect)
