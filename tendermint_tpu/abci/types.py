"""ABCI — the application boundary.

The 12-method `Application` interface that any deterministic state machine
implements to be replicated by the consensus engine
(reference: abci/types/application.go:11-31), together with the
request/response payload types (reference: abci/types/types.pb.go, field
shapes only — the wire codec lives in tendermint_tpu.abci.codec).

TPU note: the application boundary is pure host-side control plane; nothing
here touches the device. Device work (signature batches, merkle hashing)
happens *below* this seam in the consensus engine and block executor, so an
application written against this interface is oblivious to the accelerator.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..types.params import ConsensusParams

__all__ = [
    "CODE_TYPE_OK",
    "CheckTxType",
    "Event",
    "EventAttribute",
    "PubKey",
    "ValidatorUpdate",
    "Validator",
    "VoteInfo",
    "LastCommitInfo",
    "Misbehavior",
    "MISBEHAVIOR_DUPLICATE_VOTE",
    "MISBEHAVIOR_LIGHT_CLIENT_ATTACK",
    "Snapshot",
    "RequestEcho",
    "RequestFlush",
    "RequestInfo",
    "RequestInitChain",
    "RequestQuery",
    "RequestBeginBlock",
    "RequestCheckTx",
    "RequestDeliverTx",
    "RequestEndBlock",
    "RequestCommit",
    "RequestListSnapshots",
    "RequestOfferSnapshot",
    "RequestLoadSnapshotChunk",
    "RequestApplySnapshotChunk",
    "ResponseException",
    "ResponseEcho",
    "ResponseFlush",
    "ResponseInfo",
    "ResponseInitChain",
    "ResponseQuery",
    "ResponseBeginBlock",
    "ResponseCheckTx",
    "ResponseDeliverTx",
    "ResponseEndBlock",
    "ResponseCommit",
    "ResponseListSnapshots",
    "ResponseOfferSnapshot",
    "ResponseLoadSnapshotChunk",
    "ResponseApplySnapshotChunk",
    "OFFER_SNAPSHOT_ACCEPT",
    "OFFER_SNAPSHOT_ABORT",
    "OFFER_SNAPSHOT_REJECT",
    "OFFER_SNAPSHOT_REJECT_FORMAT",
    "OFFER_SNAPSHOT_REJECT_SENDER",
    "APPLY_CHUNK_ACCEPT",
    "APPLY_CHUNK_ABORT",
    "APPLY_CHUNK_RETRY",
    "APPLY_CHUNK_RETRY_SNAPSHOT",
    "APPLY_CHUNK_REJECT_SNAPSHOT",
    "Application",
    "BaseApplication",
]

CODE_TYPE_OK = 0  # reference: abci/types/types.go:9


class CheckTxType:
    """reference: abci/types/types.pb.go CheckTxType enum."""

    NEW = 0
    RECHECK = 1


# ---------------------------------------------------------------------------
# Shared payload types


@dataclass(frozen=True)
class EventAttribute:
    """A key/value tag on an event; `index` marks it for the event indexer
    (reference: abci/types/types.pb.go EventAttribute)."""

    key: bytes
    value: bytes
    index: bool = False


@dataclass(frozen=True)
class Event:
    """A typed bag of attributes emitted by the app per-tx / per-block."""

    type: str
    attributes: tuple[EventAttribute, ...] = ()


@dataclass(frozen=True)
class PubKey:
    """ABCI public-key wrapper: (key type name, raw bytes)
    (reference: proto/tendermint/crypto/keys.pb.go oneof sum)."""

    key_type: str  # "ed25519" | "sr25519" | "secp256k1"
    data: bytes


@dataclass(frozen=True)
class ValidatorUpdate:
    """Validator-set delta returned from EndBlock; power 0 removes."""

    pub_key: PubKey
    power: int


@dataclass(frozen=True)
class Validator:
    """Compact validator reference inside commit info (address, not key)."""

    address: bytes
    power: int


@dataclass(frozen=True)
class VoteInfo:
    validator: Validator
    signed_last_block: bool


@dataclass(frozen=True)
class LastCommitInfo:
    round: int = 0
    votes: tuple[VoteInfo, ...] = ()


MISBEHAVIOR_DUPLICATE_VOTE = 1
MISBEHAVIOR_LIGHT_CLIENT_ATTACK = 2


@dataclass(frozen=True)
class Misbehavior:
    """Evidence forwarded to the app in BeginBlock
    (reference: abci/types/types.pb.go Evidence)."""

    kind: int
    validator: Validator
    height: int
    time_ns: int
    total_voting_power: int


@dataclass(frozen=True)
class Snapshot:
    """State-sync snapshot advertisement
    (reference: abci/types/types.pb.go Snapshot)."""

    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


# ---------------------------------------------------------------------------
# Requests


@dataclass(frozen=True)
class RequestEcho:
    message: str = ""


@dataclass(frozen=True)
class RequestFlush:
    pass


@dataclass(frozen=True)
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass(frozen=True)
class RequestInitChain:
    time_ns: int = 0
    chain_id: str = ""
    consensus_params: Optional[ConsensusParams] = None
    validators: tuple[ValidatorUpdate, ...] = ()
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass(frozen=True)
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass(frozen=True)
class RequestBeginBlock:
    hash: bytes = b""
    header_bytes: bytes = b""  # proto-encoded Header (opaque to the app)
    last_commit_info: LastCommitInfo = field(default_factory=LastCommitInfo)
    byzantine_validators: tuple[Misbehavior, ...] = ()


@dataclass(frozen=True)
class RequestCheckTx:
    tx: bytes = b""
    type: int = CheckTxType.NEW


@dataclass(frozen=True)
class RequestDeliverTx:
    tx: bytes = b""


@dataclass(frozen=True)
class RequestEndBlock:
    height: int = 0


@dataclass(frozen=True)
class RequestCommit:
    pass


@dataclass(frozen=True)
class RequestListSnapshots:
    pass


@dataclass(frozen=True)
class RequestOfferSnapshot:
    snapshot: Optional[Snapshot] = None
    app_hash: bytes = b""


@dataclass(frozen=True)
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass(frozen=True)
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


# ---------------------------------------------------------------------------
# Responses


@dataclass(frozen=True)
class ResponseException:
    error: str = ""


@dataclass(frozen=True)
class ResponseEcho:
    message: str = ""


@dataclass(frozen=True)
class ResponseFlush:
    pass


@dataclass(frozen=True)
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass(frozen=True)
class ResponseInitChain:
    consensus_params: Optional[ConsensusParams] = None
    validators: tuple[ValidatorUpdate, ...] = ()
    app_hash: bytes = b""


@dataclass(frozen=True)
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: tuple = ()  # tuple of crypto.merkle ProofOp
    height: int = 0
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass(frozen=True)
class ResponseBeginBlock:
    events: tuple[Event, ...] = ()


@dataclass(frozen=True)
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: tuple[Event, ...] = ()
    codespace: str = ""
    sender: str = ""
    priority: int = 0
    mempool_error: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass(frozen=True)
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: tuple[Event, ...] = ()
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass(frozen=True)
class ResponseEndBlock:
    validator_updates: tuple[ValidatorUpdate, ...] = ()
    consensus_param_updates: Optional[ConsensusParams] = None
    events: tuple[Event, ...] = ()


@dataclass(frozen=True)
class ResponseCommit:
    data: bytes = b""  # the app hash
    retain_height: int = 0


@dataclass(frozen=True)
class ResponseListSnapshots:
    snapshots: tuple[Snapshot, ...] = ()


OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5


@dataclass(frozen=True)
class ResponseOfferSnapshot:
    result: int = 0


@dataclass(frozen=True)
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3
APPLY_CHUNK_RETRY_SNAPSHOT = 4
APPLY_CHUNK_REJECT_SNAPSHOT = 5


@dataclass(frozen=True)
class ResponseApplySnapshotChunk:
    result: int = 0
    refetch_chunks: tuple[int, ...] = ()
    reject_senders: tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# Application interface


class Application:
    """The 12-method deterministic state machine interface
    (reference: abci/types/application.go:11-31). Synchronous by design —
    concurrency is the *client's* concern (the proxy mux serializes each of
    the four logical connections independently)."""

    # Info/Query connection
    def info(self, req: RequestInfo) -> ResponseInfo:
        raise NotImplementedError

    def query(self, req: RequestQuery) -> ResponseQuery:
        raise NotImplementedError

    # Mempool connection
    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        raise NotImplementedError

    # Consensus connection
    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        raise NotImplementedError

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> ResponseCommit:
        raise NotImplementedError

    # State-sync connection
    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: RequestLoadSnapshotChunk
    ) -> ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: RequestApplySnapshotChunk
    ) -> ResponseApplySnapshotChunk:
        raise NotImplementedError


class BaseApplication(Application):
    """No-op application accepting everything
    (reference: abci/types/application.go:36-95)."""

    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery()

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx()

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        return ResponseDeliverTx()

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot()

    def load_snapshot_chunk(
        self, req: RequestLoadSnapshotChunk
    ) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(
        self, req: RequestApplySnapshotChunk
    ) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk()
