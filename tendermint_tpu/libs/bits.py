"""Thread-safe-enough bit array used by VoteSet / PartSet / blocksync
bookkeeping (reference: libs/bits/bit_array.go). Backed by a Python int
(arbitrary precision) rather than []uint64 — same observable semantics,
including the proto form (bits count + little-endian uint64 words).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from . import rng

__all__ = ["BitArray", "MAX_BIT_ARRAY_SIZE"]

# DoS bound on wire-decoded bit arrays. The protocol's real maxima are
# MAX_VOTES_COUNT (10_000) and MAX_BLOCK_PARTS_COUNT (1_601); 2**20
# bits (a 128 KiB mask int) leaves two orders of magnitude of headroom
# while keeping the `(1 << size)` masks every BitArray op builds
# allocation-bounded. A varint `bits` field costs the attacker ten
# bytes to claim 2**63 — without this clamp, from_words would try to
# materialize that as a Python bigint.
MAX_BIT_ARRAY_SIZE = 1 << 20


class BitArray:
    __slots__ = ("size", "_bits")

    def __init__(self, size: int) -> None:
        if size < 0:
            raise ValueError("negative size")
        self.size = size
        self._bits = 0

    # -- element access --

    def get(self, i: int) -> bool:
        if i < 0 or i >= self.size:
            return False
        return bool(self._bits >> i & 1)

    def set(self, i: int, value: bool = True) -> bool:
        if i < 0 or i >= self.size:
            return False
        if value:
            self._bits |= 1 << i
        else:
            self._bits &= ~(1 << i)
        return True

    # -- set algebra (sizes may differ; result is sized like self, matching
    # the reference's Or/And behavior of max/min sizing kept simple) --

    def or_(self, other: "BitArray") -> "BitArray":
        out = BitArray(max(self.size, other.size))
        out._bits = self._bits | other._bits
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.size, other.size))
        out._bits = self._bits & other._bits & ((1 << out.size) - 1)
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.size)
        out._bits = ~self._bits & ((1 << self.size) - 1)
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        out = BitArray(self.size)
        out._bits = self._bits & ~other._bits & ((1 << self.size) - 1)
        return out

    def update(self, other: "BitArray") -> None:
        """Copy other's bits into self (sized to self)."""
        self._bits = other._bits & ((1 << self.size) - 1)

    # -- queries --

    def is_empty(self) -> bool:
        return self._bits == 0

    def is_full(self) -> bool:
        return self.size > 0 and self._bits == (1 << self.size) - 1

    def count(self) -> int:
        return self._bits.bit_count()

    def indices(self) -> Iterator[int]:
        bits = self._bits
        i = 0
        while bits:
            if bits & 1:
                yield i
            bits >>= 1
            i += 1

    def pick_random(self) -> Optional[int]:
        """Return a uniformly random set index, or None if empty
        (reference: libs/bits/bit_array.go PickRandom — used to choose which
        block part / vote to gossip next). Draws from the seedable
        gossip RNG, not OS entropy, so fuzz runs replay from a seed."""
        idxs = list(self.indices())
        if not idxs:
            return None
        return idxs[rng.randbelow(len(idxs))]

    def copy(self) -> "BitArray":
        out = BitArray(self.size)
        out._bits = self._bits
        return out

    # -- wire form --

    def to_words(self) -> List[int]:
        n_words = (self.size + 63) // 64
        return [(self._bits >> (64 * w)) & ((1 << 64) - 1) for w in range(n_words)]

    @classmethod
    def from_words(cls, size: int, words: List[int]) -> "BitArray":
        # wire entry (decode_bit_array): `size` is an attacker-chosen
        # varint; every BitArray op masks with `(1 << size) - 1`, so an
        # unclamped size is a ten-byte bigint-allocation lever
        if size > MAX_BIT_ARRAY_SIZE:
            raise ValueError(
                f"BitArray size {size} exceeds MAX_BIT_ARRAY_SIZE "
                f"{MAX_BIT_ARRAY_SIZE}"
            )
        # the word COUNT must be bounded too: our encoder emits exactly
        # ceil(size/64) words (legacy unpacked records DROPPED zero
        # words, so fewer is tolerated — never more), and the assembly
        # below must be linear in the words actually admitted, not a
        # per-word `|=` that reallocates a growing bigint (measured
        # 9.5 s for 512 KiB of hostile packed words under the old loop)
        if len(words) > (size + 63) // 64:
            raise ValueError(
                f"BitArray: {len(words)} words exceed size {size}"
            )
        out = cls(size)
        try:
            buf = b"".join(w.to_bytes(8, "little") for w in words)
        except (OverflowError, AttributeError):
            raise ValueError("BitArray word out of uint64 range") from None
        bits = int.from_bytes(buf, "little")
        out._bits = bits & ((1 << size) - 1) if size else 0
        return out

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, BitArray)
            and self.size == other.size
            and self._bits == other._bits
        )

    def __repr__(self) -> str:
        s = "".join("x" if self.get(i) else "_" for i in range(min(self.size, 64)))
        return f"BA{{{self.size}:{s}}}"
