"""Service lifecycle primitives.

Every long-running component in the framework (reactors, routers, the
consensus machine, RPC servers) follows one lifecycle contract, mirroring the
reference's service.Service (reference: libs/service/service.go:24-49):
start-once, stop-once, wait-for-termination. Ours is asyncio-native: a
Service owns a set of tasks which are cancelled on stop.
"""

from __future__ import annotations

import asyncio
from typing import Coroutine, Optional

from . import profiler
from .log import Logger, get_logger

__all__ = ["Service", "ServiceError"]


class ServiceError(Exception):
    pass


class Service:
    """Base class for long-running components.

    Subclasses override `on_start` (spawn tasks via `self.spawn`) and
    optionally `on_stop` (cleanup before task cancellation).
    """

    def __init__(self, name: str = "", logger: Optional[Logger] = None) -> None:
        self.name = name or type(self).__name__
        self.logger = logger or get_logger(self.name)
        self._started = False
        self._stopped = False
        self._tasks: list[asyncio.Task] = []
        self._pending_stop: Optional[asyncio.Task] = None
        self._done = asyncio.Event()

    # -- lifecycle --

    @property
    def is_running(self) -> bool:
        return self._started and not self._stopped

    async def start(self) -> None:
        if self._started:
            raise ServiceError(f"{self.name}: already started")
        if self._stopped:
            raise ServiceError(f"{self.name}: already stopped; cannot restart")
        self._started = True
        self.logger.info("starting service")
        try:
            await self.on_start()
        except Exception:
            self._stopped = True
            await self._cancel_tasks()
            self._done.set()
            raise

    async def stop(self) -> None:
        if not self._started or self._stopped:
            if self._stopped:
                # A concurrent stop() is (or was) draining tasks; don't
                # return until teardown actually finished.
                await self._done.wait()
            return
        self._stopped = True
        self.logger.info("stopping service")
        try:
            await self.on_stop()
        finally:
            await self._cancel_tasks()
            self._done.set()

    async def _cancel_tasks(self) -> None:
        pending = [t for t in self._tasks if not t.done()]
        while pending:
            for task in pending:
                task.cancel()
            # Python 3.10's asyncio.wait_for can swallow a cancellation
            # that races its inner future completing (bpo-42130 family,
            # rewritten in 3.11) — a task parked in such a wait_for
            # survives one cancel and its retry loop runs forever, so a
            # single cancel+gather would hang stop(). Re-cancel until
            # every task actually finishes.
            await asyncio.wait(pending, timeout=1.0)
            # re-derive from _tasks, not the wait() leftovers: a task
            # that slipped through an await completing during this
            # sweep can spawn NEW tasks (e.g. an accept finishing its
            # handshake mid-stop) — the final gather below must never
            # wait on a task nothing cancelled
            pending = [t for t in self._tasks if not t.done()]
        # return_exceptions keeps a cancellation of stop() itself
        # propagating while swallowing the tasks' own CancelledErrors
        # (and retrieving real exceptions so none log as unretrieved).
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()

    async def wait(self) -> None:
        """Block until the service has fully stopped."""
        await self._done.wait()

    def spawn(self, coro: Coroutine, name: str = "") -> asyncio.Task:
        """Spawn a task owned by this service; cancelled on stop. Uncaught
        exceptions stop the service (fail-fast, like the reference's
        consensus panic-on-error policy, internal/consensus/state.go:820)."""
        task = asyncio.get_event_loop().create_task(
            self._run_guarded(coro, name or self.name)
        )
        # profiler task attribution: loop-thread samples landing while
        # this task runs report "service:<svc>:<task>" instead of the
        # bare loop (one attribute read when the profiler is cold)
        profiler.label_task(task, f"service:{self.name}:{name or 'main'}")
        # If the task is cancelled before its first tick, the inner coroutine
        # never starts; close it then to avoid "never awaited" warnings.
        task.add_done_callback(lambda _t: coro.close())
        self._tasks.append(task)
        # drop finished tasks so services spawning per-event work
        # (dials, accepts) don't grow the list without bound
        task.add_done_callback(self._discard_task)
        return task

    def _discard_task(self, task: asyncio.Task) -> None:
        try:
            self._tasks.remove(task)
        except ValueError:
            pass  # already cleared by stop()

    async def _run_guarded(self, coro: Coroutine, name: str) -> None:
        try:
            await coro
        except asyncio.CancelledError:
            raise
        except Exception:
            self.logger.exception(f"task {name} failed")
            # Detach to avoid self-await deadlock during stop(); hold a
            # strong reference so the stop task can't be GC'd before it runs.
            stop_task = asyncio.get_event_loop().create_task(self.stop())
            self._pending_stop = stop_task
            stop_task.add_done_callback(
                lambda _t: setattr(self, "_pending_stop", None)
            )

    # -- overridables --

    async def on_start(self) -> None:  # pragma: no cover - trivial default
        pass

    async def on_stop(self) -> None:  # pragma: no cover - trivial default
        pass
