"""The injectable, seedable RNG for gossip randomness.

The gossip routines pick WHICH part/vote to send a peer at random
(reference: PickSendVote / BitArray.PickRandom) — that randomness is
load-balancing, not security, so it does not need OS entropy. What it
DOES need is seedability: the schedulefuzz suites replay a failing
interleaving from one named seed, and an unseeded `random.choice` in
the delivery path breaks seed-exact replay (tmlint rule `det-random`
enforces this — see docs/static_analysis.md).

Production behavior is unchanged: the module RNG self-seeds from OS
entropy at import, exactly like the global `random` module. Fuzz
scenarios pin it per schedule:

    from tendermint_tpu.libs import rng
    rng.reseed(sched.subseed("gossip"))

and key-generation / cookie / nonce code keeps using `secrets` — this
module is for protocol-visible *choices*, never secrets.
"""

from __future__ import annotations

import random
from typing import Optional, Sequence, TypeVar

__all__ = [
    "derive", "gossip", "reseed", "subseed",
    "choice", "shuffle", "randbelow",
]

T = TypeVar("T")

_GOSSIP = random.Random()  # self-seeds from OS entropy, like `random`


def derive(seed: int, label: str) -> random.Random:
    """An INDEPENDENT seeded stream for (seed, label) — the loadgen
    harness derives one per concern (arrival schedule, op mix, payload
    bytes) so adding a consumer never shifts another's draws, the same
    property schedulefuzz gets from Schedule.subseed. Does not touch
    the shared gossip RNG."""
    return random.Random(f"{seed}/{label}")


def subseed(seed: int, label: str) -> int:
    """A deterministic child SEED for (seed, label) — for consumers
    that need an integer seed to hand a sibling source of seeded
    randomness (a crypto.faults rule, a chaos scenario), where
    derive()'s ready-made stream doesn't fit. One definition shared by
    Schedule.subseed and the chaos campaign so 'the same seed replays
    the same schedule' means the same thing on both planes."""
    import zlib

    return (int(seed) << 16) ^ zlib.crc32(label.encode())


def gossip() -> random.Random:
    """The shared gossip RNG instance (inject by reseeding, or swap a
    Random-compatible stand-in in tests via monkeypatch)."""
    return _GOSSIP


def reseed(seed: Optional[int]) -> None:
    """Reseed the gossip RNG — schedulefuzz calls this with
    `sched.subseed("gossip")` so gossip picks replay with the
    schedule; `None` restores OS-entropy self-seeding."""
    _GOSSIP.seed(seed)


def choice(seq: Sequence[T]) -> T:
    return _GOSSIP.choice(seq)


def shuffle(seq: list) -> None:
    _GOSSIP.shuffle(seq)


def randbelow(n: int) -> int:
    return _GOSSIP.randrange(n)
