"""Structured key-value logging.

The reference logs structured key-value pairs through zerolog
(reference: libs/log/default.go:27). We layer a keyed-context API over the
stdlib logging module so every subsystem gets `logger.with_fields(...)`
scoping and machine-parseable output without extra dependencies.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any

__all__ = ["Logger", "get_logger", "configure"]

_FORMAT_JSON = False


def configure(level: str = "info", json_format: bool = False) -> None:
    global _FORMAT_JSON
    _FORMAT_JSON = json_format
    logging.basicConfig(
        stream=sys.stderr,
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(message)s",
    )


class Logger:
    """A logger carrying bound key-value context."""

    __slots__ = ("_log", "_fields")

    def __init__(self, name: str, fields: dict[str, Any] | None = None) -> None:
        self._log = logging.getLogger(name)
        self._fields = fields or {}

    def with_fields(self, **fields: Any) -> "Logger":
        merged = dict(self._fields)
        merged.update(fields)
        return Logger(self._log.name, merged)

    def _emit(self, level: int, msg: str, fields: dict[str, Any]) -> None:
        if not self._log.isEnabledFor(level):
            return
        all_fields = {**self._fields, **fields}
        if _FORMAT_JSON:
            record = {
                "ts": time.time(),
                "level": logging.getLevelName(level).lower(),
                "module": self._log.name,
                "msg": msg,
                **all_fields,
            }
            self._log.log(level, json.dumps(record, default=str))
        else:
            kv = " ".join(f"{k}={v}" for k, v in all_fields.items())
            self._log.log(
                level, f"{logging.getLevelName(level)[0]} | {self._log.name} | {msg}"
                + (f" | {kv}" if kv else "")
            )

    def debug(self, msg: str, **fields: Any) -> None:
        self._emit(logging.DEBUG, msg, fields)

    def info(self, msg: str, **fields: Any) -> None:
        self._emit(logging.INFO, msg, fields)

    def warning(self, msg: str, **fields: Any) -> None:
        self._emit(logging.WARNING, msg, fields)

    warn = warning

    def error(self, msg: str, **fields: Any) -> None:
        self._emit(logging.ERROR, msg, fields)

    def exception(self, msg: str, **fields: Any) -> None:
        import traceback

        fields = dict(fields)
        fields["exc"] = traceback.format_exc(limit=20).strip().replace("\n", " | ")
        self._emit(logging.ERROR, msg, fields)


def get_logger(name: str, **fields: Any) -> Logger:
    return Logger(name, fields or None)
