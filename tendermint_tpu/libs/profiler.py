"""Wall-clock sampling profiler: the fifth observability plane.

Zero-dependency sibling of libs/trace.py. Where spans answer "what
happened inside THIS call" and metrics answer "how often / how long on
average", the profiler answers the question neither can: **where is
CPU/wall time actually going** across the whole process, under
production load, without instrumenting a single call site.

A daemon sampler thread walks `sys._current_frames()` at a configurable
rate (default ~97 Hz — a prime, so the sampler never phase-locks with
the 10 ms/100 ms periodic work consensus and the schedulers do) and
folds every thread's stack into a bounded aggregation keyed by
(thread-role, task-label, folded stack). Two attribution layers make
the samples comparable across runs and across PRs:

1. **Subsystem buckets** — every sample is attributed to the innermost
   in-package frame's subsystem (consensus / mempool / p2p / rpc /
   eventbus / crypto-batch / merkle / store / serialization / ...), so
   the tmload bottleneck ledger can rank "where the next 10x is
   hiding" with stable names. Samples with no in-package frame land in
   `idle` (event-loop selector poll / parked waits) or `stdlib`.

2. **asyncio-task labels** — a sample on an event-loop thread is
   sub-attributed to the *currently running task* (read cross-thread
   via `asyncio.tasks.current_task(loop)`, a plain dict lookup), whose
   origin is labeled where it is spawned (`label_task`: rpc route
   pumps, WS writers, p2p channel pumps, service loops). So "the loop
   is busy" decomposes into "the WS writer is busy".

Kill-switched exactly like trace.py: OFF by default, `enable()` starts
the sampler, `disable()` stops AND joins it (node teardown calls this —
tests/test_teardown.py pins zero surviving threads). The disabled path
of the only call-site hook (`label_task`) is a single module-attribute
read. Labeling can be **armed** independently of sampling
(`arm_labels()`, done at node start) so a profile started mid-run over
RPC still sees the long-lived pumps' labels; an unarmed process pays
tens of ns per spawn site and nothing else.

Sampling bias note (docs/observability.md): this is a *wall-clock*
profiler — a thread parked in a lock or a selector counts the same as
one burning CPU. That is the point (lock convoys and fsync stalls are
real time) but it means shares are shares of *wall*, not of CPU;
`idle`/`wait` buckets keep the distinction visible. A second,
GIL-specific bias: the sampler must acquire the GIL to read frames, and
it acquires it at the target's next *release point* — so pure-Python
CPU bursts shorter than the interpreter switch interval are attributed
to the GIL-releasing call that ends them (a socket send, a hash, a
selector poll) rather than the burst itself. `enable()` therefore
drops `sys.setswitchinterval` to 1 ms for the profiling window (forced
preemption then catches any burst over ~1 ms) and `disable()` restores
the previous value.
"""

from __future__ import annotations

import asyncio
import json
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DEFAULT_HZ",
    "DEFAULT_MAX_STACKS",
    "arm_labels",
    "disable",
    "disarm_labels",
    "enable",
    "folded",
    "is_enabled",
    "label_task",
    "labels_armed",
    "register_loop",
    "register_thread",
    "reset",
    "snapshot",
    "stats",
    "subsystem_counts",
    "subsystem_of",
    "subsystem_shares",
    "task_label",
    "to_profile_json",
]

DEFAULT_HZ = 97.0  # prime: never phase-locks with 10/50/100 ms timers
DEFAULT_MAX_STACKS = 2048
_MAX_DEPTH = 48  # frames kept per stack (innermost wins the bucket)
_CODE_CACHE_CAP = 8192  # folded-name cache: live code objects

_enabled = False
_armed = False  # label_task records labels (independent of sampling)
_hz = DEFAULT_HZ
_max_stacks = DEFAULT_MAX_STACKS

# aggregation: (role, task_label, folded_stack, subsystem) -> count.
# Only the sampler thread writes; _agg_lock makes snapshot/reset safe
# against a concurrent sample tick.
_agg: Dict[Tuple[str, str, str, str], int] = {}
_agg_lock = threading.Lock()
_samples_total = 0
_collapsed_total = 0  # samples folded into <collapsed> by the cap
_started_unix = 0.0

_thread: Optional[threading.Thread] = None
_stop_evt = threading.Event()
# serializes enable/disable: two concurrent enable() callers (the
# owning node + the profile RPC route) must not both observe
# _enabled=False and start two sampler threads. The sampler thread
# never takes this lock, so disable()'s join under it cannot deadlock.
_lifecycle_lock = threading.Lock()
_SWITCH_INTERVAL_S = 0.001  # forced-preemption bound while profiling
_saved_switch_interval: Optional[float] = None

# thread ident -> declared role ("loop", "wal", "verifier-watchdog"...)
_roles: Dict[int, str] = {}
# thread ident -> weakref to the asyncio loop running on it (for task
# attribution); stale entries are pruned when the loop is gc'd
_loops: Dict[int, "weakref.ref"] = {}
_reg_lock = threading.Lock()

# code object -> (folded entry, subsystem-or-"") — code objects are
# interned per loaded module, so holding them leaks nothing new
_code_cache: Dict[Any, Tuple[str, str]] = {}

_PKG_MARKER = "tendermint_tpu" + "/"  # path fragment of our package

# ordered: first matching prefix of the package-relative path wins
_SUBSYSTEM_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("consensus/", "consensus"),
    ("mempool/", "mempool"),
    ("p2p/", "p2p"),
    ("blocksync/", "blocksync"),
    ("statesync/", "statesync"),
    ("evidence/", "evidence"),
    ("light/", "light"),
    ("rpc/", "rpc"),
    ("eventbus/", "eventbus"),
    ("pubsub/", "eventbus"),
    ("crypto/merkle", "merkle"),
    ("crypto/tmhash", "merkle"),
    ("crypto/", "crypto-batch"),
    ("store/", "store"),
    ("state/", "store"),
    ("encoding/", "serialization"),
    ("types/", "serialization"),
    ("abci/", "abci"),
    ("node/", "node"),
    ("libs/metrics", "metrics"),
    ("libs/", "libs"),
    ("loadgen/", "harness"),
    ("e2e/", "harness"),
    ("analysis/", "analysis"),
    ("cmd/", "cmd"),
)


def subsystem_of(rel_path: str) -> str:
    """Subsystem bucket for a package-relative module path
    ("rpc/jsonrpc.py" -> "rpc"). Unmatched in-package files bucket by
    their first path component, so every sample has a *named* home."""
    for prefix, bucket in _SUBSYSTEM_PREFIXES:
        if rel_path.startswith(prefix):
            return bucket
    head = rel_path.split("/", 1)[0]
    return head[:-3] if head.endswith(".py") else head


def _describe_code(code) -> Tuple[str, str]:
    """(folded frame entry, subsystem) for one code object. Subsystem
    is "" for frames outside the package."""
    fn = code.co_filename.replace("\\", "/")
    i = fn.rfind(_PKG_MARKER)
    if i >= 0:
        rel = fn[i + len(_PKG_MARKER):]
        mod = rel[:-3] if rel.endswith(".py") else rel
        return f"{mod.replace('/', '.')}:{code.co_name}", subsystem_of(rel)
    # stdlib / site-packages: keep the last two path components
    parts = fn.rsplit("/", 2)
    stem = parts[-1]
    stem = stem[:-3] if stem.endswith(".py") else stem
    mod = f"{parts[-2]}.{stem}" if len(parts) > 2 else stem
    return f"{mod}:{code.co_name}", ""


def _entry_for(code) -> Tuple[str, str]:
    ent = _code_cache.get(code)
    if ent is None:
        ent = _describe_code(code)
        if len(_code_cache) < _CODE_CACHE_CAP:
            # tmlive: bounded=keyed by live code objects (one per
            # loaded function), hard-capped at _CODE_CACHE_CAP —
            # overflow falls through to uncached computation
            # tmlint: disable=lock-global-mutation — single GIL-atomic
            # dict store memoizing a pure function; racers write the
            # identical value (worst case the cap overshoots by one
            # entry per racer)
            _code_cache[code] = ent
    return ent


_WAIT_FUNCS = frozenset(
    ("wait", "get", "put", "join", "_wait_for_tstate_lock", "acquire")
)


def _classify_leaf(leaf: str) -> str:
    """Bucket for an out-of-package innermost frame: the event loop's
    selector poll is `idle`, parked thread primitives are `wait`,
    anything else is honest `stdlib` work (json, struct, ...)."""
    mod, _, func = leaf.partition(":")
    tail = mod.rsplit(".", 1)[-1]
    if tail in ("selectors", "base_events"):
        return "idle"
    if tail in ("threading", "queue") and func in _WAIT_FUNCS:
        return "wait"
    return "stdlib"


def _fold(frame) -> Tuple[Tuple[str, ...], str]:
    """Walk a frame chain into (root-first folded stack, subsystem).
    The subsystem is the innermost in-package frame's bucket; a stack
    with none is `idle` (selector poll / loop plumbing) or `stdlib`."""
    entries: List[str] = []
    subsystem = ""
    depth = 0
    f = frame
    while f is not None and depth < _MAX_DEPTH:
        ent, sub = _entry_for(f.f_code)
        entries.append(ent)
        if not subsystem and sub:
            subsystem = sub
        f = f.f_back
        depth += 1
    if not subsystem:
        subsystem = _classify_leaf(entries[0]) if entries else "stdlib"
    entries.reverse()
    return tuple(entries), subsystem


# -- registration hooks ---------------------------------------------------


def register_thread(role: str, ident: Optional[int] = None) -> None:
    """Declare a thread's role ("loop", "wal", "verifier-watchdog");
    samples of that thread report under the role instead of the raw
    thread name."""
    with _reg_lock:
        # tmlive: bounded=keyed by thread ident — one entry per
        # *declared* thread role; the process runs a fixed, small set
        # of long-lived named threads
        _roles[ident if ident is not None else threading.get_ident()] = role


def register_loop(
    loop: Optional[asyncio.AbstractEventLoop] = None,
    ident: Optional[int] = None,
) -> None:
    """Bind an asyncio loop to the thread running it, so loop-thread
    samples can be sub-attributed to the current task. Call from the
    loop thread (node start does)."""
    if loop is None:
        loop = asyncio.get_event_loop()
    with _reg_lock:
        # tmlive: bounded=keyed by thread ident — one entry per thread
        # that ever ran a registered loop; entries whose loop was gc'd
        # are pruned by the sampler tick
        _loops[ident if ident is not None else threading.get_ident()] = (
            weakref.ref(loop)
        )


def label_task(task, label: str):
    """Tag an asyncio task with its origin ("rpc:ws-writer",
    "p2p:ch-pump:32", "service:consensus"). Samples that land while
    this task runs report under the label. One module-attribute read
    when neither armed nor sampling — hot spawn paths call this
    unconditionally."""
    if not (_armed or _enabled):
        return task
    try:
        task._tt_profile_label = label
    except AttributeError:
        pass  # foreign task implementation without a __dict__
    ident = threading.get_ident()
    if ident not in _loops:
        try:
            register_loop(task.get_loop(), ident)
        except Exception:
            pass  # off-loop labeling: attribution degrades gracefully
    return task


def task_label(task) -> str:
    """The label a sample of this task would report (labeled origin,
    else the asyncio task name)."""
    lbl = getattr(task, "_tt_profile_label", "")
    if lbl:
        return lbl
    try:
        return task.get_name()
    except Exception:
        return ""


def arm_labels() -> None:
    """Record task labels even while sampling is off, so a profile
    started mid-run (RPC `profile` route) sees long-lived pumps'
    origins. Node assembly arms at start."""
    global _armed
    _armed = True


def disarm_labels() -> None:
    global _armed
    _armed = False


def labels_armed() -> bool:
    return _armed


# -- the sampler ----------------------------------------------------------


def _take_sample() -> None:
    global _samples_total, _collapsed_total
    frames = sys._current_frames()
    own = threading.get_ident()
    with _reg_lock:
        roles = dict(_roles)
        loops = dict(_loops)
    names: Dict[int, str] = {}
    for t in threading.enumerate():
        names[t.ident] = t.name
    with _agg_lock:
        for ident, frame in frames.items():
            if ident == own:
                continue  # never profile the profiler
            role = roles.get(ident) or names.get(ident) or f"t{ident}"
            label = ""
            ref = loops.get(ident)
            if ref is not None:
                loop = ref()
                if loop is None:
                    with _reg_lock:
                        _loops.pop(ident, None)  # loop was gc'd
                else:
                    task = asyncio.tasks.current_task(loop)
                    if task is not None:
                        label = task_label(task)
            stack, subsystem = _fold(frame)
            key = (role, label, ";".join(stack), subsystem)
            n = _agg.get(key)
            if n is not None:
                _agg[key] = n + 1
            elif len(_agg) < _max_stacks:
                # tmlive: bounded=hard cap _max_stacks: a novel stack
                # beyond the cap collapses into the per-(role,
                # subsystem) <collapsed> key below instead of growing
                _agg[key] = 1
            else:
                ckey = (role, "", "<collapsed>", subsystem)
                # tmlive: bounded=collapse keys are bounded by
                # live-threads x the fixed subsystem alphabet — the
                # eviction policy of the capped stack table
                _agg[ckey] = _agg.get(ckey, 0) + 1
                _collapsed_total += 1
            _samples_total += 1


def _sampler_main() -> None:
    interval = 1.0 / _hz
    # tmlive: block-ok — dedicated daemon sampler thread parked
    # between ticks; the wait is bounded by 1/hz and disable() sets
    # the event then joins
    while not _stop_evt.wait(interval):
        try:
            _take_sample()
        except Exception:
            # a sampler crash must never take the node down; skip the
            # tick (RuntimeError from a dict resized mid-enumerate in
            # threading.enumerate, a frame gone mid-walk, ...)
            pass


def enable(
    hz: Optional[float] = None, max_stacks: Optional[int] = None
) -> None:
    """Start sampling (idempotent). Also arms task labels."""
    global _enabled, _hz, _max_stacks, _thread, _started_unix
    if hz is not None and hz <= 0:
        raise ValueError(f"profiler hz must be > 0: {hz}")
    if max_stacks is not None and max_stacks < 1:
        raise ValueError(
            f"profiler max_stacks must be >= 1: {max_stacks}"
        )
    with _lifecycle_lock:
        if hz is not None:
            _hz = float(hz)
        if max_stacks is not None:
            _max_stacks = int(max_stacks)
        if _enabled:
            return
        global _saved_switch_interval
        cur = sys.getswitchinterval()
        if cur > _SWITCH_INTERVAL_S:
            _saved_switch_interval = cur
            sys.setswitchinterval(_SWITCH_INTERVAL_S)
        _stop_evt.clear()
        _started_unix = time.time()
        _thread = threading.Thread(
            target=_sampler_main, name="tt-profiler", daemon=True
        )
        _enabled = True
        _thread.start()


def disable() -> None:
    """Kill switch: stop the sampler and JOIN it — after return there
    is no surviving profiler thread and no further samples."""
    global _enabled, _thread, _saved_switch_interval
    with _lifecycle_lock:
        if not _enabled:
            return
        _enabled = False
        _stop_evt.set()
        t = _thread
        _thread = None
        if t is not None and t.is_alive():
            # tmlive: block-ok — bounded by the sampler's 1/hz tick
            # (the stop event is already set) plus the join timeout
            t.join(timeout=5.0)
        if _saved_switch_interval is not None:
            sys.setswitchinterval(_saved_switch_interval)
            _saved_switch_interval = None


def is_enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop every aggregated sample (tests; fresh profile windows)."""
    global _samples_total, _collapsed_total
    with _agg_lock:
        _agg.clear()
        _samples_total = 0
        _collapsed_total = 0


# -- export ---------------------------------------------------------------


def snapshot(max_entries: Optional[int] = None) -> List[Dict[str, Any]]:
    """Aggregated stacks, highest count first:
    {role, task, stack (root-first, ';'-joined), subsystem, count}."""
    with _agg_lock:
        items = sorted(
            _agg.items(), key=lambda kv: (-kv[1], kv[0])
        )
    if max_entries is not None:
        items = items[:max_entries]
    return [
        {
            "role": role,
            "task": label,
            "stack": stack,
            "subsystem": subsystem,
            "count": count,
        }
        for (role, label, stack, subsystem), count in items
    ]


def folded(max_entries: Optional[int] = None) -> List[str]:
    """Collapsed-stack lines (`role;[task;]frame;... count`) — the
    flamegraph.pl / speedscope input format, consumed by
    scripts/profile_report.py."""
    out = []
    for e in snapshot(max_entries):
        head = f"{e['role']};{e['task']};" if e["task"] else f"{e['role']};"
        out.append(f"{head}{e['stack']} {e['count']}")
    return out


def subsystem_counts() -> Dict[str, int]:
    """Raw sample counts per subsystem bucket. Cumulative since the
    last reset — harnesses diff two readings to isolate a window."""
    with _agg_lock:
        totals: Dict[str, int] = {}
        for (role, label, stack, subsystem), count in _agg.items():
            totals[subsystem] = totals.get(subsystem, 0) + count
    return dict(sorted(totals.items()))


def subsystem_shares() -> Dict[str, float]:
    """Fraction of all samples per subsystem bucket (sums to 1.0 when
    any samples exist) — the bottleneck ledger's raw material."""
    totals = subsystem_counts()
    grand = sum(totals.values())
    if grand == 0:
        return {}
    return {k: v / grand for k, v in totals.items()}


def stats() -> Dict[str, Any]:
    """Profiler status: sampling state, rates, table pressure."""
    with _agg_lock:
        n_stacks = len(_agg)
    return {
        "enabled": _enabled,
        "labels_armed": _armed,
        "hz": _hz,
        "samples_total": _samples_total,
        "stacks": n_stacks,
        "max_stacks": _max_stacks,
        "collapsed_samples": _collapsed_total,
        "started_unix": _started_unix if _enabled else 0.0,
    }


def to_profile_json() -> str:
    """Export for the debug bundle's `profile.json`: status + the full
    aggregated table + subsystem shares."""
    return json.dumps(
        {
            "stats": stats(),
            "subsystem_shares": subsystem_shares(),
            "stacks": snapshot(),
        },
        default=str,
    )
