"""Prometheus-style metrics: registry, instruments, text exposition.

reference: the go-kit prometheus metrics threaded through every
subsystem (internal/consensus/metrics.go, internal/p2p/metrics.go,
internal/mempool/metrics.go, internal/state/metrics.go; catalog in
docs/nodes/metrics.md:21-53) and the node-served endpoint
(node/node.go:606). Zero-dependency implementation of the subset those
use: Counter, Gauge, Histogram with static label names, rendered in the
Prometheus text exposition format (version 0.0.4).

Per-node registries, matching the reference's threading: each subsystem
exposes a go-kit-style Metrics struct (consensus/metrics.py,
mempool/metrics.py, p2p/metrics.py, state/metrics.py) built against a
Registry. Node assembly (node/node.py) constructs one Registry per node
and threads the structs through the constructors, so in-process
localnet nodes scrape disjoint series. DEFAULT_REGISTRY remains the
default for subsystems constructed without an explicit registry (and
for genuinely process-global instruments: the device verifier's tpu_*
family and the verified-signature cache's sigcache_* family — one
device runtime and one cache per process), so call sites outside the
constructors are unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "DEFAULT_REGISTRY",
    "new_counter",
    "new_gauge",
    "new_histogram",
]


def _escape_label(v: str) -> str:
    """Text-format escaping: backslash, quote, newline — one corrupt
    label value must not make the whole scrape unparseable."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(names: Sequence[str], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class _Metric:
    kind = ""

    def __init__(
        self, name: str, help_: str, label_names: Sequence[str] = ()
    ) -> None:
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def with_labels(self, **labels: str):
        """Bound child for a label combination."""
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._child(key)

    def _child(self, key: Tuple[str, ...]):
        raise NotImplementedError

    def render(self) -> List[str]:
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            # Prometheus counters are monotonic; a negative inc would
            # silently corrupt every rate() over the series
            raise ValueError(
                f"counter {self.name} cannot decrease (inc by {amount})"
            )
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(v)}"
            )
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            self._values[key] = float(value)

    def add(self, amount: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(v)}"
            )
        return out


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name,
        help_,
        label_names=(),
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        # key -> (per-bucket counts, sum, count)
        self._values: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            entry = self._values.get(key)
            if entry is None:
                entry = [[0] * len(self.buckets), 0.0, 0]
                self._values[key] = entry
            counts, _, _ = entry
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            entry[1] += value
            entry[2] += 1

    def time(self, **labels: str):
        """Context manager observing elapsed seconds."""
        return _Timer(self, labels)

    def count(self, **labels: str) -> int:
        key = tuple(str(labels[n]) for n in self.label_names)
        entry = self._values.get(key)
        return entry[2] if entry else 0

    def sum(self, **labels: str) -> float:
        key = tuple(str(labels[n]) for n in self.label_names)
        entry = self._values.get(key)
        return entry[1] if entry else 0.0

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, (counts, total, n) in items:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum = counts[i]
                lbls = dict(zip(self.label_names, key))
                lbls["le"] = _fmt_value(float(b))
                names = list(self.label_names) + ["le"]
                vals = tuple(lbls[x] for x in names)
                out.append(
                    f"{self.name}_bucket{_fmt_labels(names, vals)} {cum}"
                )
            names = list(self.label_names) + ["le"]
            vals = tuple(list(key) + ["+Inf"])
            out.append(
                f"{self.name}_bucket{_fmt_labels(names, vals)} {n}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(total)}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(self.label_names, key)} {n}"
            )
        return out


class _Timer:
    def __init__(self, hist: Histogram, labels: dict) -> None:
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(
            time.perf_counter() - self._t0, **self.labels
        )
        return False


class Registry:
    """Named collection rendered as one exposition document."""

    def __init__(self, namespace: str = "tendermint_tpu") -> None:
        self.namespace = namespace
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        """Idempotent for an identical spec (node restarts in-process
        return the live instrument); a CONFLICTING re-registration —
        same name, different kind, label names, or buckets — raises,
        because the typo'd duplicate would silently record into the
        wrong series."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if (
                    existing.kind != metric.kind
                    or existing.label_names != metric.label_names
                    or getattr(existing, "buckets", None)
                    != getattr(metric, "buckets", None)
                ):
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{existing.kind}{existing.label_names} "
                        f"(conflicts with {metric.kind}"
                        f"{metric.label_names})"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def names(self) -> frozenset:
        with self._lock:
            return frozenset(self._metrics)

    def full_name(self, subsystem: str, name: str) -> str:
        return f"{self.namespace}_{subsystem}_{name}"

    def counter(
        self, subsystem: str, name: str, help_: str, label_names=()
    ) -> Counter:
        return self.register(
            Counter(self.full_name(subsystem, name), help_, label_names)
        )

    def gauge(
        self, subsystem: str, name: str, help_: str, label_names=()
    ) -> Gauge:
        return self.register(
            Gauge(self.full_name(subsystem, name), help_, label_names)
        )

    def histogram(
        self, subsystem: str, name: str, help_: str, label_names=(),
        buckets=None,
    ) -> Histogram:
        return self.register(
            Histogram(
                self.full_name(subsystem, name),
                help_,
                label_names,
                buckets=buckets or _DEFAULT_BUCKETS,
            )
        )

    def render(self, exclude=frozenset()) -> str:
        """The exposition document; `exclude` skips series by full name
        (node/node.py merges the per-node registry with the
        process-global one without emitting duplicate series)."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.name in exclude:
                continue
            lines.extend(m.render())
        return "\n".join(lines) + "\n" if lines else ""


DEFAULT_REGISTRY = Registry()


def new_counter(
    subsystem: str, name: str, help_: str, label_names=()
) -> Counter:
    return DEFAULT_REGISTRY.counter(subsystem, name, help_, label_names)


def new_gauge(subsystem: str, name: str, help_: str, label_names=()) -> Gauge:
    return DEFAULT_REGISTRY.gauge(subsystem, name, help_, label_names)


def new_histogram(
    subsystem: str, name: str, help_: str, label_names=(), buckets=None
) -> Histogram:
    return DEFAULT_REGISTRY.histogram(
        subsystem, name, help_, label_names, buckets=buckets
    )
