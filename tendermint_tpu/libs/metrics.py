"""Prometheus-style metrics: registry, instruments, text exposition.

reference: the go-kit prometheus metrics threaded through every
subsystem (internal/consensus/metrics.go, internal/p2p/metrics.go,
internal/mempool/metrics.go, internal/state/metrics.go; catalog in
docs/nodes/metrics.md:21-53) and the node-served endpoint
(node/node.go:606). Zero-dependency implementation of the subset those
use: Counter, Gauge, Histogram with static label names, rendered in the
Prometheus text exposition format (version 0.0.4).

Per-node registries, matching the reference's threading: each subsystem
exposes a go-kit-style Metrics struct (consensus/metrics.py,
mempool/metrics.py, p2p/metrics.py, state/metrics.py) built against a
Registry. Node assembly (node/node.py) constructs one Registry per node
and threads the structs through the constructors, so in-process
localnet nodes scrape disjoint series. DEFAULT_REGISTRY remains the
default for subsystems constructed without an explicit registry (and
for genuinely process-global instruments: the device verifier's tpu_*
family and the verified-signature cache's sigcache_* family — one
device runtime and one cache per process), so call sites outside the
constructors are unchanged.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LatencySketch",
    "Sketch",
    "Registry",
    "DEFAULT_REGISTRY",
    "new_counter",
    "new_gauge",
    "new_histogram",
    "new_sketch",
]


def _escape_label(v: str) -> str:
    """Text-format escaping: backslash, quote, newline — one corrupt
    label value must not make the whole scrape unparseable."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(names: Sequence[str], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class _Metric:
    kind = ""

    def __init__(
        self, name: str, help_: str, label_names: Sequence[str] = ()
    ) -> None:
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def with_labels(self, **labels: str):
        """Bound child for a label combination."""
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._child(key)

    def _child(self, key: Tuple[str, ...]):
        raise NotImplementedError

    def render(self) -> List[str]:
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            # Prometheus counters are monotonic; a negative inc would
            # silently corrupt every rate() over the series
            raise ValueError(
                f"counter {self.name} cannot decrease (inc by {amount})"
            )
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(v)}"
            )
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            self._values[key] = float(value)

    def add(self, amount: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(v)}"
            )
        return out


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name,
        help_,
        label_names=(),
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        # key -> (per-bucket counts, sum, count)
        self._values: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            entry = self._values.get(key)
            if entry is None:
                entry = [[0] * len(self.buckets), 0.0, 0]
                self._values[key] = entry
            counts, _, _ = entry
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            entry[1] += value
            entry[2] += 1

    def time(self, **labels: str):
        """Context manager observing elapsed seconds."""
        return _Timer(self, labels)

    def count(self, **labels: str) -> int:
        key = tuple(str(labels[n]) for n in self.label_names)
        entry = self._values.get(key)
        return entry[2] if entry else 0

    def sum(self, **labels: str) -> float:
        key = tuple(str(labels[n]) for n in self.label_names)
        entry = self._values.get(key)
        return entry[1] if entry else 0.0

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, (counts, total, n) in items:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum = counts[i]
                lbls = dict(zip(self.label_names, key))
                lbls["le"] = _fmt_value(float(b))
                names = list(self.label_names) + ["le"]
                vals = tuple(lbls[x] for x in names)
                out.append(
                    f"{self.name}_bucket{_fmt_labels(names, vals)} {cum}"
                )
            names = list(self.label_names) + ["le"]
            vals = tuple(list(key) + ["+Inf"])
            out.append(
                f"{self.name}_bucket{_fmt_labels(names, vals)} {n}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(total)}"
            )
            out.append(
                f"{self.name}_count{_fmt_labels(self.label_names, key)} {n}"
            )
        return out


class _Timer:
    def __init__(self, hist: Histogram, labels: dict) -> None:
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(
            time.perf_counter() - self._t0, **self.labels
        )
        return False


class LatencySketch:
    """Mergeable log-bucketed latency sketch (DDSketch/HDR-style).

    Values land in geometric buckets `(gamma**(i-1), gamma**i]` with
    `gamma = (1+eps)/(1-eps)`; a bucket's reported value is its
    harmonic midpoint `2*gamma**i/(gamma+1)`, so every quantile
    estimate is within **relative error `eps`** (default 1%) of the
    sample the same nearest-rank rule would pick from the sorted data —
    for values inside `[min_value, max_value]` (outside, the value is
    clamped to the edge bucket and the bound does not hold; the
    defaults cover 1 µs .. ~28 h of latency). Memory is bounded by the
    bucket-index range: `ceil(log(max/min)/log(gamma)) + 1` buckets
    (~1.2k at eps=1%), independent of observation count.

    Sketches with identical `(relative_error, min_value, max_value)`
    merge exactly (bucket-wise count addition): per-worker or per-node
    sketches combine into fleet quantiles without re-recording — the
    property ad-hoc "sort all the samples" percentile math lacks once
    samples outlive one process. merge() is associative and
    commutative; quantiles of a merged sketch carry the same eps bound.
    """

    __slots__ = (
        "relative_error",
        "min_value",
        "max_value",
        "_gamma",
        "_log_gamma",
        "_min_idx",
        "_max_idx",
        "_counts",
        "_count",
        "_sum",
        "_min",
        "_max",
        "_lock",
    )

    def __init__(
        self,
        relative_error: float = 0.01,
        min_value: float = 1e-6,
        max_value: float = 1e5,
    ) -> None:
        if not 0.0 < relative_error < 1.0:
            raise ValueError(
                f"relative_error must be in (0, 1): {relative_error}"
            )
        if not 0.0 < min_value < max_value:
            raise ValueError(
                f"need 0 < min_value < max_value: {min_value}, {max_value}"
            )
        self.relative_error = relative_error
        self.min_value = min_value
        self.max_value = max_value
        self._gamma = (1.0 + relative_error) / (1.0 - relative_error)
        self._log_gamma = math.log(self._gamma)
        self._min_idx = math.ceil(math.log(min_value) / self._log_gamma)
        self._max_idx = math.ceil(math.log(max_value) / self._log_gamma)
        # bucket index -> count; key range is clamped to
        # [_min_idx, _max_idx], so the dict is bounded at ~1.2k entries
        # regardless of how many values are recorded
        # tmlive: bounded= keys clamped to the fixed index range
        # [_min_idx, _max_idx] (~1.2k log buckets at eps=1%)
        self._counts: Dict[int, int] = {}
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def _index(self, value: float) -> int:
        if value <= self.min_value:
            return self._min_idx
        i = math.ceil(math.log(value) / self._log_gamma)
        if i > self._max_idx:
            return self._max_idx
        return i

    def _value_of(self, idx: int) -> float:
        return 2.0 * self._gamma ** idx / (self._gamma + 1.0)

    def record(self, value: float) -> None:
        """Record one observation (seconds, bytes, depth — any
        positive-ish magnitude; <= 0 clamps into the lowest bucket)."""
        v = float(value)
        i = self._index(v)
        with self._lock:
            self._counts[i] = self._counts.get(i, 0) + 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> float:
        return self._min if self._count else 0.0

    @property
    def max(self) -> float:
        return self._max if self._count else 0.0

    def _compatible(self, other: "LatencySketch") -> bool:
        return (
            self.relative_error == other.relative_error
            and self.min_value == other.min_value
            and self.max_value == other.max_value
        )

    def merge(self, other: "LatencySketch") -> "LatencySketch":
        """Fold `other`'s observations into this sketch (in place).
        Both must share bucket parameters — merging sketches with
        different error bounds would silently produce neither bound."""
        if not self._compatible(other):
            raise ValueError(
                "cannot merge sketches with different parameters: "
                f"(eps={self.relative_error}, range=[{self.min_value}, "
                f"{self.max_value}]) vs (eps={other.relative_error}, "
                f"range=[{other.min_value}, {other.max_value}])"
            )
        with other._lock:
            counts = dict(other._counts)
            o_count, o_sum = other._count, other._sum
            o_min, o_max = other._min, other._max
        with self._lock:
            for i, c in counts.items():
                self._counts[i] = self._counts.get(i, 0) + c
            self._count += o_count
            self._sum += o_sum
            if o_min < self._min:
                self._min = o_min
            if o_max > self._max:
                self._max = o_max
        return self

    def snapshot(self) -> "LatencySketch":
        """An independent point-in-time copy (safe to merge/quantile
        while the original keeps recording)."""
        out = LatencySketch(
            self.relative_error, self.min_value, self.max_value
        )
        with self._lock:
            out._counts = dict(self._counts)
            out._count = self._count
            out._sum = self._sum
            out._min = self._min
            out._max = self._max
        return out

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile estimate: the bucket holding the
        `ceil(q*count)`-th smallest observation, reported at the bucket
        midpoint (within `relative_error` of the true sample for
        in-range values). Returns 0.0 on an empty sketch."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1]: {q}")
        with self._lock:
            if self._count == 0:
                return 0.0
            rank = max(1, math.ceil(q * self._count))
            cum = 0
            for i in sorted(self._counts):
                cum += self._counts[i]
                if cum >= rank:
                    return self._value_of(i)
        return self._value_of(self._max_idx)  # pragma: no cover

    def quantiles(self, qs: Sequence[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    def to_dict(self) -> dict:
        """JSON-encodable form (BENCH_LOAD rows, cross-process merge)."""
        with self._lock:
            return {
                "relative_error": self.relative_error,
                "min_value": self.min_value,
                "max_value": self.max_value,
                "counts": {str(i): c for i, c in self._counts.items()},
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else 0.0,
                "max": self._max if self._count else 0.0,
            }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencySketch":
        out = cls(
            float(d["relative_error"]),
            float(d["min_value"]),
            float(d["max_value"]),
        )
        out._counts = {int(i): int(c) for i, c in d["counts"].items()}
        out._count = int(d["count"])
        out._sum = float(d["sum"])
        if out._count:
            out._min = float(d["min"])
            out._max = float(d["max"])
        return out


class Sketch(_Metric):
    """Registry instrument wrapping one LatencySketch per label set,
    rendered as a Prometheus `summary` (quantile series + _sum +
    _count). Where Histogram answers "how many under 100 ms", Sketch
    answers "what IS p999" — with a documented error bound and
    mergeable children (`sketch()` hands out the live LatencySketch)."""

    kind = "summary"

    QUANTILES = (0.5, 0.9, 0.99, 0.999)

    def __init__(
        self,
        name,
        help_,
        label_names=(),
        relative_error: float = 0.01,
    ):
        super().__init__(name, help_, label_names)
        self.relative_error = relative_error
        self._values: Dict[Tuple[str, ...], LatencySketch] = {}

    def _child(self, key: Tuple[str, ...]) -> LatencySketch:
        with self._lock:
            sk = self._values.get(key)
            if sk is None:
                sk = LatencySketch(self.relative_error)
                self._values[key] = sk
            return sk

    def sketch(self, **labels: str) -> LatencySketch:
        """The live per-label-set sketch (record/merge/quantile)."""
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._child(key)

    def observe(self, value: float, **labels: str) -> None:
        self.sketch(**labels).record(value)

    def quantile(self, q: float, **labels: str) -> float:
        return self.sketch(**labels).quantile(q)

    def count(self, **labels: str) -> int:
        return self.sketch(**labels).count

    def merged(self) -> LatencySketch:
        """All label sets folded into one sketch (fleet view)."""
        out = LatencySketch(self.relative_error)
        with self._lock:
            children = list(self._values.values())
        for sk in children:
            out.merge(sk.snapshot())
        return out

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, sk in items:
            snap = sk.snapshot()
            names = list(self.label_names) + ["quantile"]
            for q in self.QUANTILES:
                vals = tuple(list(key) + [str(q)])
                out.append(
                    f"{self.name}{_fmt_labels(names, vals)}"
                    f" {_fmt_value(snap.quantile(q))}"
                )
            out.append(
                f"{self.name}_sum{_fmt_labels(self.label_names, key)}"
                f" {_fmt_value(snap.sum)}"
            )
            out.append(
                f"{self.name}_count"
                f"{_fmt_labels(self.label_names, key)} {snap.count}"
            )
        return out


class Registry:
    """Named collection rendered as one exposition document."""

    def __init__(self, namespace: str = "tendermint_tpu") -> None:
        self.namespace = namespace
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        """Idempotent for an identical spec (node restarts in-process
        return the live instrument); a CONFLICTING re-registration —
        same name, different kind, label names, or buckets — raises,
        because the typo'd duplicate would silently record into the
        wrong series."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if (
                    existing.kind != metric.kind
                    or existing.label_names != metric.label_names
                    or getattr(existing, "buckets", None)
                    != getattr(metric, "buckets", None)
                    or getattr(existing, "relative_error", None)
                    != getattr(metric, "relative_error", None)
                ):
                    raise ValueError(
                        f"metric {metric.name!r} already registered as "
                        f"{existing.kind}{existing.label_names} "
                        f"(conflicts with {metric.kind}"
                        f"{metric.label_names})"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def names(self) -> frozenset:
        with self._lock:
            return frozenset(self._metrics)

    def full_name(self, subsystem: str, name: str) -> str:
        return f"{self.namespace}_{subsystem}_{name}"

    def counter(
        self, subsystem: str, name: str, help_: str, label_names=()
    ) -> Counter:
        return self.register(
            Counter(self.full_name(subsystem, name), help_, label_names)
        )

    def gauge(
        self, subsystem: str, name: str, help_: str, label_names=()
    ) -> Gauge:
        return self.register(
            Gauge(self.full_name(subsystem, name), help_, label_names)
        )

    def histogram(
        self, subsystem: str, name: str, help_: str, label_names=(),
        buckets=None,
    ) -> Histogram:
        return self.register(
            Histogram(
                self.full_name(subsystem, name),
                help_,
                label_names,
                buckets=buckets or _DEFAULT_BUCKETS,
            )
        )

    def sketch(
        self,
        subsystem: str,
        name: str,
        help_: str,
        label_names=(),
        relative_error: float = 0.01,
    ) -> Sketch:
        return self.register(
            Sketch(
                self.full_name(subsystem, name),
                help_,
                label_names,
                relative_error=relative_error,
            )
        )

    def render(self, exclude=frozenset()) -> str:
        """The exposition document; `exclude` skips series by full name
        (node/node.py merges the per-node registry with the
        process-global one without emitting duplicate series)."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.name in exclude:
                continue
            lines.extend(m.render())
        return "\n".join(lines) + "\n" if lines else ""


DEFAULT_REGISTRY = Registry()


def new_counter(
    subsystem: str, name: str, help_: str, label_names=()
) -> Counter:
    return DEFAULT_REGISTRY.counter(subsystem, name, help_, label_names)


def new_gauge(subsystem: str, name: str, help_: str, label_names=()) -> Gauge:
    return DEFAULT_REGISTRY.gauge(subsystem, name, help_, label_names)


def new_histogram(
    subsystem: str, name: str, help_: str, label_names=(), buckets=None
) -> Histogram:
    return DEFAULT_REGISTRY.histogram(
        subsystem, name, help_, label_names, buckets=buckets
    )


def new_sketch(
    subsystem: str,
    name: str,
    help_: str,
    label_names=(),
    relative_error: float = 0.01,
) -> Sketch:
    return DEFAULT_REGISTRY.sketch(
        subsystem, name, help_, label_names, relative_error=relative_error
    )
