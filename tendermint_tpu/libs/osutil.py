"""Filesystem helpers (reference: internal/libs/tempfile)."""

from __future__ import annotations

import os

__all__ = ["atomic_write"]


def atomic_write(path: str, data: str, mode: int = 0o600) -> None:
    """Write-fsync-rename-fsync(dir) so the file is never torn and the
    rename is crash-durable (reference: internal/libs/tempfile/tempfile.go
    WriteFileAtomic; key/state files are 0600 like privval/file.go).

    Deliberately synchronous: callers (privval sign-state, node key)
    must never proceed before the bytes are on disk.
    """
    tmp = path + ".tmp"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
