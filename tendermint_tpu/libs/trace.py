"""Span tracing: nestable context managers over a bounded in-memory ring.

Zero-dependency sibling of libs/metrics.py. Where metrics answer "how
often / how long on average", spans answer "what happened inside THIS
call": each `span(name, **attrs)` records one timed interval with its
parent (nesting follows the asyncio task / thread via contextvars), so
a single commit verification decomposes into
addVote -> batch_accumulate -> tpu_dispatch -> merkle_hash with
per-stage attributes (batch size, pad waste, host-prep vs device-wall
split, and the verified-signature cache's sigcache_hits /
sigcache_misses on batch_accumulate — the count of triples that skipped
crypto entirely vs. those actually assembled into the batch). PERF.md's claim discipline is the motivation: device sessions
die mid-run, so every surviving number must be attributable to a stage.

Completed spans land in a bounded ring (old spans are evicted, never
blocked on) and export as Chrome-trace JSON (chrome://tracing /
Perfetto "traceEvents" format). Spans can additionally feed an existing
metrics Histogram (`span(..., hist=h)`), replacing `h.time()` at the
call site; the histogram is observed whether or not tracing is enabled.

Tracing is OFF by default. The disabled path is consensus-grade cheap:
`span()` returns a shared no-op singleton — no Span object, no ring
write, no contextvar touch.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, List, Optional

__all__ = [
    "DEFAULT_CAPACITY",
    "NOOP_SPAN",
    "Span",
    "add_attrs",
    "current",
    "disable",
    "enable",
    "is_enabled",
    "reset",
    "set_capacity",
    "snapshot",
    "span",
    "to_chrome_trace",
]

DEFAULT_CAPACITY = 8192

_enabled = False
# deque.append is atomic in CPython — writers never take a lock; the
# lock only guards ring replacement (set_capacity/reset vs export).
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
_ring_lock = threading.Lock()
_next_id = itertools.count(1).__next__
_current: ContextVar[Optional["Span"]] = ContextVar(
    "tt_trace_current", default=None
)
# perf_counter epoch: Chrome-trace ts is relative anyway, and
# perf_counter is the only clock monotonic enough to nest spans.
_EPOCH = time.perf_counter()


class Span:
    """One timed interval. Use as a context manager; re-entry is not
    supported (spans are one-shot, like the histograms they feed)."""

    __slots__ = (
        "name",
        "attrs",
        "span_id",
        "parent_id",
        "tid",
        "start_us",
        "dur_us",
        "_hist",
        "_hist_labels",
        "_t0",
        "_token",
    )

    def __init__(
        self,
        name: str,
        hist=None,
        hist_labels: Optional[Dict[str, str]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.attrs: Dict[str, Any] = attrs if attrs is not None else {}
        self.span_id = _next_id()
        self.parent_id = 0
        self.tid = 0
        self.start_us = 0.0
        self.dur_us = 0.0
        self._hist = hist
        self._hist_labels = hist_labels
        self._t0 = 0.0
        self._token = None

    def set(self, **attrs: Any) -> "Span":
        """Attach attributes mid-span (batch sizes known only after
        accumulation, device timings known only after gather)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        parent = _current.get()
        if parent is not None:
            self.parent_id = parent.span_id
        self.tid = threading.get_ident()
        self._token = _current.set(self)
        self._t0 = time.perf_counter()
        self.start_us = (self._t0 - _EPOCH) * 1e6
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        self.dur_us = (t1 - self._t0) * 1e6
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if self._hist is not None:
            self._hist.observe(
                t1 - self._t0, **(self._hist_labels or {})
            )
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        if _enabled:
            # tmlint: disable=lock-global-mutation — deque.append is
            # GIL-atomic; _ring_lock guards ring *replacement* only
            # (module docstring, line ~55)
            _ring.append(self)
        return False


class _NoopSpan:
    """Shared do-nothing span: the disabled path allocates no Span and
    touches neither the ring nor the contextvar."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def span(name: str, hist=None, hist_labels=None, **attrs: Any):
    """A nestable timed span. With `hist`, the elapsed seconds are also
    observed into that Histogram — so instrumented call sites keep
    their metrics series when tracing is off (the span then degrades to
    exactly `hist.time()`)."""
    if not _enabled:
        if hist is not None:
            return hist.time(**(hist_labels or {}))
        return NOOP_SPAN
    return Span(name, hist, hist_labels, attrs)


def add_attrs(**attrs: Any) -> None:
    """Attach attributes to the innermost live span, if any. A no-op
    when tracing is disabled or no span is open — hot paths call this
    unconditionally."""
    s = _current.get()
    if s is not None:
        s.attrs.update(attrs)


def current() -> Optional[Span]:
    """The innermost live span of this task/thread (None if tracing is
    off or no span is open)."""
    return _current.get()


def enable(capacity: Optional[int] = None) -> None:
    """Turn the recorder on (optionally resizing the ring first)."""
    global _enabled
    if capacity is not None:
        set_capacity(capacity)
    _enabled = True


def disable() -> None:
    """Kill switch: spans created after this return the no-op
    singleton; spans already open stop recording at exit."""
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def set_capacity(capacity: int) -> None:
    """Resize the ring, keeping the most recent spans."""
    global _ring
    if capacity < 1:
        raise ValueError(f"trace ring capacity must be >= 1: {capacity}")
    with _ring_lock:
        _ring = deque(_ring, maxlen=capacity)


def reset() -> None:
    """Drop every recorded span (tests; debug-dump isolation)."""
    with _ring_lock:
        _ring.clear()


def snapshot() -> List[Span]:
    """The recorded spans, oldest first."""
    with _ring_lock:
        return list(_ring)


def to_chrome_trace() -> str:
    """Export the ring as Chrome-trace JSON ("traceEvents" complete
    events, loadable in chrome://tracing and Perfetto). `span_id` /
    `parent_id` ride in args so the exact nesting survives export even
    across interleaved asyncio tasks on one thread."""
    events = []
    for s in snapshot():
        args = dict(s.attrs)
        args["span_id"] = s.span_id
        args["parent_id"] = s.parent_id
        events.append(
            {
                "name": s.name,
                "ph": "X",
                "ts": round(s.start_us, 3),
                "dur": round(s.dur_us, 3),
                "pid": 0,
                "tid": s.tid,
                "args": args,
            }
        )
    return json.dumps(
        {"traceEvents": events, "displayTimeUnit": "ms"}, default=str
    )
