"""Span tracing: nestable context managers over a bounded in-memory ring.

Zero-dependency sibling of libs/metrics.py. Where metrics answer "how
often / how long on average", spans answer "what happened inside THIS
call": each `span(name, **attrs)` records one timed interval with its
parent (nesting follows the asyncio task / thread via contextvars), so
a single commit verification decomposes into
addVote -> batch_accumulate -> tpu_dispatch -> merkle_hash with
per-stage attributes (batch size, pad waste, host-prep vs device-wall
split, and the verified-signature cache's sigcache_hits /
sigcache_misses on batch_accumulate — the count of triples that skipped
crypto entirely vs. those actually assembled into the batch). PERF.md's claim discipline is the motivation: device sessions
die mid-run, so every surviving number must be attributable to a stage.

Completed spans land in a bounded ring (old spans are evicted, never
blocked on) and export as Chrome-trace JSON (chrome://tracing /
Perfetto "traceEvents" format). Spans can additionally feed an existing
metrics Histogram (`span(..., hist=h)`), replacing `h.time()` at the
call site; the histogram is observed whether or not tracing is enabled.

Tracing is OFF by default. The disabled path is consensus-grade cheap:
`span()` returns a shared no-op singleton — no Span object, no ring
write, no contextvar touch.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, List, Optional

__all__ = [
    "DEFAULT_CAPACITY",
    "DEFAULT_EXEMPLAR_CAPACITY",
    "NOOP_SPAN",
    "Span",
    "add_attrs",
    "current",
    "disable",
    "disable_exemplars",
    "enable",
    "enable_exemplars",
    "exemplar_snapshot",
    "exemplars_enabled",
    "exemplars_to_json",
    "is_enabled",
    "record_slow_request",
    "reset",
    "reset_exemplars",
    "set_capacity",
    "set_exemplar_capacity",
    "snapshot",
    "span",
    "to_chrome_trace",
]

DEFAULT_CAPACITY = 8192
DEFAULT_EXEMPLAR_CAPACITY = 64

_enabled = False
# deque.append is atomic in CPython — writers never take a lock; the
# lock only guards ring replacement (set_capacity/reset vs export).
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
_ring_lock = threading.Lock()
_next_id = itertools.count(1).__next__
_current: ContextVar[Optional["Span"]] = ContextVar(
    "tt_trace_current", default=None
)
# perf_counter epoch: Chrome-trace ts is relative anyway, and
# perf_counter is the only clock monotonic enough to nest spans.
_EPOCH = time.perf_counter()


class Span:
    """One timed interval. Use as a context manager; re-entry is not
    supported (spans are one-shot, like the histograms they feed)."""

    __slots__ = (
        "name",
        "attrs",
        "span_id",
        "parent_id",
        "tid",
        "start_us",
        "dur_us",
        "_hist",
        "_hist_labels",
        "_t0",
        "_token",
    )

    def __init__(
        self,
        name: str,
        hist=None,
        hist_labels: Optional[Dict[str, str]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.attrs: Dict[str, Any] = attrs if attrs is not None else {}
        self.span_id = _next_id()
        self.parent_id = 0
        self.tid = 0
        self.start_us = 0.0
        self.dur_us = 0.0
        self._hist = hist
        self._hist_labels = hist_labels
        self._t0 = 0.0
        self._token = None

    def set(self, **attrs: Any) -> "Span":
        """Attach attributes mid-span (batch sizes known only after
        accumulation, device timings known only after gather)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        parent = _current.get()
        if parent is not None:
            self.parent_id = parent.span_id
        self.tid = threading.get_ident()
        self._token = _current.set(self)
        self._t0 = time.perf_counter()
        self.start_us = (self._t0 - _EPOCH) * 1e6
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        self.dur_us = (t1 - self._t0) * 1e6
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if self._hist is not None:
            self._hist.observe(
                t1 - self._t0, **(self._hist_labels or {})
            )
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        if _enabled:
            # tmlint: disable=lock-global-mutation — deque.append is
            # GIL-atomic; _ring_lock guards ring *replacement* only
            # (module docstring, line ~55)
            _ring.append(self)
        return False


class _NoopSpan:
    """Shared do-nothing span: the disabled path allocates no Span and
    touches neither the ring nor the contextvar."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def span(name: str, hist=None, hist_labels=None, **attrs: Any):
    """A nestable timed span. With `hist`, the elapsed seconds are also
    observed into that Histogram — so instrumented call sites keep
    their metrics series when tracing is off (the span then degrades to
    exactly `hist.time()`)."""
    if not _enabled:
        if hist is not None:
            return hist.time(**(hist_labels or {}))
        return NOOP_SPAN
    return Span(name, hist, hist_labels, attrs)


def add_attrs(**attrs: Any) -> None:
    """Attach attributes to the innermost live span, if any. A no-op
    when tracing is disabled or no span is open — hot paths call this
    unconditionally."""
    s = _current.get()
    if s is not None:
        s.attrs.update(attrs)


def current() -> Optional[Span]:
    """The innermost live span of this task/thread (None if tracing is
    off or no span is open)."""
    return _current.get()


def enable(capacity: Optional[int] = None) -> None:
    """Turn the recorder on (optionally resizing the ring first)."""
    global _enabled
    if capacity is not None:
        set_capacity(capacity)
    _enabled = True


def disable() -> None:
    """Kill switch: spans created after this return the no-op
    singleton; spans already open stop recording at exit."""
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def set_capacity(capacity: int) -> None:
    """Resize the ring, keeping the most recent spans."""
    global _ring
    if capacity < 1:
        raise ValueError(f"trace ring capacity must be >= 1: {capacity}")
    with _ring_lock:
        _ring = deque(_ring, maxlen=capacity)


def reset() -> None:
    """Drop every recorded span (tests; debug-dump isolation)."""
    with _ring_lock:
        _ring.clear()


def snapshot() -> List[Span]:
    """The recorded spans, oldest first."""
    with _ring_lock:
        return list(_ring)


# -- slow-request SLO exemplars ------------------------------------------
#
# When an RPC request blows past its per-route SLO threshold
# (rpc/metrics.py slo_for), the server captures the request's span
# subtree from the ring into this second bounded ring — so a p99
# outlier in the latency sketch arrives with its own flame
# decomposition instead of a bare number. Kill-switched exactly like
# the span recorder itself (off by default; `record_slow_request` is a
# cheap boolean check when disabled), and capacity-bounded (old
# exemplars are evicted, never blocked on). With span tracing disabled
# the exemplar still records route/duration/threshold — just with an
# empty span tree.

_exemplars_enabled = False
_exemplars: deque = deque(maxlen=DEFAULT_EXEMPLAR_CAPACITY)
_exemplar_lock = threading.Lock()


def enable_exemplars(capacity: Optional[int] = None) -> None:
    """Turn slow-request exemplar capture on (optionally resizing)."""
    global _exemplars_enabled
    if capacity is not None:
        set_exemplar_capacity(capacity)
    _exemplars_enabled = True


def disable_exemplars() -> None:
    """Kill switch: record_slow_request becomes a no-op."""
    global _exemplars_enabled
    _exemplars_enabled = False


def exemplars_enabled() -> bool:
    return _exemplars_enabled


def set_exemplar_capacity(capacity: int) -> None:
    """Resize the exemplar ring, keeping the most recent entries."""
    global _exemplars
    if capacity < 1:
        raise ValueError(
            f"exemplar ring capacity must be >= 1: {capacity}"
        )
    with _exemplar_lock:
        _exemplars = deque(_exemplars, maxlen=capacity)


def reset_exemplars() -> None:
    """Drop every captured exemplar (tests; debug-dump isolation)."""
    with _exemplar_lock:
        _exemplars.clear()


def _span_dict(s: Span) -> Dict[str, Any]:
    return {
        "name": s.name,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "start_us": round(s.start_us, 3),
        "dur_us": round(s.dur_us, 3),
        "attrs": dict(s.attrs),
    }


def record_slow_request(
    route: str, dur_s: float, threshold_s: float, root=None
) -> None:
    """Capture one SLO-breach exemplar. `root` is the request's Span
    (anything else — the no-op singleton, a histogram timer — yields an
    exemplar without a tree). The root's recorded descendants are
    collected from the span ring; children exit before their parent, so
    the newest-first walk sees the root, then its children, then their
    children. O(ring) per capture — SLO breaches are rare by
    definition, and the disabled path is one boolean check."""
    if not _exemplars_enabled:
        return
    spans = []
    if isinstance(root, Span):
        ids = {root.span_id}
        for s in reversed(snapshot()):
            if s.span_id in ids or s.parent_id in ids:
                ids.add(s.span_id)
                spans.append(_span_dict(s))
        spans.reverse()  # chronological (oldest first)
    exemplar = {
        "route": route,
        "dur_ms": round(dur_s * 1e3, 3),
        "slo_ms": round(threshold_s * 1e3, 3),
        "spans": spans,
    }
    # tmlint: disable=lock-global-mutation — deque.append is
    # GIL-atomic; _exemplar_lock guards ring *replacement* only (same
    # contract as the span ring above)
    _exemplars.append(exemplar)


def exemplar_snapshot() -> List[Dict[str, Any]]:
    """The captured exemplars, oldest first."""
    with _exemplar_lock:
        return list(_exemplars)


def exemplars_to_json() -> str:
    """Export the exemplar ring (debug bundle `slow_requests.json`)."""
    return json.dumps(
        {"slow_requests": exemplar_snapshot()}, default=str
    )


def to_chrome_trace() -> str:
    """Export the ring as Chrome-trace JSON ("traceEvents" complete
    events, loadable in chrome://tracing and Perfetto). `span_id` /
    `parent_id` ride in args so the exact nesting survives export even
    across interleaved asyncio tasks on one thread."""
    events = []
    for s in snapshot():
        args = dict(s.attrs)
        args["span_id"] = s.span_id
        args["parent_id"] = s.parent_id
        events.append(
            {
                "name": s.name,
                "ph": "X",
                "ts": round(s.start_us, 3),
                "dur": round(s.dur_us, 3),
                "pid": 0,
                "tid": s.tid,
                "args": args,
            }
        )
    return json.dumps(
        {"traceEvents": events, "displayTimeUnit": "ms"}, default=str
    )
