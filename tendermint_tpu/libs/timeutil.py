"""Integer-nanosecond <-> float-second conversion, OUTSIDE the
consensus-critical tree.

Consensus code does its time math in integer nanoseconds (tmlint's
det-float rule: IEEE-754 results vary with evaluation order and
platform, so floats may never feed sign-bytes/hash/encode input).
Floats only exist at the process boundaries — asyncio timeouts,
metrics observations, config files — and the conversions live here so
a consensus module never contains float arithmetic of its own.
"""

from __future__ import annotations

NS_PER_S = 1_000_000_000


def ns_to_s(ns: int) -> float:
    """Nanoseconds -> float seconds (asyncio/metrics boundary)."""
    return ns / NS_PER_S


def s_to_ns(s: float) -> int:
    """Float seconds (config/API boundary) -> integer nanoseconds."""
    return int(round(s * NS_PER_S))
