"""Seeded concurrency-schedule exploration.

The reference's CI runs its entire suite under `go test -race`
(Makefile test targets): every goroutine interleaving the scheduler
happens to pick is a free race probe. This framework's concurrency
model is different — single-writer asyncio loops fed by queues — so
its race surface is ORDERING: which inputs land first, interleaved
how, duplicated or delayed. This module is the reusable analog: run
one scenario under many seeded random delivery schedules and assert
the OUTCOME is schedule-independent (or that stated invariants hold
under every ordering).

Every failure names the seed, so any exploration result reproduces
exactly: `Schedule(seed)` rebuilds the identical schedule.

Usage (see tests/test_schedule_fuzz.py for real scenarios):

    async def scenario(sched: Schedule):
        plan = sched.with_dups(sched.shuffled(inputs), 3)
        for msg in plan:
            deliver(msg)
            await sched.yield_point()
        return await observed_outcome()

    await explore(scenario, schedules=8, base_seed=100)
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Awaitable, Callable, Iterable, List, Sequence

__all__ = ["Schedule", "explore"]


class Schedule:
    """One seeded delivery schedule: shuffle/duplicate/interleave
    helpers plus cooperative yield points, all driven by a single
    `random.Random(seed)` so the schedule is reproducible from the
    seed alone."""

    def __init__(self, seed: int) -> None:
        self.seed = seed
        self.rng = random.Random(seed)

    def shuffled(self, items: Iterable[Any]) -> List[Any]:
        out = list(items)
        self.rng.shuffle(out)
        return out

    def with_dups(self, items: Sequence[Any], k: int) -> List[Any]:
        """Append k duplicates of random elements — byte-identical
        redelivery, the gossip-dup path."""
        out = list(items)
        if out:
            out += [
                out[self.rng.randrange(len(out))] for _ in range(k)
            ]
        return out

    def interleave(self, *seqs: Sequence[Any]) -> List[Any]:
        """Random merge that PRESERVES each sequence's internal order —
        the shape of real concurrency: per-source FIFO, cross-source
        interleaving chosen by the scheduler."""
        pools = [list(s) for s in seqs if s]
        out: List[Any] = []
        while pools:
            i = self.rng.randrange(len(pools))
            out.append(pools[i].pop(0))
            if not pools[i]:
                pools.pop(i)
        return out

    def subseed(self, label: str) -> int:
        """A deterministic child seed for a sibling source of seeded
        randomness (e.g. a crypto.faults rule riding along with this
        delivery schedule): a pure function of (seed, label), so the
        combined exploration still reproduces from the one seed the
        failure message names — and independent of how much of THIS
        schedule's rng was consumed before the sibling was armed."""
        from . import rng

        return rng.subseed(self.seed, label)

    def seed_gossip(self) -> None:
        """Pin the process-wide gossip RNG (libs/rng.py — part/vote
        pick order in the reactors and BitArray.pick_random) to this
        schedule, so a scenario that drives real gossip replays its
        picks from the one named seed. explore() calls this before
        every scenario run; standalone scenarios call it themselves."""
        from . import rng

        rng.reseed(self.subseed("gossip"))

    async def yield_point(self, p: float = 0.5) -> None:
        """With probability p, yield the event loop 1-2 times so other
        tasks interleave here."""
        if self.rng.random() < p:
            for _ in range(self.rng.randrange(1, 3)):
                await asyncio.sleep(0)


async def explore(
    scenario: Callable[[Schedule], Awaitable[Any]],
    *,
    schedules: int = 8,
    base_seed: int = 0,
) -> Any:
    """Run `scenario` under `schedules` seeded schedules; every outcome
    must be equal (use a constant return + internal asserts for
    invariant-style scenarios). Failures name the seed that triggered
    them — to reproduce standalone, build `Schedule(seed)` AND call
    its `seed_gossip()` (explore() does both; the gossip RNG is part
    of the schedule). Returns the common outcome."""
    from . import rng

    outcomes: List[tuple] = []
    try:
        for i in range(schedules):
            seed = base_seed + i
            sched = Schedule(seed)
            sched.seed_gossip()
            try:
                out = await scenario(sched)
            except Exception as e:  # not BaseException: cancellation and
                # KeyboardInterrupt must propagate as themselves, not
                # masquerade as seed-reproducible scenario failures
                raise AssertionError(
                    f"schedule-fuzz scenario failed under seed={seed} "
                    f"(reproduce with sched = Schedule({seed}); "
                    f"sched.seed_gossip() — the gossip RNG is part of "
                    f"the schedule): {e!r}"
                ) from e
            outcomes.append((seed, out))
    finally:
        rng.reseed(None)  # hand the gossip RNG back to OS entropy
    ref_seed, ref = outcomes[0]
    for seed, out in outcomes[1:]:
        if out != ref:
            raise AssertionError(
                "outcome depends on the delivery schedule: "
                f"seed {ref_seed} -> {ref!r}, seed {seed} -> {out!r}"
            )
    return ref
