"""Constant-time comparison helpers for secret material.

A plain `==` on bytes short-circuits at the first mismatching byte —
the comparison's duration is a function of the secret prefix it
matched. Anywhere a secret (key bytes, nonces, MACs) is compared, the
tmct gate (scripts/lint.py --ct, rule ct-secret-compare) requires the
comparison to route through here instead.

Pure Python cannot promise cycle-constancy; what `bytes_eq` promises
is *structure*: the CPython primitive `hmac.compare_digest` scans the
full length of both operands regardless of where they differ, so the
data-dependent short-circuit — the part a remote timing adversary can
integrate over many probes — is gone (docs/static_analysis.md, "why
Python constant-time means structure, not cycles").
"""

from __future__ import annotations

import hmac as _hmac

__all__ = ["bytes_eq"]


def bytes_eq(a: bytes, b: bytes) -> bool:
    """Compare two byte strings without a secret-dependent
    short-circuit. The boolean result is public by contract — callers
    branch on it freely (the *decision* is published behavior; the
    *path to it* is what must not leak)."""
    return _hmac.compare_digest(bytes(a), bytes(b))
