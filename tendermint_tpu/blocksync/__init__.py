"""Block sync — catch up to the chain head by fetching verified blocks.

reference: internal/blocksync/.
"""

from .msgs import (
    BlockRequestMessage,
    BlockResponseMessage,
    BlocksyncCodec,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
)
from .pool import BlockPool
from .reactor import (
    BLOCKSYNC_CHANNEL,
    BlocksyncReactor,
    blocksync_channel_descriptor,
)

__all__ = [
    "BLOCKSYNC_CHANNEL",
    "BlockPool",
    "BlockRequestMessage",
    "BlockResponseMessage",
    "BlocksyncCodec",
    "BlocksyncReactor",
    "NoBlockResponseMessage",
    "StatusRequestMessage",
    "StatusResponseMessage",
    "blocksync_channel_descriptor",
]
