"""Block sync reactor — catch up to the chain head, then hand off to
consensus.

reference: internal/blocksync/reactor.go. Serves BlockRequests from the
block store, feeds responses into the pool, and runs the verification
pipeline: block H is verified with the LastCommit inside block H+1 via
VerifyCommitLight — the batched device-verify showcase during catch-up —
then applied through the BlockExecutor.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..libs.log import get_logger
from ..libs.service import Service
from ..p2p.channel import Channel
from ..p2p.peermanager import PeerStatus
from ..p2p.types import ChannelDescriptor, Envelope, PeerError
from ..state.execution import BlockExecutor
from ..state.types import State
from ..store.block_store import BlockStore
from ..types.block_id import BlockID
from ..types.validation import verify_commit_light
from .msgs import (
    BlockRequestMessage,
    BlockResponseMessage,
    BlocksyncCodec,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
)
from .pool import BlockPool

__all__ = [
    "BlocksyncReactor",
    "BLOCKSYNC_CHANNEL",
    "blocksync_channel_descriptor",
]

BLOCKSYNC_CHANNEL = 0x40
_STATUS_UPDATE_INTERVAL = 2.0


def blocksync_channel_descriptor():
    """reference: reactor.go:66-75."""
    return ChannelDescriptor(
        channel_id=BLOCKSYNC_CHANNEL,
        message_type=BlocksyncCodec,
        priority=5,
        send_queue_capacity=1000,
        recv_buffer_capacity=1024,
        name="blocksync",
    )


class BlocksyncReactor(Service):
    def __init__(
        self,
        state: State,
        block_exec: BlockExecutor,
        block_store: BlockStore,
        channel: Channel,
        peer_updates: asyncio.Queue,
        block_sync: bool = True,  # start in sync mode?
        consensus_reactor=None,  # switch target when caught up
        event_bus=None,
    ) -> None:
        super().__init__(name="blocksync", logger=get_logger("blocksync"))
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.channel = channel
        self.peer_updates = peer_updates
        self.block_sync = block_sync
        self.consensus_reactor = consensus_reactor
        self.event_bus = event_bus
        start_height = state.last_block_height + 1
        if start_height == 1:
            start_height = state.initial_height
        self.pool = BlockPool(start_height, self._request_block)
        self.synced = False

    async def on_start(self) -> None:
        self.spawn(self._recv_routine(), "recv")
        self.spawn(self._peer_update_routine(), "peer-updates")
        if self.block_sync:
            await self._start_sync_routines()

    async def on_stop(self) -> None:
        if self.pool.is_running:
            await self.pool.stop()

    async def start_sync(self, state: State) -> None:
        """Begin block sync from a statesync-bootstrapped state
        (reference: node wiring bcReactor.SwitchToBlockSync after
        stateSyncReactor.Sync)."""
        self.state = state
        self.block_sync = True
        start = state.last_block_height + 1
        self.pool.height = max(self.pool.height, start)
        await self._start_sync_routines()

    async def _start_sync_routines(self) -> None:
        # idempotent: two concurrent pool routines would double-apply blocks
        if getattr(self, "_sync_routines_started", False):
            return
        self._sync_routines_started = True
        if not self.pool.is_running:
            await self.pool.start()
        self.spawn(self._pool_routine(), "pool")
        self.spawn(self._status_routine(), "status")

    def _request_block(self, height: int, peer_id: str) -> None:
        self.channel.try_send(
            Envelope(message=BlockRequestMessage(height=height), to=peer_id)
        )

    # -- inbound --

    async def _recv_routine(self) -> None:
        async for envelope in self.channel:
            try:
                await self._handle_msg(envelope)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.error(
                    "failed to process blocksync message", err=str(e)
                )
                await self.channel.send_error(
                    PeerError(node_id=envelope.from_peer, err=str(e))
                )

    async def _handle_msg(self, envelope: Envelope) -> None:
        """reference: reactor.go:236-320 handleMessage."""
        msg = envelope.message
        peer_id = envelope.from_peer
        if isinstance(msg, BlockRequestMessage):
            block = self.block_store.load_block(msg.height)
            if block is not None:
                self.channel.try_send(
                    Envelope(
                        message=BlockResponseMessage(block=block), to=peer_id
                    )
                )
            else:
                self.channel.try_send(
                    Envelope(
                        message=NoBlockResponseMessage(height=msg.height),
                        to=peer_id,
                    )
                )
        elif isinstance(msg, BlockResponseMessage):
            if msg.block is not None:
                self.pool.add_block(peer_id, msg.block)
        elif isinstance(msg, NoBlockResponseMessage):
            pass  # requester will time out and retry another peer
        elif isinstance(msg, StatusRequestMessage):
            self.channel.try_send(
                Envelope(
                    message=StatusResponseMessage(
                        height=self.block_store.height(),
                        base=self.block_store.base(),
                    ),
                    to=peer_id,
                )
            )
        elif isinstance(msg, StatusResponseMessage):
            self.pool.set_peer_range(peer_id, msg.base, msg.height)
        else:
            raise ValueError(
                f"unexpected blocksync message {type(msg).__name__}"
            )

    async def _peer_update_routine(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP:
                # learn the peer's range; offer ours
                self.channel.try_send(
                    Envelope(
                        message=StatusRequestMessage(), to=update.node_id
                    )
                )
                self.channel.try_send(
                    Envelope(
                        message=StatusResponseMessage(
                            height=self.block_store.height(),
                            base=self.block_store.base(),
                        ),
                        to=update.node_id,
                    )
                )
            elif update.status == PeerStatus.DOWN:
                self.pool.remove_peer(update.node_id)

    async def _status_routine(self) -> None:
        while True:
            await asyncio.sleep(_STATUS_UPDATE_INTERVAL)
            self.channel.try_send(
                Envelope(message=StatusRequestMessage(), broadcast=True)
            )

    # -- the sync pipeline (reference: reactor.go:322-450 poolRoutine) --

    async def _pool_routine(self) -> None:
        while True:
            if self.pool.is_caught_up():
                await self._switch_to_consensus()
                return
            first, second = self.pool.peek_two_blocks()
            if first is None or second is None:
                await asyncio.sleep(0.05)
                continue
            await self._verify_apply(first, second)

    async def _verify_apply(self, first, second) -> None:
        """Verify `first` with `second.LastCommit`, then apply
        (reference: reactor.go:452-520)."""
        first_parts = first.make_part_set()
        first_id = BlockID(
            hash=first.hash(), part_set_header=first_parts.header()
        )
        try:
            # the whole LastCommit of block H+1 in one device batch call
            verify_commit_light(
                self.state.chain_id,
                self.state.validators,
                first_id,
                first.header.height,
                second.last_commit,
            )
        except Exception as e:
            self.logger.error(
                "invalid last commit during block sync",
                height=first.header.height,
                err=str(e),
            )
            # punish both providers and refetch
            for peer_id in (
                self.pool.first_block_peer(),
                self.pool.second_block_peer(),
            ):
                if peer_id:
                    self.pool.ban_peer(peer_id)
                    await self.channel.send_error(
                        PeerError(node_id=peer_id, err=f"bad block: {e}")
                    )
            self.pool.redo_request(first.header.height)
            return

        self.block_store.save_block(first, first_parts, second.last_commit)
        self.state = await self.block_exec.apply_block(
            self.state, first_id, first
        )
        self.pool.pop_request()
        if self.pool.height % 100 == 0:
            self.logger.info(
                "block-synced", height=self.pool.height,
                target=self.pool.max_peer_height,
            )

    async def _switch_to_consensus(self) -> None:
        """reference: reactor.go poolRoutine switch branch +
        consensus/reactor.go:252 SwitchToConsensus."""
        self.synced = True
        self.block_sync = False
        self.logger.info(
            "caught up; switching to consensus",
            height=self.state.last_block_height,
        )
        if self.event_bus is not None:
            from ..types import events as E

            self.event_bus.publish_block_sync_status(
                E.EventDataBlockSyncStatus(
                    complete=True, height=self.state.last_block_height
                )
            )
        if self.pool.is_running:
            await self.pool.stop()
        if self.consensus_reactor is not None:
            # rebuild LastCommit from the stored seen-commit, then roll the
            # round state forward (reference: consensus/reactor.go:252-306)
            cs = self.consensus_reactor.cs
            if self.state.last_block_height > 0:
                cs._reconstruct_last_commit_from_store(self.state)
            cs._update_to_state(self.state)
            await self.consensus_reactor.switch_to_consensus(self.state)
