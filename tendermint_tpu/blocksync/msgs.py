"""Block sync wire messages (channel 0x40).

reference: proto/tendermint/blocksync/types.pb.go — BlockRequest,
NoBlockResponse, BlockResponse, StatusRequest, StatusResponse and the
Message oneof (fields 1-5).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..encoding.proto import FieldReader, ProtoWriter
from ..types.block import Block

__all__ = [
    "BlockRequestMessage",
    "NoBlockResponseMessage",
    "BlockResponseMessage",
    "StatusRequestMessage",
    "StatusResponseMessage",
    "BlocksyncCodec",
]


@dataclass
class BlockRequestMessage:
    height: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockRequestMessage":
        return cls(height=FieldReader(data).int64(1))


@dataclass
class NoBlockResponseMessage:
    height: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "NoBlockResponseMessage":
        return cls(height=FieldReader(data).int64(1))


@dataclass
class BlockResponseMessage:
    block: Optional[Block] = None

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.block.to_proto() if self.block else None)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockResponseMessage":
        b = FieldReader(data).get(1)
        return cls(block=Block.from_proto(b) if b is not None else None)


@dataclass
class StatusRequestMessage:
    def to_proto(self) -> bytes:
        return b""

    @classmethod
    def from_proto(cls, data: bytes) -> "StatusRequestMessage":
        return cls()


@dataclass
class StatusResponseMessage:
    height: int = 0
    base: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.base)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "StatusResponseMessage":
        r = FieldReader(data)
        return cls(height=r.int64(1), base=r.int64(2))


_FIELDS = {
    1: BlockRequestMessage,
    2: NoBlockResponseMessage,
    3: BlockResponseMessage,
    4: StatusRequestMessage,
    5: StatusResponseMessage,
}
_FIELD_OF = {cls: num for num, cls in _FIELDS.items()}


class BlocksyncCodec:
    @staticmethod
    def encode(msg) -> bytes:
        num = _FIELD_OF.get(type(msg))
        if num is None:
            raise TypeError(f"unknown blocksync message {type(msg).__name__}")
        w = ProtoWriter()
        w.message(num, msg.to_proto())
        return w.finish()

    @staticmethod
    def decode(data: bytes):
        r = FieldReader(data)
        for num, cls in _FIELDS.items():
            body = r.get(num)
            if body is not None:
                return cls.from_proto(body)
        raise ValueError("empty or unknown blocksync Message envelope")
