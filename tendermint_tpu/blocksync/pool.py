"""BlockPool — parallel block fetching with ordered delivery.

reference: internal/blocksync/pool.go (:98-348). Per-height requester
tasks fan out over peers advertising the height; blocks come back out in
strict height order via peek_two_blocks so the reactor can verify block
H with the LastCommit carried in block H+1.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set, Tuple

from ..libs import rng
from ..libs.log import get_logger
from ..libs.service import Service
from ..types.block import Block

__all__ = ["BlockPool"]

MAX_PENDING_REQUESTS = 32  # heights in flight
REQUEST_TIMEOUT = 10.0  # per-attempt fetch timeout
_CAUGHT_UP_GRACE_S = 3.0  # don't declare caught-up in the first seconds


@dataclass
class _PoolPeer:
    peer_id: str
    height: int = 0
    base: int = 0
    banned: bool = False


class BlockPool(Service):
    def __init__(
        self,
        start_height: int,
        send_request: Callable[[int, str], None],  # (height, peer_id)
    ) -> None:
        super().__init__(name="blockpool", logger=get_logger("blocksync.pool"))
        self.height = start_height  # next height to verify/apply
        self._send_request = send_request
        self.peers: Dict[str, _PoolPeer] = {}
        self.max_peer_height = 0
        self._blocks: Dict[int, Tuple[Block, str]] = {}  # height → (block, peer)
        self._requesters: Dict[int, asyncio.Task] = {}
        self._block_events: Dict[int, asyncio.Event] = {}
        self._started_at = 0.0

    async def on_start(self) -> None:
        self._started_at = time.monotonic()
        self.spawn(self._make_requesters_routine(), "make-requesters")

    # -- peer bookkeeping --

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """From StatusResponse (reference: pool.go SetPeerRange)."""
        peer = self.peers.get(peer_id)
        if peer is None:
            peer = _PoolPeer(peer_id=peer_id)
            self.peers[peer_id] = peer
        peer.base = base
        peer.height = height
        self.max_peer_height = max(
            (p.height for p in self.peers.values() if not p.banned), default=0
        )

    def remove_peer(self, peer_id: str) -> None:
        """Received blocks are kept; live requesters retry other peers."""
        self.peers.pop(peer_id, None)
        self.max_peer_height = max(
            (p.height for p in self.peers.values() if not p.banned), default=0
        )

    def ban_peer(self, peer_id: str) -> None:
        """Sent us a bad block (reference: pool.go RedoRequest path)."""
        peer = self.peers.get(peer_id)
        if peer is not None:
            peer.banned = True
        self.max_peer_height = max(
            (p.height for p in self.peers.values() if not p.banned), default=0
        )

    # -- block intake --

    def add_block(self, peer_id: str, block: Block) -> None:
        """reference: pool.go:280-305 AddBlock."""
        h = block.header.height
        if h < self.height or h in self._blocks:
            return
        if h not in self._requesters:
            return  # unsolicited height
        self._blocks[h] = (block, peer_id)
        ev = self._block_events.get(h)
        if ev is not None:
            ev.set()

    # -- ordered consumption (reference: pool.go:218-260) --

    def peek_two_blocks(self) -> Tuple[Optional[Block], Optional[Block]]:
        first = self._blocks.get(self.height)
        second = self._blocks.get(self.height + 1)
        return (
            first[0] if first else None,
            second[0] if second else None,
        )

    def first_block_peer(self) -> Optional[str]:
        first = self._blocks.get(self.height)
        return first[1] if first else None

    def second_block_peer(self) -> Optional[str]:
        second = self._blocks.get(self.height + 1)
        return second[1] if second else None

    def pop_request(self) -> None:
        """Block at self.height verified and applied; advance."""
        h = self.height
        self._blocks.pop(h, None)
        t = self._requesters.pop(h, None)
        if t is not None and not t.done():
            t.cancel()
        self._block_events.pop(h, None)
        self.height = h + 1
        self._tasks = [x for x in self._tasks if not x.done()]

    def redo_request(self, height: int) -> None:
        """Verification failed: drop fetched blocks from this height up and
        refetch from other peers (reference: pool.go RedoRequest)."""
        for h in list(self._blocks.keys()):
            if h >= height:
                block, peer_id = self._blocks.pop(h)
                ev = self._block_events.get(h)
                if ev is not None:
                    ev.clear()
                # requester for h is still alive and will refetch

    def is_caught_up(self) -> bool:
        """reference: pool.go:200-216."""
        if not self.peers:
            return False
        if time.monotonic() - self._started_at < _CAUGHT_UP_GRACE_S:
            return False
        return self.height >= self.max_peer_height

    # -- requesters --

    async def _make_requesters_routine(self) -> None:
        while True:
            pending = len(self._requesters)
            if (
                pending < MAX_PENDING_REQUESTS
                and self.height + pending <= self.max_peer_height
            ):
                h = self.height + pending
                if h not in self._requesters:
                    self._block_events[h] = asyncio.Event()
                    self._requesters[h] = self.spawn(
                        self._requester(h), f"req-{h}"
                    )
                    continue
            await asyncio.sleep(0.02)

    async def _requester(self, height: int) -> None:
        """Fetch `height` from some peer; retry across peers until a block
        arrives (reference: pool.go bpRequester:415-470)."""
        tried: Set[str] = set()
        while True:
            peer = self._pick_peer(height, tried)
            if peer is None:
                tried.clear()  # all peers tried; start over
                await asyncio.sleep(1.0)
                continue
            tried.add(peer.peer_id)
            self._send_request(height, peer.peer_id)
            ev = self._block_events.get(height)
            if ev is None:
                return
            # asyncio.wait, not wait_for: on Python 3.10, wait_for
            # swallows a cancellation that races the event being set
            # (bpo-42130 family), leaving this requester alive forever
            # and hanging Service.stop()'s gather. wait() re-raises the
            # outer cancel unconditionally.
            waiter = asyncio.ensure_future(ev.wait())
            try:
                done, _pending = await asyncio.wait(
                    {waiter}, timeout=REQUEST_TIMEOUT
                )
            finally:
                waiter.cancel()
            if waiter not in done:
                continue  # timeout: try another peer
            # block arrived (possibly from redo_request → cleared event)
            while height in self._blocks:
                await asyncio.sleep(0.1)
                if height < self.height:
                    return  # consumed
            if height < self.height:
                return
            ev.clear()  # redo_request dropped it; refetch

    def _pick_peer(self, height: int, tried: Set[str]) -> Optional[_PoolPeer]:
        candidates = [
            p
            for p in self.peers.values()
            if not p.banned
            and p.height >= height
            and (p.base == 0 or p.base <= height)
            and p.peer_id not in tried
        ]
        if not candidates:
            return None
        return rng.choice(candidates)
