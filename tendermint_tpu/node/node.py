"""Node assembly — compose every subsystem into a runnable node.

reference: node/node.go:116-412 (makeNode), node/setup.go (initDBs,
createPeerManager, createRouter, create*Reactor), node/public.go (New).

Wiring order mirrors the reference: DBs → stores → genesis → device
verifier install → proxy app → event bus + indexer → privval → ABCI
handshake → peer manager / router → mempool/evidence/consensus/
blocksync/statesync reactors → start. The TPU-backed BatchVerifier is
installed from config *before* any verification path runs, so the
served path (consensus LastCommit checks, blocksync VerifyCommitLight,
statesync light-block verification) all dispatch through the device
seam (reference plugin boundary: crypto/crypto.go:53-61).
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional

from ..abci.client import local_creator, socket_creator
from ..abci.kvstore import KVStoreApplication
from ..abci.proxy import AppConns
from ..config import (
    MODE_SEED,
    MODE_VALIDATOR,
    Config,
)
from ..consensus import ConsensusState
from ..consensus.reactor import (
    ConsensusReactor,
    consensus_channel_descriptors,
)
from ..consensus.replay import Handshaker
from ..consensus.wal import WAL
from ..crypto import tpu_verifier
from ..eventbus import EventBus, EventBusMetrics
from ..consensus.metrics import ConsensusMetrics
from ..evidence import (
    EvidenceMetrics,
    EvidencePool,
    EvidenceReactor,
    evidence_channel_descriptor,
)
from ..libs.log import get_logger
from ..libs.metrics import Registry
from ..libs.service import Service
from ..mempool import TxMempool
from ..mempool.metrics import MempoolMetrics
from ..mempool.reactor import MempoolReactor, mempool_channel_descriptor
from ..p2p.metrics import P2PMetrics
from ..p2p.peermanager import PeerManager, PeerManagerOptions
from ..p2p.router import Router, RouterOptions
from ..p2p.transport import TCPTransport, Transport
from ..p2p.types import NodeInfo
from ..privval import FilePV
from ..state import StateStore, state_from_genesis
from ..state.execution import BlockExecutor
from ..state.indexer import IndexerService, KVSink, NullSink
from ..state.metrics import StateMetrics
from ..store.block_store import BlockStore
from ..store.kv import open_db
from ..types.genesis import GenesisDoc
from .key import NodeKey

__all__ = ["Node", "make_node"]


class Node(Service):
    """A full node (validator or not), assembled from a Config.

    reference: node/node.go nodeImpl. Construction (make_node) is
    synchronous and cheap; everything with I/O ordering constraints
    (proxy start, ABCI handshake, reactor startup, sync orchestration)
    happens in on_start.
    """

    def __init__(
        self,
        cfg: Config,
        genesis: GenesisDoc,
        app=None,
        transport: Optional[Transport] = None,
    ) -> None:
        super().__init__(name="node", logger=get_logger("node"))
        self.cfg = cfg
        self.genesis = genesis
        genesis.validate_and_complete()

        # -- per-node metrics registry (reference: each subsystem's
        # go-kit Metrics struct threaded from node/setup.go). Every node
        # gets its own registry so in-process localnet embeddings scrape
        # disjoint series; process-global instruments (the device
        # verifier's tpu_* family) stay on DEFAULT_REGISTRY and are
        # merged into the scrape without duplication.
        self.metrics_registry = Registry()

        # span tracing is process-wide (one ring); any node asking for
        # it turns it on
        if cfg.instrumentation.trace_spans:
            from ..libs import trace

            trace.enable(capacity=cfg.instrumentation.trace_ring_capacity)
        # ditto the slow-request exemplar ring (SLO-breach span trees,
        # surfaced in the debug bundle; see docs/load.md)
        if cfg.instrumentation.slo_exemplars:
            from ..libs import trace

            trace.enable_exemplars(
                capacity=cfg.instrumentation.slo_exemplar_capacity
            )
        # wall-clock sampling profiler (libs/profiler.py): process-wide
        # like the trace ring. Arming labels alone is near-free and
        # lets a profile started later (RPC `profile` route) attribute
        # loop samples to the pumps spawned now; actually *sampling*
        # starts only when cfg asks. The enabling node owns the
        # stop-and-join at teardown.
        self._profiler_owner = False
        if cfg.instrumentation.profiler_labels or cfg.instrumentation.profiler:
            from ..libs import profiler

            profiler.arm_labels()
        if cfg.instrumentation.profiler:
            from ..libs import profiler

            # a cfg-owned profile is per-run: drop samples a previous
            # in-process run (bench A/B, back-to-back localnets) left
            profiler.reset()
            profiler.enable(
                hz=cfg.instrumentation.profiler_hz,
                max_stacks=cfg.instrumentation.profiler_max_stacks,
            )
            self._profiler_owner = True

        # -- device verifier install (the north-star seam) --
        # Done first so every later verification dispatches through it.
        # Install state is process-global (one device runtime per
        # process); warn when two in-process nodes disagree on policy.
        if cfg.tpu.enable:
            prior = tpu_verifier.installed()
            if prior is not None and prior != cfg.tpu.min_batch_size:
                self.logger.info(
                    "tpu verifier already installed with a different "
                    "min_batch; overriding process-wide",
                    prior=prior, new=cfg.tpu.min_batch_size,
                )
            tpu_verifier.install(
                min_batch=cfg.tpu.min_batch_size,
                mesh=self._device_mesh(cfg.tpu.devices),
            )
            from ..ops import merkle_kernel

            merkle_kernel.install()
        elif tpu_verifier.installed() is not None:
            self.logger.info(
                "tpu.enable=false but the device verifier is already "
                "installed process-wide by another node; it stays active"
            )

        # -- DBs + stores (reference: node/setup.go initDBs) --
        backend = cfg.base.db_backend
        db_dir = cfg.base.path(cfg.base.db_dir)
        self._dbs = []

        def _db(name: str):
            db = open_db(name, backend, db_dir)
            self._dbs.append(db)
            return db

        self.block_store = BlockStore(_db("blockstore"))
        self.state_store = StateStore(_db("state"))
        self._evidence_db = _db("evidence")

        # -- proxy app (reference: internal/proxy) --
        if cfg.base.abci == "builtin":
            self._app = app if app is not None else KVStoreApplication()
            creator = local_creator(self._app)
        elif cfg.base.abci == "socket":
            self._app = None
            creator = socket_creator(cfg.base.proxy_app, must_connect=True)
        elif cfg.base.abci == "grpc":
            from ..abci.grpc_transport import grpc_creator

            self._app = None
            creator = grpc_creator(cfg.base.proxy_app, must_connect=True)
        else:
            raise ValueError(f"unknown abci mode {cfg.base.abci!r}")
        self.proxy = AppConns(creator)

        # -- event bus + indexer --
        self.event_bus = EventBus(
            metrics=EventBusMetrics(self.metrics_registry)
        )
        sinks = []
        for kind in cfg.tx_index.indexer:
            if kind == "kv":
                sinks.append(KVSink(_db("tx_index")))
            elif kind == "null":
                sinks.append(NullSink())
            elif kind == "psql":
                # reference: indexer/sink/psql — SQL schema sink
                from ..state.sink_sql import SQLSink

                dsn = cfg.tx_index.psql_conn or (
                    "sqlite:"
                    + os.path.join(
                        cfg.base.path(cfg.base.db_dir), "tx_index.sqlite"
                    )
                )
                sinks.append(
                    SQLSink(dsn, chain_id=self.genesis.chain_id)
                )
            else:
                raise ValueError(f"unknown indexer {kind!r}")
        self.indexer = IndexerService(sinks or [NullSink()], self.event_bus)

        # node identity key (also the privval listener's transport key)
        self.node_key = NodeKey.load_or_generate(
            cfg.base.path(cfg.base.node_key_file)
        )

        # -- privval (reference: node/setup.go createPrivval) --
        self.privval = None
        self.privval_listener = None
        self.privval_pub_key = None
        if cfg.base.mode == MODE_VALIDATOR:
            if cfg.priv_validator.listen_addr.startswith("grpc://"):
                # node dials a gRPC signer (reference: node/setup.go:586
                # "grpc" scheme -> DialRemoteSigner); started (and its
                # lifecycle owned) via privval_listener like the socket
                # variant
                from ..privval.grpc import GRPCSignerClient
                from ..privval.signer import RetrySignerClient

                client = GRPCSignerClient(cfg.priv_validator.listen_addr)
                self.privval_listener = client
                # same retry envelope as the socket path: a signer that
                # is not up yet (or blips) must not abort node.start();
                # refusals (double-sign) still propagate immediately
                self.privval = RetrySignerClient(client)
            elif cfg.priv_validator.listen_addr:
                # remote signer dials in (reference:
                # privval/signer_listener_endpoint.go via
                # createAndStartPrivValidatorSocketClient)
                from ..privval.signer import (
                    RetrySignerClient,
                    SignerListenerEndpoint,
                )

                self.privval_listener = SignerListenerEndpoint(
                    cfg.priv_validator.listen_addr,
                    self.node_key.priv_key,
                )
                self.privval = RetrySignerClient(self.privval_listener)
            else:
                self.privval = FilePV.load_or_generate(
                    cfg.base.path(cfg.priv_validator.key_file),
                    cfg.base.path(cfg.priv_validator.state_file),
                )

        # -- state --
        state = self.state_store.load()
        if state is None:
            state = state_from_genesis(genesis)
            self.state_store.save(state)
        self.initial_state = state

        # -- p2p (reference: node/setup.go createPeerManager/createRouter) --
        listen = cfg.p2p.laddr.replace("tcp://", "")
        advertise = (
            cfg.p2p.external_address.replace("tcp://", "")
            if cfg.p2p.external_address
            else listen
        )
        self.node_info = NodeInfo(
            node_id=self.node_key.node_id,
            listen_addr=advertise,
            network=genesis.chain_id,
            moniker=cfg.base.moniker,
        )
        persistent = [
            p.strip()
            for p in cfg.p2p.persistent_peers.split(",")
            if p.strip()
        ]
        p2p_metrics = P2PMetrics(self.metrics_registry)
        self.peer_manager = PeerManager(
            self.node_key.node_id,
            PeerManagerOptions(
                persistent_peers=persistent,
                max_connected=cfg.p2p.max_connections,
                min_retry_time=cfg.p2p.min_retry_time,
                max_retry_time=cfg.p2p.max_retry_time,
                max_retry_time_persistent=(
                    cfg.p2p.max_retry_time_persistent
                ),
            ),
            store=_db("peerstore"),
            metrics=p2p_metrics,
        )
        for addr in (
            a.strip() for a in cfg.p2p.bootstrap_peers.split(",")
        ):
            if addr:
                self.peer_manager.add(addr)
        self.transport = transport if transport is not None else TCPTransport()
        self.router = Router(
            self.node_info,
            self.node_key.priv_key,
            self.peer_manager,
            self.transport,
            listen_addr=listen,
            options=RouterOptions(
                handshake_timeout=cfg.p2p.handshake_timeout,
                dial_timeout=cfg.p2p.dial_timeout,
                send_rate=cfg.p2p.send_rate,
                recv_rate=cfg.p2p.recv_rate,
                ping_interval=cfg.p2p.ping_interval,
                pong_timeout=cfg.p2p.pong_timeout,
                max_incoming_per_ip=(
                    cfg.p2p.max_incoming_connection_attempts
                ),
                slow_peer_drop_threshold=(
                    cfg.p2p.slow_peer_drop_threshold
                ),
                slow_peer_window_s=cfg.p2p.slow_peer_window,
                slow_peer_ban_s=cfg.p2p.slow_peer_ban,
            ),
            metrics=p2p_metrics,
        )

        # reactors are built in on_start, after the ABCI handshake
        self.mempool: Optional[TxMempool] = None
        self.evidence_pool: Optional[EvidencePool] = None
        self.block_exec: Optional[BlockExecutor] = None
        self.consensus: Optional[ConsensusState] = None
        self.consensus_reactor: Optional[ConsensusReactor] = None
        self.mempool_reactor: Optional[MempoolReactor] = None
        self.evidence_reactor: Optional[EvidenceReactor] = None
        self.blocksync_reactor = None
        self.statesync_reactor = None
        self.pex_reactor = None
        self.rpc_server = None
        self.rpc_env = None
        self.genesis_state_synced = False

    # ------------------------------------------------------------------

    async def on_start(self) -> None:
        """reference: node/node.go OnStart :415-470. A failure partway
        through tears down whatever already started — Service.stop()
        won't call on_stop after a failed start."""
        self._acquire_data_lock()
        # bind this loop (from its own thread — we are on it) so
        # profiler samples of the loop thread sub-attribute to the
        # running task's labeled origin
        from ..libs import profiler

        profiler.register_loop()
        try:
            await self._start_impl()
        except BaseException:
            await self._teardown()
            raise

    @staticmethod
    def _device_mesh(devices: int):
        """The batch-sharding mesh from `[tpu] devices` (reference
        seam: the backend choice is config, not code —
        crypto/crypto.go:53-61). 1 -> None (single chip); 0 -> every
        visible device; n -> the first n (erroring if absent, since a
        silently smaller mesh would change bucket padding semantics)."""
        if devices == 1:
            return None
        if devices < 0:
            raise RuntimeError(f"[tpu] devices = {devices}: must be >= 0")
        import jax

        from ..parallel import make_mesh

        avail = jax.devices()
        if devices == 0:
            devices = len(avail)
        if devices == 1:
            return None
        if len(avail) < devices:
            raise RuntimeError(
                f"[tpu] devices = {devices} but only {len(avail)} "
                f"jax device(s) are visible"
            )
        return make_mesh(avail[:devices])

    def _acquire_data_lock(self) -> None:
        """Advisory data-dir lock: offline commands (reindex-event,
        rollback, reset) refuse to touch the DBs of a RUNNING node, and
        a second node process on the same home fails fast instead of
        corrupting stores. Same-pid locks are treated as stale so an
        in-process crash-restart (the replay tests' crash simulation)
        can reacquire."""
        data_dir = self.cfg.base.path(self.cfg.base.db_dir)
        self._lock_path = os.path.join(data_dir, "LOCK")
        self._lock_fd = acquire_pid_lock(
            self._lock_path, what=f"data dir {data_dir}"
        )

    def _release_data_lock(self) -> None:
        fd = getattr(self, "_lock_fd", None)
        if fd is not None:
            release_pid_lock(self._lock_path, fd)
            self._lock_fd = None

    async def _start_impl(self) -> None:
        cfg = self.cfg
        if cfg.base.mode == MODE_SEED:
            # seed nodes run ONLY peer exchange (reference: node/seed.go)
            await self._start_seed()
            return
        await self.proxy.start()
        await self.event_bus.start()
        await self.indexer.start()
        if self.privval_listener is not None:
            await self.privval_listener.start()
        # resolve the validator identity once: with a remote signer this
        # blocks until the signer dials in (reference: node/setup.go
        # createAndStartPrivValidatorSocketClient + GetPubKey)
        self.privval_pub_key = None
        if self.privval is not None:
            self.privval_pub_key = await self.privval.get_pub_key()

        # ABCI handshake: replay stored blocks into the app until app,
        # store, and state agree (reference: replay.go:240)
        handshaker = Handshaker(
            self.state_store,
            self.initial_state,
            self.block_store,
            self.genesis,
            event_bus=self.event_bus,
        )
        await handshaker.handshake(self.proxy.consensus)
        state = self.state_store.load()
        assert state is not None

        # -- build reactors against the post-handshake state --
        self.mempool = TxMempool(
            self.proxy.mempool,
            cfg.mempool,
            height=state.last_block_height,
            metrics=MempoolMetrics(self.metrics_registry),
        )
        self.evidence_pool = EvidencePool(
            self._evidence_db,
            self.state_store,
            self.block_store,
            metrics=EvidenceMetrics(self.metrics_registry),
        )
        self.block_exec = BlockExecutor(
            self.state_store,
            self.proxy.consensus,
            self.mempool,
            evidence_pool=self.evidence_pool,
            block_store=self.block_store,
            event_bus=self.event_bus,
            metrics=StateMetrics(self.metrics_registry),
        )
        wal = WAL(cfg.base.path(cfg.consensus.wal_file))
        cs_metrics = ConsensusMetrics(self.metrics_registry)
        # per-node flight recorder (consensus/timeline.py): the ring
        # the consensus_timeline RPC route and debug bundle serve,
        # feeding the quorum-latency/rounds/stall metrics above
        from ..consensus.timeline import TimelineRecorder

        timeline = TimelineRecorder(
            capacity=cfg.instrumentation.consensus_timeline_capacity,
            enabled=cfg.instrumentation.consensus_timeline,
            metrics=cs_metrics,
        )
        self.consensus = ConsensusState(
            cfg.consensus,
            state,
            self.block_exec,
            self.block_store,
            privval=self.privval,
            event_bus=self.event_bus,
            wal=wal,
            evidence_pool=self.evidence_pool,
            metrics=cs_metrics,
            timeline=timeline,
        )

        # sync orchestration flags (reference: node/node.go:230
        # onlyValidatorIsUs skips block sync entirely)
        state_sync = cfg.statesync.enable and state.last_block_height == 0
        block_sync = cfg.blocksync.enable and not self._only_validator_is_us(
            state
        )
        wait_sync = state_sync or block_sync

        cs_channels = {
            cid: self.router.open_channel(d)
            for cid, d in consensus_channel_descriptors().items()
        }
        self.consensus_reactor = ConsensusReactor(
            self.consensus,
            cs_channels,
            self.peer_manager.subscribe(),
            self.event_bus,
            cfg=cfg.consensus,
            wait_sync=wait_sync,
        )
        # byzantine adversary plane (consensus/byzantine.py): one
        # armed() check at assembly — a disarmed process (TM_TPU_BYZ
        # unset) installs nothing and pays nothing on any hot path
        from ..consensus import byzantine

        if byzantine.armed():
            byzantine.maybe_install(
                self.consensus, self.consensus_reactor, cfg.base.moniker
            )
        self.mempool_reactor = MempoolReactor(
            self.mempool,
            self.router.open_channel(mempool_channel_descriptor()),
            self.peer_manager.subscribe(),
        )
        self.evidence_reactor = EvidenceReactor(
            self.evidence_pool,
            self.router.open_channel(evidence_channel_descriptor()),
            self.peer_manager.subscribe(),
        )
        from ..blocksync import BlocksyncReactor, blocksync_channel_descriptor

        self.blocksync_reactor = BlocksyncReactor(
            state,
            self.block_exec,
            self.block_store,
            self.router.open_channel(blocksync_channel_descriptor()),
            self.peer_manager.subscribe(),
            block_sync=block_sync and not state_sync,
            consensus_reactor=self.consensus_reactor,
            event_bus=self.event_bus,
        )
        from ..statesync import StatesyncReactor, statesync_channel_descriptors

        self.statesync_reactor = StatesyncReactor(
            self.genesis.chain_id,
            state,
            self.proxy.snapshot,
            self.state_store,
            self.block_store,
            {
                cid: self.router.open_channel(d)
                for cid, d in statesync_channel_descriptors().items()
            },
            self.peer_manager.subscribe(),
            cfg=cfg.statesync,
        )

        if cfg.p2p.pex:
            from ..p2p.pex import PexReactor, pex_channel_descriptor

            self.pex_reactor = PexReactor(
                self.peer_manager,
                self.router.open_channel(pex_channel_descriptor()),
                self.peer_manager.subscribe(),
            )

        # -- start everything (channels are registered; safe to listen) --
        await self.router.start()
        await self.consensus_reactor.start()
        await self.mempool_reactor.start()
        await self.evidence_reactor.start()
        await self.blocksync_reactor.start()
        await self.statesync_reactor.start()
        if self.pex_reactor is not None:
            await self.pex_reactor.start()

        # -- RPC (reference: node/node.go:480-540 startRPC). The
        # Environment always exists — in-process consumers
        # (rpc.LocalClient) need it even when the network listener is
        # disabled; only the server is gated on rpc.laddr --
        from ..rpc import Environment, RPCServer
        from ..rpc.metrics import RPCMetrics

        self.rpc_env = Environment(
            chain_id=self.genesis.chain_id,
            block_store=self.block_store,
            state_store=self.state_store,
            mempool=self.mempool,
            event_bus=self.event_bus,
            consensus=self.consensus,
            consensus_reactor=self.consensus_reactor,
            peer_manager=self.peer_manager,
            proxy=self.proxy,
            genesis=self.genesis,
            evidence_pool=self.evidence_pool,
            event_sinks=self.indexer.sinks,
            node_info=self.node_info,
            privval_pub_key=self.privval_pub_key,
            cfg=cfg,
            metrics=RPCMetrics(self.metrics_registry),
        )
        if cfg.rpc.laddr:
            self.rpc_server = RPCServer(
                self.rpc_env,
                laddr=cfg.rpc.laddr,
                max_body_bytes=cfg.rpc.max_body_bytes,
            )
            await self.rpc_server.start()

        # -- Prometheus exposition (reference: node/node.go:606) --
        if cfg.instrumentation.prometheus:
            await self._start_metrics_server(
                cfg.instrumentation.prometheus_listen_addr
            )

        if state_sync:
            self.spawn(self._state_sync_then_follow(), "state-sync")

        self.logger.info(
            "node started",
            node_id=self.node_key.node_id,
            chain_id=self.genesis.chain_id,
            mode=cfg.base.mode,
            tpu="installed" if cfg.tpu.enable else "disabled",
        )

    def _render_metrics(self) -> str:
        """Per-node series first, then the process-global registry
        (device verifier, any subsystem constructed without a per-node
        registry) minus names the per-node registry already rendered —
        one exposition document with no duplicate series."""
        from ..libs.metrics import DEFAULT_REGISTRY

        text = self.metrics_registry.render()
        return text + DEFAULT_REGISTRY.render(
            exclude=self.metrics_registry.names()
        )

    def _health_payload(self) -> dict:
        """/healthz: node height + sync status (block height from the
        store; syncing while the consensus reactor still waits on
        state/block sync)."""
        syncing = False
        if self.consensus_reactor is not None:
            syncing = bool(self.consensus_reactor.wait_sync)
        return {
            "node_id": self.node_key.node_id,
            "height": self.block_store.height(),
            "syncing": syncing,
        }

    async def _start_metrics_server(self, addr: str) -> None:
        """Plain-text Prometheus exposition on /metrics, JSON liveness
        on /healthz (reference: node/node.go:606)."""
        import json as _json

        host, _, port = addr.replace("tcp://", "").rpartition(":")

        async def handler(reader, writer):
            try:
                # bound the whole request (deadline + header cap): this
                # is an unauthenticated port, and a slow-loris client
                # feeding one header per few seconds must not pin a
                # task forever
                deadline = asyncio.get_event_loop().time() + 10.0

                async def _line():
                    budget = deadline - asyncio.get_event_loop().time()
                    if budget <= 0:
                        raise asyncio.TimeoutError
                    return await asyncio.wait_for(reader.readline(), budget)

                line = await _line()
                for _ in range(100):  # header cap
                    h = await _line()
                    if h in (b"\r\n", b"\n", b""):
                        break
                else:
                    raise asyncio.TimeoutError
                # parse the request line properly: an arbitrary request
                # merely CONTAINING "/metrics" (a query param, a longer
                # path) must not scrape
                try:
                    method, target, _version = (
                        line.decode("latin-1").strip().split(" ", 2)
                    )
                except (ValueError, UnicodeDecodeError):
                    method, target = "", ""
                path = target.split("?", 1)[0]
                ctype = b"text/plain; version=0.0.4"
                if method not in ("GET", "HEAD"):
                    status, body = b"405 Method Not Allowed", b"GET only\n"
                elif path == "/metrics":
                    status = b"200 OK"
                    body = self._render_metrics().encode()
                elif path == "/healthz":
                    status = b"200 OK"
                    ctype = b"application/json"
                    body = _json.dumps(self._health_payload()).encode()
                else:
                    status = b"404 Not Found"
                    body = b"see /metrics or /healthz\n"
                writer.write(
                    b"HTTP/1.1 " + status + b"\r\n"
                    b"Content-Type: " + ctype + b"\r\n"
                    b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                    b"Connection: close\r\n\r\n"
                    + (b"" if method == "HEAD" else body)
                )
                await writer.drain()
            except (
                ConnectionError,
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
                ValueError,  # readline: line longer than the 64K limit
            ):
                pass
            finally:
                writer.close()

        self._metrics_server = await asyncio.start_server(
            handler, host or "0.0.0.0", int(port)
        )
        self.metrics_port = self._metrics_server.sockets[0].getsockname()[1]
        self.logger.info("prometheus metrics", addr=f"{host}:{self.metrics_port}")

    async def _start_seed(self) -> None:
        """Seed-mode boot: router + PEX only (reference: node/seed.go)."""
        from ..p2p.pex import PexReactor, pex_channel_descriptor

        self.pex_reactor = PexReactor(
            self.peer_manager,
            self.router.open_channel(pex_channel_descriptor()),
            self.peer_manager.subscribe(),
        )
        await self.router.start()
        await self.pex_reactor.start()
        self.logger.info(
            "seed node started", node_id=self.node_key.node_id
        )

    async def _state_sync_then_follow(self) -> None:
        """statesync → blocksync → consensus (reference:
        node/node.go:592 startStateSync → SwitchToBlockSync)."""
        try:
            state = await self.statesync_reactor.sync()
            await self.statesync_reactor.backfill(state)
            self.genesis_state_synced = True
            await self.blocksync_reactor.start_sync(state)
        except Exception as e:
            self.logger.error("state sync failed", err=str(e))
            raise

    def _only_validator_is_us(self, state) -> bool:
        """reference: node/node.go:230 onlyValidatorIsUs."""
        if self.privval_pub_key is None:
            return False
        if state.validators.size() != 1:
            return False
        addr = state.validators.validators[0].address
        return addr == self.privval_pub_key.address()

    async def on_stop(self) -> None:
        """reference: node/node.go OnStop — reverse start order."""
        await self._teardown()

    async def _teardown(self) -> None:
        # stop-and-join the sampler FIRST if this node enabled it: no
        # profiler thread may survive a node stop, and no sample may
        # land after (tests/test_teardown.py pins both)
        if getattr(self, "_profiler_owner", False):
            from ..libs import profiler

            profiler.disable()
            self._profiler_owner = False
        ms = getattr(self, "_metrics_server", None)
        if ms is not None:
            ms.close()
            try:
                await asyncio.wait_for(ms.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass  # straggling scrape connections die with the loop
            self._metrics_server = None
        for svc in (
            self.rpc_server,
            self.pex_reactor,
            self.statesync_reactor,
            self.blocksync_reactor,
            self.evidence_reactor,
            self.mempool_reactor,
            self.consensus_reactor,
            self.router,
            self.privval_listener,
            self.indexer,
            self.event_bus,
            self.proxy,
        ):
            if svc is not None and svc.is_running:
                try:
                    await svc.stop()
                except Exception as e:
                    self.logger.error(
                        "error stopping service", svc=svc.name, err=str(e)
                    )
        self.peer_manager.flush()
        for sink in getattr(self.indexer, "sinks", ()):
            close = getattr(sink, "close", None)
            if close is not None:
                try:
                    close()
                except Exception as e:
                    self.logger.error("error closing sink", err=str(e))
        for db in self._dbs:
            try:
                db.close()
            except Exception as e:
                self.logger.error("error closing db", err=str(e))
        self._dbs = []
        self._release_data_lock()


def _read_lock_pid(path: str) -> int:
    try:
        with open(path) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def acquire_pid_lock(path: str, what: str = "") -> int:
    """Atomically claim the advisory lockfile at `path`; returns an fd
    that must be kept open while held and passed to release_pid_lock().

    flock() on a held fd is the atomic claim step — two processes
    starting simultaneously cannot both succeed (a read-check-then-write
    pidfile guard fails exactly in the race it exists to prevent), the
    kernel releases the lock if the holder dies mid-hold, and pid-reuse
    cannot fake liveness. The file's pid content is secondary: it names
    the holder for error messages, and a live *foreign* pid written
    without the flock (a holder on another fs view, or tests simulating
    a running node) still refuses. Our own pid in the file is fine — an
    in-process crash-restart (the replay tests' crash simulation)
    reacquires after its dead fd's flock lapsed.
    """
    import fcntl

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        pid = _read_lock_pid(path)
        os.close(fd)
        holder = f"process {pid}" if pid else "another process"
        raise RuntimeError(
            f"{what or path} is locked by running {holder}"
        ) from None
    pid = _read_lock_pid(path)
    if pid and pid != os.getpid() and _pid_alive(pid):
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)
        raise RuntimeError(
            f"{what or path} is locked by running process {pid}"
        )
    os.ftruncate(fd, 0)
    os.write(fd, str(os.getpid()).encode())
    return fd


def release_pid_lock(path: str, fd: int) -> None:
    """Empty the pidfile and drop the flock. The file itself stays
    (unlinking a flock-ed path lets a third process lock a fresh inode
    while a second still holds the old one)."""
    import fcntl

    try:
        os.ftruncate(fd, 0)
        fcntl.flock(fd, fcntl.LOCK_UN)
    except OSError:
        pass
    finally:
        try:
            os.close(fd)
        except OSError:
            pass


def make_node(
    cfg: Config,
    app=None,
    genesis: Optional[GenesisDoc] = None,
    transport: Optional[Transport] = None,
) -> Node:
    """Build a Node from config files on disk (reference:
    node/node.go:116 makeNode + node/public.go New).

    `app` overrides the builtin application (defaults to kvstore);
    `genesis`/`transport` overrides support tests and in-process
    harnesses.
    """
    cfg.ensure_dirs()
    if genesis is None:
        genesis = GenesisDoc.from_file(cfg.base.path(cfg.base.genesis_file))
    return Node(cfg, genesis, app=app, transport=transport)
