"""Node key — the p2p identity key, persisted at config/node_key.json.

reference: types/node_key.go (NodeKey struct, LoadOrGenNodeKey). The
node ID is the lowercase hex of SHA-256(pubkey)[:20]
(p2p.types.node_id_from_pubkey).
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass, field

from ..crypto.ed25519 import PrivKeyEd25519
from ..libs.osutil import atomic_write
from ..p2p.types import NodeID, node_id_from_pubkey

__all__ = ["NodeKey"]


@dataclass
class NodeKey:
    # repr=False: the generated __repr__ must never embed key material
    # (tmct ct-leak-telemetry — logs render reprs)
    priv_key: PrivKeyEd25519 = field(repr=False)

    @property
    def node_id(self) -> NodeID:
        return node_id_from_pubkey(self.priv_key.pub_key())

    def save_as(self, path: str) -> None:
        doc = {
            "priv_key": {
                "type": "tendermint/PrivKeyEd25519",
                "value": base64.b64encode(self.priv_key.bytes()).decode(),
            }
        }
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        atomic_write(path, json.dumps(doc, indent=2))

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            doc = json.load(f)
        raw = base64.b64decode(doc["priv_key"]["value"])
        return cls(priv_key=PrivKeyEd25519(raw))

    @classmethod
    def load_or_generate(cls, path: str) -> "NodeKey":
        """reference: types/node_key.go LoadOrGenNodeKey."""
        if os.path.exists(path):
            return cls.load(path)
        nk = cls(priv_key=PrivKeyEd25519.generate())
        nk.save_as(path)
        return nk
