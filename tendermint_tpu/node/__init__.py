"""Node assembly (reference: node/ — makeNode, OnStart)."""

from .key import NodeKey
from .node import Node, make_node

__all__ = ["Node", "NodeKey", "make_node"]
