/* Batch CanonicalVote sign-bytes assembly.
 *
 * The batch VerifyCommit host path (types/canonical.py
 * VoteSignTemplate.sign_bytes_batch) splices a per-commit constant
 * prefix/suffix around a per-signature protobuf Timestamp. The Python
 * loop costs ~5 us/signature — ~50 ms of the 10k-validator commit
 * latency budget; this file is the same splice in C (~50 ns/sig).
 * The reference marshals the equivalent bytes per signature in Go
 * (types/validation.go:152 -> vote.SignBytes).
 *
 * Byte-exactness contract (differential-tested against the Python
 * loop in tests/test_encoding.py):
 *   seconds, nanos = floordivmod(ns, 1e9)      (Python // semantics)
 *   ts  = ("\x08" varint(seconds) if seconds else "")
 *       + ("\x10" varint(nanos)   if nanos   else "")
 *   body = prefix + ts_tag + varint(len(ts)) + ts + suffix
 *   row  = varint(len(body)) + body
 * varint: unsigned base-128 LSB-first; negative int64 values encode
 * as 10-byte two's complement (proto3 int64).
 *
 * Compiled on demand by tendermint_tpu.native (cc -O2 -shared),
 * called through ctypes; Python remains the fallback.
 */
#include <stdint.h>
#include <string.h>

static inline long put_varint(uint8_t *p, uint64_t v) {
    long i = 0;
    do {
        uint8_t b = v & 0x7F;
        v >>= 7;
        p[i++] = v ? (b | 0x80) : b;
    } while (v);
    return i;
}

/* Fills `out` with n concatenated rows, lens[i] = bytes of row i.
 * Returns total bytes written, or -1 if out_cap would overflow. */
long tm_vote_sign_bytes_batch(
    const uint8_t *prefix, long prefix_len,
    const uint8_t *suffix, long suffix_len,
    uint8_t ts_tag,
    const int64_t *ts_ns, long n,
    uint8_t *out, long out_cap, int32_t *lens)
{
    /* worst case per row: 10-byte seconds varint + 5-byte nanos varint
     * + 2 field tags + 1 ts-len byte + tag + 2 body-len bytes */
    const long row_bound = prefix_len + suffix_len + 24;
    uint8_t ts[24];
    long off = 0;
    for (long i = 0; i < n; i++) {
        if (off + row_bound > out_cap) return -1;
        int64_t ns = ts_ns[i];
        /* Python divmod: floored division, nanos in [0, 1e9) */
        int64_t sec = ns / 1000000000LL;
        int64_t nano = ns % 1000000000LL;
        if (nano < 0) { nano += 1000000000LL; sec -= 1; }
        long ts_len = 0;
        if (sec) {
            ts[ts_len++] = 0x08;
            ts_len += put_varint(ts + ts_len, (uint64_t)sec);
        }
        if (nano) {
            ts[ts_len++] = 0x10;
            ts_len += put_varint(ts + ts_len, (uint64_t)nano);
        }
        /* body = prefix + ts_tag + varint(ts_len) + ts + suffix;
         * ts_len <= 17 so its varint is one byte */
        long body_len = prefix_len + 1 + 1 + ts_len + suffix_len;
        uint8_t *row = out + off;
        long w = put_varint(row, (uint64_t)body_len);
        memcpy(row + w, prefix, (size_t)prefix_len);
        w += prefix_len;
        row[w++] = ts_tag;
        row[w++] = (uint8_t)ts_len;
        memcpy(row + w, ts, (size_t)ts_len);
        w += ts_len;
        memcpy(row + w, suffix, (size_t)suffix_len);
        w += suffix_len;
        lens[i] = (int32_t)w;
        off += w;
    }
    return off;
}
