/* Keccak-f[1600] permutation core, shared by keccakf.c (the merlin
 * host-prep library) and ed25519_batch.c (the in-kernel STROBE for
 * tm_sr25519_verify_full) — ONE implementation of the cryptographic
 * permutation, included statically by both compilation units so the
 * two .so files can never diverge. Round constants and the rho/pi
 * schedule are the published FIPS-202 values.
 *
 * Lane order: st[x + 5*y] (row-major y), little-endian u64 — matches
 * the 200-byte STROBE state viewed as <25Q. */
#ifndef TM_KECCAKF_CORE_H
#define TM_KECCAKF_CORE_H

#include <stdint.h>

#define TM_ROTL64(v, n) (((v) << (n)) | ((v) >> (64 - (n))))

static const uint64_t TM_KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

static void tm_keccakf_core(uint64_t st[25]) {
    uint64_t bc[5], t;
    for (int round = 0; round < 24; round++) {
        /* theta */
        for (int i = 0; i < 5; i++)
            bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
        for (int i = 0; i < 5; i++) {
            t = bc[(i + 4) % 5] ^ TM_ROTL64(bc[(i + 1) % 5], 1);
            for (int j = 0; j < 25; j += 5)
                st[j + i] ^= t;
        }
        /* rho + pi */
        {
            static const int piln[24] = {10, 7,  11, 17, 18, 3,  5,  16,
                                         8,  21, 24, 4,  15, 23, 19, 13,
                                         12, 2,  20, 14, 22, 9,  6,  1};
            static const int rotc[24] = {1,  3,  6,  10, 15, 21, 28, 36,
                                         45, 55, 2,  14, 27, 41, 56, 8,
                                         25, 43, 62, 18, 39, 61, 20, 44};
            t = st[1];
            for (int i = 0; i < 24; i++) {
                int j = piln[i];
                bc[0] = st[j];
                st[j] = TM_ROTL64(t, rotc[i]);
                t = bc[0];
            }
        }
        /* chi */
        for (int j = 0; j < 25; j += 5) {
            for (int i = 0; i < 5; i++)
                bc[i] = st[j + i];
            for (int i = 0; i < 5; i++)
                st[j + i] = bc[i] ^ ((~bc[(i + 1) % 5]) & bc[(i + 2) % 5]);
        }
        /* iota */
        st[0] ^= TM_KECCAK_RC[round];
    }
}

#endif /* TM_KECCAKF_CORE_H */
