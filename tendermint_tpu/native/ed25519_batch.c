/* Batched ed25519 verification via the random-linear-combination batch
 * equation — the CPU-fallback analog of the reference's curve25519-voi
 * batch verifier (reference: crypto/ed25519/ed25519.go:202-237, which
 * wraps voi's ed25519.VerifyBatch).
 *
 * The host (crypto/ed25519.py) hashes and does all scalar arithmetic
 * mod L in Python (fast big-int), then hands this kernel:
 *
 *   terms:  zb*B  +  sum a_i * (-A_i)  +  sum z_i * (-R_i)
 *   where   zb  = sum z_i*s_i mod L,  a_i = z_i*k_i mod L,
 *           z_i = 128-bit random,     k_i = SHA512(R|A|M) mod L
 *
 * and the kernel answers whether [8] * (that sum) is the identity —
 * the cofactored (ZIP-215) batch equation. Field/point arithmetic
 * mirrors crypto/ed25519_math.py exactly (radix-2^51 limbs; unified
 * add-2008-hwcd-3 addition, complete for a=-1 and nonsquare d, so
 * small-order/mixed-order ZIP-215 points are handled identically).
 * Multi-scalar multiplication is Pippenger with 8-bit windows.
 *
 * Returns 1 = batch equation holds (every signature valid),
 *         0 = equation fails (caller falls back per-signature for the
 *             bitmap, like the reference does on batch failure),
 *        -1 = some encoding failed ZIP-215 decoding (caller falls
 *             back; the bad index is identified there).
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef uint64_t fe[5];
typedef unsigned __int128 u128;

#define MASK51 0x7ffffffffffffULL

static const fe FE_D = {0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL, 0x739c663a03cbbULL, 0x52036cee2b6ffULL};
static const fe FE_2D = {0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL, 0x6738cc7407977ULL, 0x2406d9dc56dffULL};
static const fe FE_SQRTM1 = {0x61b274a0ea0b0ULL, 0x0d5a5fc8f189dULL, 0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL, 0x2b8324804fc1dULL};
static const fe FE_BX = {0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL, 0x1ff60527118feULL, 0x216936d3cd6e5ULL};
static const fe FE_BY = {0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL, 0x3333333333333ULL, 0x6666666666666ULL};
static const fe FE_BT = {0x68ab3a5b7dda3ULL, 0x00eea2a5eadbbULL, 0x2af8df483c27eULL, 0x332b375274732ULL, 0x67875f0fd78b7ULL};

static void fe_copy(fe r, const fe a) { memcpy(r, a, sizeof(fe)); }

static void fe_zero(fe r) { memset(r, 0, sizeof(fe)); }

static void fe_one(fe r) { fe_zero(r); r[0] = 1; }

static void fe_add(fe r, const fe a, const fe b) {
    for (int i = 0; i < 5; i++) r[i] = a[i] + b[i];
}

/* r = a - b, biased by 2p so limbs stay nonnegative (inputs < 2^52) */
static void fe_sub(fe r, const fe a, const fe b) {
    r[0] = a[0] + 0xfffffffffffdaULL - b[0];
    r[1] = a[1] + 0xffffffffffffeULL - b[1];
    r[2] = a[2] + 0xffffffffffffeULL - b[2];
    r[3] = a[3] + 0xffffffffffffeULL - b[3];
    r[4] = a[4] + 0xffffffffffffeULL - b[4];
}

static void fe_neg(fe r, const fe a) {
    fe z;
    fe_zero(z);
    fe_sub(r, z, a);
}

static void fe_carry(fe r) {
    uint64_t c;
    c = r[0] >> 51; r[0] &= MASK51; r[1] += c;
    c = r[1] >> 51; r[1] &= MASK51; r[2] += c;
    c = r[2] >> 51; r[2] &= MASK51; r[3] += c;
    c = r[3] >> 51; r[3] &= MASK51; r[4] += c;
    c = r[4] >> 51; r[4] &= MASK51; r[0] += 19 * c;
    c = r[0] >> 51; r[0] &= MASK51; r[1] += c;
}

static void fe_mul(fe r, const fe a, const fe b) {
    u128 t0, t1, t2, t3, t4;
    uint64_t b1_19 = 19 * b[1], b2_19 = 19 * b[2], b3_19 = 19 * b[3],
             b4_19 = 19 * b[4];

    t0 = (u128)a[0] * b[0] + (u128)a[1] * b4_19 + (u128)a[2] * b3_19 +
         (u128)a[3] * b2_19 + (u128)a[4] * b1_19;
    t1 = (u128)a[0] * b[1] + (u128)a[1] * b[0] + (u128)a[2] * b4_19 +
         (u128)a[3] * b3_19 + (u128)a[4] * b2_19;
    t2 = (u128)a[0] * b[2] + (u128)a[1] * b[1] + (u128)a[2] * b[0] +
         (u128)a[3] * b4_19 + (u128)a[4] * b3_19;
    t3 = (u128)a[0] * b[3] + (u128)a[1] * b[2] + (u128)a[2] * b[1] +
         (u128)a[3] * b[0] + (u128)a[4] * b4_19;
    t4 = (u128)a[0] * b[4] + (u128)a[1] * b[3] + (u128)a[2] * b[2] +
         (u128)a[3] * b[1] + (u128)a[4] * b[0];

    uint64_t c;
    uint64_t r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51);
    t1 += c;
    uint64_t r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51);
    t2 += c;
    uint64_t r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51);
    t3 += c;
    uint64_t r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51);
    t4 += c;
    uint64_t r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
    r0 += 19 * c;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    r[0] = r0; r[1] = r1; r[2] = r2; r[3] = r3; r[4] = r4;
}

static void fe_sq(fe r, const fe a) { fe_mul(r, a, a); }

static uint64_t load64_le(const uint8_t *b) {
    uint64_t v = 0;
    for (int i = 7; i >= 0; i--) v = (v << 8) | b[i];
    return v;
}

/* 255 low bits of the encoding (bit 255 — the x sign — is dropped);
 * values >= p are fine: arithmetic is mod p (ZIP-215 non-canonical y) */
static void fe_frombytes(fe r, const uint8_t *s) {
    r[0] = load64_le(s) & MASK51;
    r[1] = (load64_le(s + 6) >> 3) & MASK51;
    r[2] = (load64_le(s + 12) >> 6) & MASK51;
    r[3] = (load64_le(s + 19) >> 1) & MASK51;
    r[4] = (load64_le(s + 24) >> 12) & MASK51;
}

/* canonical little-endian encoding (fully reduced mod p) */
static void fe_tobytes(uint8_t *s, const fe a) {
    fe t;
    fe_copy(t, a);
    fe_carry(t);
    fe_carry(t);
    /* q = whether t >= p, computed by propagating (t + 19) carries */
    uint64_t q = (t[0] + 19) >> 51;
    q = (t[1] + q) >> 51;
    q = (t[2] + q) >> 51;
    q = (t[3] + q) >> 51;
    q = (t[4] + q) >> 51;
    t[0] += 19 * q;
    uint64_t c;
    c = t[0] >> 51; t[0] &= MASK51; t[1] += c;
    c = t[1] >> 51; t[1] &= MASK51; t[2] += c;
    c = t[2] >> 51; t[2] &= MASK51; t[3] += c;
    c = t[3] >> 51; t[3] &= MASK51; t[4] += c;
    t[4] &= MASK51;
    uint64_t w0 = t[0] | (t[1] << 51);
    uint64_t w1 = (t[1] >> 13) | (t[2] << 38);
    uint64_t w2 = (t[2] >> 26) | (t[3] << 25);
    uint64_t w3 = (t[3] >> 39) | (t[4] << 12);
    memcpy(s, &w0, 8);
    memcpy(s + 8, &w1, 8);
    memcpy(s + 16, &w2, 8);
    memcpy(s + 24, &w3, 8);
}

static int fe_iszero(const fe a) {
    uint8_t s[32];
    fe_tobytes(s, a);
    uint8_t acc = 0;
    for (int i = 0; i < 32; i++) acc |= s[i];
    return acc == 0;
}

static int fe_eq(const fe a, const fe b) {
    fe d;
    fe_sub(d, a, b);
    return fe_iszero(d);
}

static void fe_sqn(fe r, const fe a, int n) {
    fe_sq(r, a);
    for (int i = 1; i < n; i++) fe_sq(r, r);
}

/* a^(2^252 - 3): the exponent in the combined sqrt/division trick
 * ((p-5)/8), via the standard 2^k-1 addition chain (251 squarings +
 * ~12 multiplies — decompression cost is dominated by this power). */
static void fe_pow2523(fe r, const fe z) {
    fe t0, t1, t2;
    fe_sq(t0, z);                  /* z^2 */
    fe_sqn(t1, t0, 2);
    fe_mul(t1, t1, z);             /* z^9 */
    fe_mul(t0, t1, t0);            /* z^11 */
    fe_sq(t0, t0);                 /* z^22 */
    fe_mul(t0, t0, t1);            /* z^31 = z^(2^5-1) */
    fe_sqn(t1, t0, 5);
    fe_mul(t0, t1, t0);            /* z^(2^10-1) */
    fe_sqn(t1, t0, 10);
    fe_mul(t1, t1, t0);            /* z^(2^20-1) */
    fe_sqn(t2, t1, 20);
    fe_mul(t1, t2, t1);            /* z^(2^40-1) */
    fe_sqn(t1, t1, 10);
    fe_mul(t0, t1, t0);            /* z^(2^50-1) */
    fe_sqn(t1, t0, 50);
    fe_mul(t1, t1, t0);            /* z^(2^100-1) */
    fe_sqn(t2, t1, 100);
    fe_mul(t1, t2, t1);            /* z^(2^200-1) */
    fe_sqn(t1, t1, 50);
    fe_mul(t0, t1, t0);            /* z^(2^250-1) */
    fe_sqn(t0, t0, 2);
    fe_mul(r, t0, z);              /* z^(2^252-3) */
}

/* extended (twisted Edwards) coordinates, mirrors ed25519_math.Point */
typedef struct { fe X, Y, Z, T; } ge;

static void ge_identity(ge *r) {
    fe_zero(r->X);
    fe_one(r->Y);
    fe_one(r->Z);
    fe_zero(r->T);
}

/* unified add-2008-hwcd-3 (complete for a=-1, d nonsquare — same
 * formula as ed25519_math.point_add, valid for P==Q and small order) */
static void ge_add(ge *r, const ge *p, const ge *q) {
    fe a, b, c, d, e, f, g, h, t1, t2;
    fe_sub(t1, p->Y, p->X);
    fe_sub(t2, q->Y, q->X);
    fe_carry(t1);
    fe_carry(t2);
    fe_mul(a, t1, t2);
    fe_add(t1, p->Y, p->X);
    fe_add(t2, q->Y, q->X);
    fe_mul(b, t1, t2);
    fe_mul(c, p->T, FE_2D);
    fe_mul(c, c, q->T);
    fe_mul(d, p->Z, q->Z);
    fe_add(d, d, d);
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_carry(e);
    fe_carry(f);
    fe_carry(g);
    fe_carry(h);
    fe_mul(r->X, e, f);
    fe_mul(r->Y, g, h);
    fe_mul(r->Z, f, g);
    fe_mul(r->T, e, h);
}

/* dbl-2008-hwcd, mirrors ed25519_math.point_double */
static void ge_dbl(ge *r, const ge *p) {
    fe a, b, c, h, e, g, f, t;
    fe_sq(a, p->X);
    fe_sq(b, p->Y);
    fe_sq(c, p->Z);
    fe_add(c, c, c);
    fe_carry(c);
    fe_add(h, a, b);
    fe_carry(h);
    fe_add(t, p->X, p->Y);
    fe_carry(t);
    fe_sq(t, t);
    fe_sub(e, h, t);
    fe_sub(g, a, b);
    fe_add(f, c, g);
    fe_carry(e);
    fe_carry(g);
    fe_carry(f);
    fe_mul(r->X, e, f);
    fe_mul(r->Y, g, h);
    fe_mul(r->Z, f, g);
    fe_mul(r->T, e, h);
}

static void ge_neg(ge *r, const ge *p) {
    fe_neg(r->X, p->X);
    fe_carry(r->X);
    fe_copy(r->Y, p->Y);
    fe_copy(r->Z, p->Z);
    fe_neg(r->T, p->T);
    fe_carry(r->T);
}

/* ZIP-215 decompression, mirroring ed25519_math.decompress/_recover_x:
 * non-canonical y accepted (reduced mod p); x recovered via the
 * combined sqrt; "-0" (x == 0 with sign bit 1) rejected.
 * Returns 1 on success. */
static int ge_frombytes_zip215(ge *r, const uint8_t *s) {
    fe y, y2, u, v, v3, x, vx2, chk;
    int sign = s[31] >> 7;
    fe_frombytes(y, s);
    fe_sq(y2, y);
    fe_one(u);
    fe_sub(u, y2, u);
    fe_carry(u);                 /* u = y^2 - 1 */
    fe_mul(v, y2, FE_D);
    fe_one(chk);
    fe_add(v, v, chk);
    fe_carry(v);                 /* v = d*y^2 + 1 */

    fe_sq(v3, v);
    fe_mul(v3, v3, v);           /* v^3 */
    fe_sq(x, v3);
    fe_mul(x, x, v);             /* v^7 */
    fe_mul(x, x, u);             /* u*v^7 */
    fe_pow2523(x, x);            /* (u*v^7)^((p-5)/8) */
    fe_mul(x, x, v3);
    fe_mul(x, x, u);             /* x = u*v^3*(u*v^7)^((p-5)/8) */

    fe_sq(vx2, x);
    fe_mul(vx2, vx2, v);         /* v*x^2 */
    if (!fe_eq(vx2, u)) {
        fe nu;
        fe_neg(nu, u);
        if (!fe_eq(vx2, nu)) return 0;  /* u/v is not a square */
        fe_mul(x, x, FE_SQRTM1);        /* now v*x^2 == u */
    }

    uint8_t xb[32];
    fe_tobytes(xb, x);
    int xzero = 1;
    for (int i = 0; i < 32; i++) xzero &= (xb[i] == 0);
    if (xzero && sign) return 0; /* "-0" rejected (RFC 8032 + ZIP-215) */
    if ((xb[0] & 1) != sign) {
        fe_neg(x, x);
        fe_carry(x);
    }
    fe_copy(r->X, x);
    fe_copy(r->Y, y);
    fe_one(r->Z);
    fe_mul(r->T, x, y);
    return 1;
}

/* sqrt_ratio_m1 (RFC 9496 §4.2, mirrors crypto/ristretto.py
 * _sqrt_ratio_m1): r = |sqrt(u/v)| when it exists, else |sqrt(i*u/v)|;
 * returns was_square. */
static int fe_sqrt_ratio_m1(fe r, const fe u, const fe v) {
    fe v3, v7, t, check, nu, nui;
    fe_sq(v3, v);
    fe_mul(v3, v3, v);           /* v^3 */
    fe_sq(v7, v3);
    fe_mul(v7, v7, v);           /* v^7 */
    fe_mul(t, u, v7);
    fe_pow2523(t, t);
    fe_mul(t, t, v3);
    fe_mul(t, t, u);             /* u*v^3*(u*v^7)^((p-5)/8) */
    fe_sq(check, t);
    fe_mul(check, check, v);     /* v*r^2 */
    int correct = fe_eq(check, u);
    fe_neg(nu, u);
    fe_carry(nu);
    int flipped = fe_eq(check, nu);
    fe_mul(nui, nu, FE_SQRTM1);
    int flipped_i = fe_eq(check, nui);
    if (flipped || flipped_i) fe_mul(t, t, FE_SQRTM1);
    uint8_t b[32];
    fe_tobytes(b, t);
    if (b[0] & 1) {              /* |r| */
        fe_neg(t, t);
        fe_carry(t);
    }
    fe_copy(r, t);
    return correct || flipped;
}

/* ristretto255 decode (RFC 9496 §4.3.1, mirrors crypto/ristretto.py
 * decode): canonical nonneg s -> extended point representative in 2E.
 * Returns 1 on success. */
static int ge_frombytes_ristretto(ge *r, const uint8_t *bytes) {
    fe s;
    uint8_t canon[32];
    fe_frombytes(s, bytes);
    fe_tobytes(canon, s);
    /* canonical: no high bit, value < p (re-encode matches), even */
    if ((bytes[31] & 0x80) || memcmp(canon, bytes, 32) != 0) return 0;
    if (bytes[0] & 1) return 0;
    fe one, ss, u1, u2, u2s, du1, v, vu, invsq, dx, dy, x, y, tt, s2;
    fe_one(one);
    fe_sq(ss, s);
    fe_sub(u1, one, ss);
    fe_carry(u1);                /* 1 - s^2 */
    fe_add(u2, one, ss);
    fe_carry(u2);                /* 1 + s^2 */
    fe_sq(u2s, u2);
    fe_sq(du1, u1);
    fe_mul(du1, du1, FE_D);      /* D*u1^2 */
    fe_neg(v, du1);
    fe_carry(v);
    fe_sub(v, v, u2s);
    fe_carry(v);                 /* -D*u1^2 - u2^2 */
    fe_mul(vu, v, u2s);
    int was_square = fe_sqrt_ratio_m1(invsq, one, vu);
    fe_mul(dx, invsq, u2);
    fe_mul(dy, invsq, dx);
    fe_mul(dy, dy, v);
    fe_add(s2, s, s);
    fe_carry(s2);
    fe_mul(x, s2, dx);
    uint8_t xb[32];
    fe_tobytes(xb, x);
    if (xb[0] & 1) {             /* |x| */
        fe_neg(x, x);
        fe_carry(x);
    }
    fe_mul(y, u1, dy);
    fe_mul(tt, x, y);
    uint8_t tb[32];
    fe_tobytes(tb, tt);
    if (!was_square || (tb[0] & 1) || fe_iszero(y)) return 0;
    fe_copy(r->X, x);
    fe_copy(r->Y, y);
    fe_one(r->Z);
    fe_copy(r->T, tt);
    return 1;
}

/* Pippenger with 8-bit windows: per-term cost ~64 adds but a fixed
 * ~16k-add bucket-aggregation cost per call — the large-batch MSM. */
static void ge_msm_pippenger(ge *result, const uint8_t *scalars,
                             const ge *pts, size_t n) {
    ge buckets[255]; /* ~40 KB of stack; single-threaded use */
    ge_identity(result);
    for (int w = 31; w >= 0; w--) {
        if (w != 31)
            for (int k = 0; k < 8; k++) ge_dbl(result, result);
        for (int d = 0; d < 255; d++) ge_identity(&buckets[d]);
        for (size_t i = 0; i < n; i++) {
            int d = scalars[i * 32 + w];
            if (d) ge_add(&buckets[d - 1], &buckets[d - 1], &pts[i]);
        }
        ge run, acc;
        ge_identity(&run);
        ge_identity(&acc);
        for (int d = 254; d >= 0; d--) {
            ge_add(&run, &run, &buckets[d]);
            ge_add(&acc, &acc, &run);
        }
        ge_add(result, result, &acc);
    }
}

/* Straus with 4-bit windows and per-term tables: ~78 adds per term
 * with only a ~250-doubling fixed cost — wins below ~1000 terms
 * (commit-sized batches and single verifies). */
static int ge_msm_straus(ge *result, const uint8_t *scalars,
                         const ge *pts, size_t n) {
    /* tables[i][d-1] = d * pts[i] for d in 1..15 */
    ge *tables = malloc(n * 15 * sizeof(ge));
    if (!tables) return 0;
    for (size_t i = 0; i < n; i++) {
        ge *t = tables + i * 15;
        t[0] = pts[i];
        for (int d = 1; d < 15; d++) ge_add(&t[d], &t[d - 1], &pts[i]);
    }
    ge_identity(result);
    for (int w = 63; w >= 0; w--) {
        if (w != 63)
            for (int k = 0; k < 4; k++) ge_dbl(result, result);
        int byte = w >> 1;
        for (size_t i = 0; i < n; i++) {
            int b = scalars[i * 32 + byte];
            int d = (w & 1) ? (b >> 4) : (b & 0x0f);
            if (d) ge_add(result, result, &tables[i * 15 + d - 1]);
        }
    }
    free(tables);
    return 1;
}

/* MSM dispatch: Straus for small term counts, Pippenger for large.
 * Crossover: Straus ~78n+250 adds, Pippenger ~64n+16300 — Straus wins
 * until n ~ 1150. Scalars are 32-byte little-endian (< L < 2^253). */
static void ge_msm(ge *result, const uint8_t *scalars, const ge *pts,
                   size_t n) {
    if (n < 1024 && ge_msm_straus(result, scalars, pts, n)) return;
    ge_msm_pippenger(result, scalars, pts, n);
}

/* Shared driver: decode all A_i/R_i with `decode`, then check
 * [8](zb*B + sum a_i*(-A_i) + sum z_i*(-R_i)) == identity. */
static int batch_verify_common(const uint8_t *pk_bytes,
                               const uint8_t *r_bytes, const uint8_t *zb,
                               const uint8_t *a_scalars,
                               const uint8_t *z_scalars, uint64_t n,
                               int (*decode)(ge *, const uint8_t *)) {
    size_t nterms = 2 * (size_t)n + 1;
    ge *pts = malloc(nterms * sizeof(ge));
    uint8_t *scalars = malloc(nterms * 32);
    if (!pts || !scalars) {
        free(pts);
        free(scalars);
        return -1;
    }
    int rc = -1;

    /* term 0: zb * B */
    fe_copy(pts[0].X, FE_BX);
    fe_copy(pts[0].Y, FE_BY);
    fe_one(pts[0].Z);
    fe_copy(pts[0].T, FE_BT);
    memcpy(scalars, zb, 32);

    for (uint64_t i = 0; i < n; i++) {
        ge t;
        if (!decode(&t, pk_bytes + 32 * i)) goto done;
        ge_neg(&pts[1 + i], &t);
        if (!decode(&t, r_bytes + 32 * i)) goto done;
        ge_neg(&pts[1 + n + i], &t);
        memcpy(scalars + 32 * (1 + i), a_scalars + 32 * i, 32);
        memcpy(scalars + 32 * (1 + n + i), z_scalars + 32 * i, 32);
    }

    {
        ge sum;
        ge_msm(&sum, scalars, pts, nterms);
        /* cofactored: [8] * sum must be the identity */
        ge_dbl(&sum, &sum);
        ge_dbl(&sum, &sum);
        ge_dbl(&sum, &sum);
        /* identity in extended coords: X == 0 and Y == Z */
        rc = (fe_iszero(sum.X) && fe_eq(sum.Y, sum.Z)) ? 1 : 0;
    }

done:
    free(pts);
    free(scalars);
    return rc;
}

/* See file header for the contract. */
int tm_ed25519_batch_verify(const uint8_t *pk_bytes, const uint8_t *r_bytes,
                            const uint8_t *zb, const uint8_t *a_scalars,
                            const uint8_t *z_scalars, uint64_t n) {
    return batch_verify_common(pk_bytes, r_bytes, zb, a_scalars, z_scalars,
                               n, ge_frombytes_zip215);
}

/* sr25519: same batch equation over ristretto255 representatives
 * (schnorrkel verify is s*B - k*A == R as ristretto POINTS, i.e. equal
 * cosets mod the 4-torsion). Soundness of the cofactored check: all
 * decoded representatives lie in 2E, and 2E ∩ E[8] is exactly the
 * 4-torsion set ristretto quotients by — so for decoded inputs,
 * [8]*(sum) == identity  <=>  every per-signature coset equation
 * holds (w.h.p. over the random z_i), the same argument schnorrkel's
 * own batch verification uses. Challenges k_i (merlin transcripts)
 * and all scalar products arrive precomputed, like the ed25519 entry. */
int tm_sr25519_batch_verify(const uint8_t *pk_bytes, const uint8_t *r_bytes,
                            const uint8_t *zb, const uint8_t *a_scalars,
                            const uint8_t *z_scalars, uint64_t n) {
    return batch_verify_common(pk_bytes, r_bytes, zb, a_scalars, z_scalars,
                               n, ge_frombytes_ristretto);
}
