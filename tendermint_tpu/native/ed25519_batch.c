/* Batched ed25519 verification via the random-linear-combination batch
 * equation — the CPU-fallback analog of the reference's curve25519-voi
 * batch verifier (reference: crypto/ed25519/ed25519.go:202-237, which
 * wraps voi's ed25519.VerifyBatch).
 *
 * The kernel checks, for terms
 *
 *   zb*B  +  sum a_i * (-A_i)  +  sum z_i * (-R_i)
 *   where   zb  = sum z_i*s_i mod L,  a_i = z_i*k_i mod L,
 *           z_i = 128-bit random,     k_i = SHA512(R|A|M) mod L
 *
 * (tm_ed25519_verify_full computes the hashes and mod-L products
 * natively; the older tm_*_batch_verify entries take them
 * precomputed — the sr25519 path still preps its merlin challenges in
 * Python),
 *
 * and the kernel answers whether [8] * (that sum) is the identity —
 * the cofactored (ZIP-215) batch equation. Field/point arithmetic
 * mirrors crypto/ed25519_math.py exactly (radix-2^51 limbs; unified
 * add-2008-hwcd-3 addition, complete for a=-1 and nonsquare d, so
 * small-order/mixed-order ZIP-215 points are handled identically).
 * Multi-scalar multiplication is Pippenger with 8-bit windows.
 *
 * Returns 1 = batch equation holds (every signature valid),
 *         0 = equation fails (caller falls back per-signature for the
 *             bitmap, like the reference does on batch failure),
 *        -1 = some encoding failed ZIP-215 decoding (caller falls
 *             back; the bad index is identified there).
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* IFMA path needs target-attribute + AVX-512 IFMA intrinsic support
 * (GCC >= 7, or clang); older toolchains must still compile the
 * scalar kernel rather than lose the whole library */
#if defined(__x86_64__) && \
    ((defined(__GNUC__) && !defined(__clang__) && __GNUC__ >= 7) || \
     (defined(__clang__) && __clang_major__ >= 7))
#define TM_HAVE_IFMA_BUILD 1
#include <immintrin.h>
#endif

typedef uint64_t fe[5];
typedef unsigned __int128 u128;

#define MASK51 0x7ffffffffffffULL

static const fe FE_D = {0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL, 0x739c663a03cbbULL, 0x52036cee2b6ffULL};
static const fe FE_2D = {0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL, 0x6738cc7407977ULL, 0x2406d9dc56dffULL};
static const fe FE_SQRTM1 = {0x61b274a0ea0b0ULL, 0x0d5a5fc8f189dULL, 0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL, 0x2b8324804fc1dULL};
static const fe FE_BX = {0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL, 0x1ff60527118feULL, 0x216936d3cd6e5ULL};
static const fe FE_BY = {0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL, 0x3333333333333ULL, 0x6666666666666ULL};
static const fe FE_BT = {0x68ab3a5b7dda3ULL, 0x00eea2a5eadbbULL, 0x2af8df483c27eULL, 0x332b375274732ULL, 0x67875f0fd78b7ULL};

static void fe_copy(fe r, const fe a) { memcpy(r, a, sizeof(fe)); }

static void fe_zero(fe r) { memset(r, 0, sizeof(fe)); }

static void fe_one(fe r) { fe_zero(r); r[0] = 1; }

static void fe_add(fe r, const fe a, const fe b) {
    for (int i = 0; i < 5; i++) r[i] = a[i] + b[i];
}

/* r = a - b, biased by 2p so limbs stay nonnegative (inputs < 2^52) */
static void fe_sub(fe r, const fe a, const fe b) {
    r[0] = a[0] + 0xfffffffffffdaULL - b[0];
    r[1] = a[1] + 0xffffffffffffeULL - b[1];
    r[2] = a[2] + 0xffffffffffffeULL - b[2];
    r[3] = a[3] + 0xffffffffffffeULL - b[3];
    r[4] = a[4] + 0xffffffffffffeULL - b[4];
}

static void fe_neg(fe r, const fe a) {
    fe z;
    fe_zero(z);
    fe_sub(r, z, a);
}

static void fe_carry(fe r) {
    uint64_t c;
    c = r[0] >> 51; r[0] &= MASK51; r[1] += c;
    c = r[1] >> 51; r[1] &= MASK51; r[2] += c;
    c = r[2] >> 51; r[2] &= MASK51; r[3] += c;
    c = r[3] >> 51; r[3] &= MASK51; r[4] += c;
    c = r[4] >> 51; r[4] &= MASK51; r[0] += 19 * c;
    c = r[0] >> 51; r[0] &= MASK51; r[1] += c;
}

static void fe_mul(fe r, const fe a, const fe b) {
    u128 t0, t1, t2, t3, t4;
    uint64_t b1_19 = 19 * b[1], b2_19 = 19 * b[2], b3_19 = 19 * b[3],
             b4_19 = 19 * b[4];

    t0 = (u128)a[0] * b[0] + (u128)a[1] * b4_19 + (u128)a[2] * b3_19 +
         (u128)a[3] * b2_19 + (u128)a[4] * b1_19;
    t1 = (u128)a[0] * b[1] + (u128)a[1] * b[0] + (u128)a[2] * b4_19 +
         (u128)a[3] * b3_19 + (u128)a[4] * b2_19;
    t2 = (u128)a[0] * b[2] + (u128)a[1] * b[1] + (u128)a[2] * b[0] +
         (u128)a[3] * b4_19 + (u128)a[4] * b3_19;
    t3 = (u128)a[0] * b[3] + (u128)a[1] * b[2] + (u128)a[2] * b[1] +
         (u128)a[3] * b[0] + (u128)a[4] * b4_19;
    t4 = (u128)a[0] * b[4] + (u128)a[1] * b[3] + (u128)a[2] * b[2] +
         (u128)a[3] * b[1] + (u128)a[4] * b[0];

    uint64_t c;
    uint64_t r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51);
    t1 += c;
    uint64_t r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51);
    t2 += c;
    uint64_t r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51);
    t3 += c;
    uint64_t r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51);
    t4 += c;
    uint64_t r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
    r0 += 19 * c;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    r[0] = r0; r[1] = r1; r[2] = r2; r[3] = r3; r[4] = r4;
}

static void fe_sq(fe r, const fe a) { fe_mul(r, a, a); }

static uint64_t load64_le(const uint8_t *b) {
    uint64_t v = 0;
    for (int i = 7; i >= 0; i--) v = (v << 8) | b[i];
    return v;
}

/* 255 low bits of the encoding (bit 255 — the x sign — is dropped);
 * values >= p are fine: arithmetic is mod p (ZIP-215 non-canonical y) */
static void fe_frombytes(fe r, const uint8_t *s) {
    r[0] = load64_le(s) & MASK51;
    r[1] = (load64_le(s + 6) >> 3) & MASK51;
    r[2] = (load64_le(s + 12) >> 6) & MASK51;
    r[3] = (load64_le(s + 19) >> 1) & MASK51;
    r[4] = (load64_le(s + 24) >> 12) & MASK51;
}

/* canonical little-endian encoding (fully reduced mod p) */
static void fe_tobytes(uint8_t *s, const fe a) {
    fe t;
    fe_copy(t, a);
    fe_carry(t);
    fe_carry(t);
    /* q = whether t >= p, computed by propagating (t + 19) carries */
    uint64_t q = (t[0] + 19) >> 51;
    q = (t[1] + q) >> 51;
    q = (t[2] + q) >> 51;
    q = (t[3] + q) >> 51;
    q = (t[4] + q) >> 51;
    t[0] += 19 * q;
    uint64_t c;
    c = t[0] >> 51; t[0] &= MASK51; t[1] += c;
    c = t[1] >> 51; t[1] &= MASK51; t[2] += c;
    c = t[2] >> 51; t[2] &= MASK51; t[3] += c;
    c = t[3] >> 51; t[3] &= MASK51; t[4] += c;
    t[4] &= MASK51;
    uint64_t w0 = t[0] | (t[1] << 51);
    uint64_t w1 = (t[1] >> 13) | (t[2] << 38);
    uint64_t w2 = (t[2] >> 26) | (t[3] << 25);
    uint64_t w3 = (t[3] >> 39) | (t[4] << 12);
    memcpy(s, &w0, 8);
    memcpy(s + 8, &w1, 8);
    memcpy(s + 16, &w2, 8);
    memcpy(s + 24, &w3, 8);
}

static int fe_iszero(const fe a) {
    uint8_t s[32];
    fe_tobytes(s, a);
    uint8_t acc = 0;
    for (int i = 0; i < 32; i++) acc |= s[i];
    return acc == 0;
}

static int fe_eq(const fe a, const fe b) {
    fe d;
    fe_sub(d, a, b);
    return fe_iszero(d);
}

static void fe_sqn(fe r, const fe a, int n) {
    fe_sq(r, a);
    for (int i = 1; i < n; i++) fe_sq(r, r);
}

/* a^(2^252 - 3): the exponent in the combined sqrt/division trick
 * ((p-5)/8), via the standard 2^k-1 addition chain (251 squarings +
 * ~12 multiplies — decompression cost is dominated by this power). */
static void fe_pow2523(fe r, const fe z) {
    fe t0, t1, t2;
    fe_sq(t0, z);                  /* z^2 */
    fe_sqn(t1, t0, 2);
    fe_mul(t1, t1, z);             /* z^9 */
    fe_mul(t0, t1, t0);            /* z^11 */
    fe_sq(t0, t0);                 /* z^22 */
    fe_mul(t0, t0, t1);            /* z^31 = z^(2^5-1) */
    fe_sqn(t1, t0, 5);
    fe_mul(t0, t1, t0);            /* z^(2^10-1) */
    fe_sqn(t1, t0, 10);
    fe_mul(t1, t1, t0);            /* z^(2^20-1) */
    fe_sqn(t2, t1, 20);
    fe_mul(t1, t2, t1);            /* z^(2^40-1) */
    fe_sqn(t1, t1, 10);
    fe_mul(t0, t1, t0);            /* z^(2^50-1) */
    fe_sqn(t1, t0, 50);
    fe_mul(t1, t1, t0);            /* z^(2^100-1) */
    fe_sqn(t2, t1, 100);
    fe_mul(t1, t2, t1);            /* z^(2^200-1) */
    fe_sqn(t1, t1, 50);
    fe_mul(t0, t1, t0);            /* z^(2^250-1) */
    fe_sqn(t0, t0, 2);
    fe_mul(r, t0, z);              /* z^(2^252-3) */
}

/* ------------------------------------------------------------------
 * SHA-512 (FIPS 180-4) — the k = SHA512(R|A|M) challenge hashes, so
 * the whole ed25519 batch prep can run in one native call.
 * ------------------------------------------------------------------ */

static const uint64_t SHA512_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

#define ROR64(x, n) (((x) >> (n)) | ((x) << (64 - (n))))

static void sha512_block(uint64_t st[8], const uint8_t *p) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[i * 8 + j];
        w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
        uint64_t s0 = ROR64(w[i - 15], 1) ^ ROR64(w[i - 15], 8) ^
                      (w[i - 15] >> 7);
        uint64_t s1 = ROR64(w[i - 2], 19) ^ ROR64(w[i - 2], 61) ^
                      (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = st[0], b = st[1], c = st[2], d = st[3], e = st[4],
             f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 80; i++) {
        uint64_t S1 = ROR64(e, 14) ^ ROR64(e, 18) ^ ROR64(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + SHA512_K[i] + w[i];
        uint64_t S0 = ROR64(a, 28) ^ ROR64(a, 34) ^ ROR64(a, 39);
        uint64_t mj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + mj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* digest64 = SHA-512 of the concatenation of up to three chunks */
static void sha512_3(uint8_t out[64], const uint8_t *c1, size_t n1,
                     const uint8_t *c2, size_t n2, const uint8_t *c3,
                     size_t n3) {
    uint64_t st[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
        0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
        0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
    };
    uint8_t buf[128];
    size_t fill = 0;
    uint64_t total = 0;
    const uint8_t *chunks[3] = {c1, c2, c3};
    size_t lens[3] = {n1, n2, n3};
    for (int c = 0; c < 3; c++) {
        const uint8_t *p = chunks[c];
        size_t n = lens[c];
        total += n;
        while (n) {
            size_t take = 128 - fill;
            if (take > n) take = n;
            memcpy(buf + fill, p, take);
            fill += take;
            p += take;
            n -= take;
            if (fill == 128) {
                sha512_block(st, buf);
                fill = 0;
            }
        }
    }
    /* padding: 0x80, zeros, 128-bit big-endian bit length */
    buf[fill++] = 0x80;
    if (fill > 112) {
        memset(buf + fill, 0, 128 - fill);
        sha512_block(st, buf);
        fill = 0;
    }
    memset(buf + fill, 0, 128 - fill);
    uint64_t bits = total * 8;
    for (int j = 0; j < 8; j++)
        buf[120 + j] = (uint8_t)(bits >> (8 * (7 - j)));
    sha512_block(st, buf);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[i * 8 + j] = (uint8_t)(st[i] >> (8 * (7 - j)));
}

/* ------------------------------------------------------------------
 * Scalar arithmetic mod L = 2^252 + delta (delta < 2^125), for the
 * host-prep offload: k = digest mod L, a = z*k mod L, zb = sum z*s.
 * Reduction is Barrett with MU = floor(2^512 / L): q = (x*MU) >> 512,
 * r = x - q*L, then at most two conditional subtracts (classic bound
 * r < 3L). Differential-tested against Python big-ints over random
 * and boundary inputs via the tm_sc_mod_l_test hook
 * (tests/test_crypto.py::test_native_scalar_and_sha512_building_blocks).
 * ------------------------------------------------------------------ */

/* L as 4x64 little-endian limbs */
static const uint64_t SC_L[4] = {
    0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0x0000000000000000ULL,
    0x1000000000000000ULL,
};

static void sc4_frombytes(uint64_t r[4], const uint8_t *b) {
    for (int i = 0; i < 4; i++) r[i] = load64_le(b + 8 * i);
}

static void sc4_tobytes(uint8_t *b, const uint64_t r[4]) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            b[8 * i + j] = (uint8_t)(r[i] >> (8 * j));
}

/* ge/lt over 4-limb little-endian */
static int sc4_gte(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static void sc4_sub(uint64_t r[4], const uint64_t a[4],
                    const uint64_t b[4]) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        unsigned __int128 d =
            (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
        r[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

/* generic little-endian multiply: r[na+nb] = a[na] * b[nb] */
static void sc_mul_nn(uint64_t *r, const uint64_t *a, int na,
                      const uint64_t *b, int nb) {
    memset(r, 0, (size_t)(na + nb) * 8);
    for (int i = 0; i < na; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < nb; j++) {
            unsigned __int128 cur = (unsigned __int128)a[i] * b[j] +
                                    r[i + j] + (uint64_t)carry;
            r[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        r[i + nb] += (uint64_t)carry;
    }
}

/* r(4 limbs, < L) = x (nx <= 8 limbs, little-endian, < 2^512) mod L.
 * Barrett reduction: q = floor(x * MU / 2^512) with
 * MU = floor(2^512 / L); r = x - q*L, then at most a few conditional
 * subtracts (classic bound r < 3L). Differential-tested against
 * Python big-ints over random and boundary inputs. */
static void sc_mod_l(uint64_t r[4], const uint64_t *x, int nx) {
    static const uint64_t MU[5] = {
        0xed9ce5a30a2c131bULL, 0x2106215d086329a7ULL,
        0xffffffffffffffebULL, 0xffffffffffffffffULL,
        0x000000000000000fULL,
    };
    uint64_t xs[8];
    memset(xs, 0, sizeof(xs));
    memcpy(xs, x, (size_t)nx * 8);
    uint64_t prod[13];
    sc_mul_nn(prod, xs, 8, MU, 5);        /* x * MU, 13 limbs */
    uint64_t q[5];
    memcpy(q, prod + 8, 5 * 8);           /* >> 512 */
    uint64_t ql[9];
    sc_mul_nn(ql, q, 5, SC_L, 4);         /* q * L */
    /* r = x - q*L: fits comfortably in 5 limbs (< 3L < 2^254) */
    uint64_t rem[8];
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 8; i++) {
        unsigned __int128 d =
            (unsigned __int128)xs[i] - ql[i] - (uint64_t)borrow;
        rem[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    while (sc4_gte(rem, SC_L)) sc4_sub(rem, rem, SC_L);
    memcpy(r, rem, 32);
}

/* r = a*b mod L (a: 4 limbs < L, b: nb limbs) */
static void sc_mulmod(uint64_t r[4], const uint64_t a[4],
                      const uint64_t *b, int nb) {
    uint64_t prod[8];
    memset(prod, 0, sizeof(prod));
    for (int i = 0; i < 4; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; j < nb; j++) {
            unsigned __int128 cur = (unsigned __int128)a[i] * b[j] +
                                    prod[i + j] + (uint64_t)carry;
            prod[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        int k = i + nb;
        while (carry) {
            unsigned __int128 cur =
                (unsigned __int128)prod[k] + (uint64_t)carry;
            prod[k] = (uint64_t)cur;
            carry = cur >> 64;
            k++;
        }
    }
    sc_mod_l(r, prod, 8);
}

static void sc_addmod(uint64_t r[4], const uint64_t a[4],
                      const uint64_t b[4]) {
    uint64_t sum[5];
    unsigned __int128 carry = 0;
    for (int i = 0; i < 4; i++) {
        unsigned __int128 cur =
            (unsigned __int128)a[i] + b[i] + (uint64_t)carry;
        sum[i] = (uint64_t)cur;
        carry = cur >> 64;
    }
    sum[4] = (uint64_t)carry;
    sc_mod_l(r, sum, 5);
}

/* ------------------------------------------------------------------
 * 8-way field exponentiation with AVX-512 IFMA (radix-2^52, 5 limbs,
 * one zmm register per limb holding 8 field elements). Only the
 * pow2523 chain — the dominant cost of point decompression — runs
 * vectorized; everything else stays scalar radix-2^51. Functions are
 * target-attributed so the binary stays runnable on non-AVX-512
 * hosts (runtime-gated via __builtin_cpu_supports).
 * ------------------------------------------------------------------ */

#define MASK52 0xfffffffffffffULL

/* canonical bytes -> radix-2^52 limbs */
static void fe52_frombytes(uint64_t l[5], const uint8_t *s) {
    l[0] = load64_le(s) & MASK52;
    l[1] = (load64_le(s + 6) >> 4) & MASK52;
    l[2] = load64_le(s + 13) & MASK52;
    l[3] = (load64_le(s + 19) >> 4) & MASK52;
    uint64_t top = 0;
    memcpy(&top, s + 26, 6); /* bits 208..255; input < p so < 2^47 */
    l[4] = top;
}

/* radix-2^52 limbs (each < 2^52) -> canonical bytes */
static void fe52_tobytes(uint8_t *s, const uint64_t l_in[5]) {
    uint64_t l[5];
    memcpy(l, l_in, sizeof(l));
    uint64_t c;
    c = l[0] >> 52; l[0] &= MASK52; l[1] += c;
    c = l[1] >> 52; l[1] &= MASK52; l[2] += c;
    c = l[2] >> 52; l[2] &= MASK52; l[3] += c;
    c = l[3] >> 52; l[3] &= MASK52; l[4] += c;
    /* top limb weight 2^208; bit 47 of it is bit 255 overall */
    c = l[4] >> 47; l[4] &= (1ULL << 47) - 1; l[0] += 19 * c;
    c = l[0] >> 52; l[0] &= MASK52; l[1] += c;
    /* conditional subtract p via the (t + 19) carry trick */
    uint64_t q = (l[0] + 19) >> 52;
    q = (l[1] + q) >> 52;
    q = (l[2] + q) >> 52;
    q = (l[3] + q) >> 52;
    q = (l[4] + q) >> 47;
    l[0] += 19 * q;
    c = l[0] >> 52; l[0] &= MASK52; l[1] += c;
    c = l[1] >> 52; l[1] &= MASK52; l[2] += c;
    c = l[2] >> 52; l[2] &= MASK52; l[3] += c;
    c = l[3] >> 52; l[3] &= MASK52; l[4] += c;
    l[4] &= (1ULL << 47) - 1;
    uint64_t w0 = l[0] | (l[1] << 52);
    uint64_t w1 = (l[1] >> 12) | (l[2] << 40);
    uint64_t w2 = (l[2] >> 24) | (l[3] << 28);
    uint64_t w3 = (l[3] >> 36) | (l[4] << 16);
    memcpy(s, &w0, 8);
    memcpy(s + 8, &w1, 8);
    memcpy(s + 16, &w2, 8);
    memcpy(s + 24, &w3, 8);
}

#ifdef TM_HAVE_IFMA_BUILD

typedef struct { __m512i l[5]; } fe8;

#define TM_IFMA_TARGET \
    __attribute__((target("avx512f,avx512ifma,avx512dq,avx512vl")))

/* r = a * b mod p over 8 lanes. Operand limbs must be < 2^52; output
 * limbs are masked < 2^52. Schoolbook into 10 accumulators via
 * vpmadd52{lo,hi}, then 2^260 = 608 (mod p) folding. */
TM_IFMA_TARGET static void fe8_mul(fe8 *r, const fe8 *a, const fe8 *b) {
    __m512i z = _mm512_setzero_si512();
    __m512i t[10];
    for (int k = 0; k < 10; k++) t[k] = z;
    for (int i = 0; i < 5; i++) {
        for (int j = 0; j < 5; j++) {
            t[i + j] = _mm512_madd52lo_epu64(t[i + j], a->l[i], b->l[j]);
            t[i + j + 1] =
                _mm512_madd52hi_epu64(t[i + j + 1], a->l[i], b->l[j]);
        }
    }
    const __m512i mask = _mm512_set1_epi64((long long)MASK52);
    const __m512i c608 = _mm512_set1_epi64(608); /* 2^260 mod p */
    /* carry the high half so its limbs fit madd52 operands */
    __m512i c;
    for (int k = 5; k < 9; k++) {
        c = _mm512_srli_epi64(t[k], 52);
        t[k] = _mm512_and_si512(t[k], mask);
        t[k + 1] = _mm512_add_epi64(t[k + 1], c);
    }
    c = _mm512_srli_epi64(t[9], 52); /* weight 2^520 = 608^2 mod p */
    t[9] = _mm512_and_si512(t[9], mask);
    t[0] = _mm512_add_epi64(
        t[0], _mm512_mullo_epi64(c, _mm512_set1_epi64(608 * 608)));
    /* fold t[5..9] into t[0..4]: value += 608 * t[5+j] * 2^(52j) */
    for (int j = 0; j < 5; j++) {
        t[j] = _mm512_madd52lo_epu64(t[j], t[5 + j], c608);
        if (j < 4)
            t[j + 1] = _mm512_madd52hi_epu64(t[j + 1], t[5 + j], c608);
    }
    /* hi of 608*t[9] has weight 2^260 again: one more 608 fold */
    __m512i h = _mm512_madd52hi_epu64(z, t[9], c608);
    t[0] = _mm512_madd52lo_epu64(t[0], h, c608);
    /* Two carry passes, FOLD-FIRST ordering: reduce t4's overflow into
     * t0 before t0's own carry is computed, then run the chain down to
     * t4 (which only receives t3's small carry and is NOT re-folded in
     * the same pass). This makes the bound provable: after pass 1 all
     * limbs < 2^56-ish shrink to t0<2^52+2^14, t1..t3 masked, t4<2^48;
     * after pass 2 every limb is strictly < 2^52 — the operand bound
     * vpmadd52 requires (it reads only the low 52 bits). A mask-last
     * ordering would leave t0 <= 2^52+18 reachable in theory. */
    const __m512i mask47 = _mm512_set1_epi64((1LL << 47) - 1);
    const __m512i c19 = _mm512_set1_epi64(19);
    for (int pass = 0; pass < 2; pass++) {
        c = _mm512_srli_epi64(t[4], 47); /* bit 255 boundary */
        t[4] = _mm512_and_si512(t[4], mask47);
        t[0] = _mm512_add_epi64(t[0], _mm512_mullo_epi64(c, c19));
        for (int k = 0; k < 4; k++) {
            c = _mm512_srli_epi64(t[k], 52);
            t[k] = _mm512_and_si512(t[k], mask);
            t[k + 1] = _mm512_add_epi64(t[k + 1], c);
        }
    }
    for (int k = 0; k < 5; k++) r->l[k] = t[k];
}

TM_IFMA_TARGET static void fe8_sqn(fe8 *r, int n) {
    for (int i = 0; i < n; i++) fe8_mul(r, r, r);
}

/* the fe_pow2523 addition chain, 8 lanes at once */
TM_IFMA_TARGET static void fe8_pow2523(fe8 *r, const fe8 *zin) {
    fe8 z = *zin, t0, t1, t2;
    fe8_mul(&t0, &z, &z);               /* z^2 */
    t1 = t0;
    fe8_sqn(&t1, 2);
    fe8_mul(&t1, &t1, &z);              /* z^9 */
    fe8_mul(&t0, &t1, &t0);             /* z^11 */
    fe8_mul(&t0, &t0, &t0);             /* z^22 */
    fe8_mul(&t0, &t0, &t1);             /* z^31 */
    t1 = t0;
    fe8_sqn(&t1, 5);
    fe8_mul(&t0, &t1, &t0);             /* z^(2^10-1) */
    t1 = t0;
    fe8_sqn(&t1, 10);
    fe8_mul(&t1, &t1, &t0);             /* z^(2^20-1) */
    t2 = t1;
    fe8_sqn(&t2, 20);
    fe8_mul(&t1, &t2, &t1);             /* z^(2^40-1) */
    fe8_sqn(&t1, 10);
    fe8_mul(&t0, &t1, &t0);             /* z^(2^50-1) */
    t1 = t0;
    fe8_sqn(&t1, 50);
    fe8_mul(&t1, &t1, &t0);             /* z^(2^100-1) */
    t2 = t1;
    fe8_sqn(&t2, 100);
    fe8_mul(&t1, &t2, &t1);             /* z^(2^200-1) */
    fe8_sqn(&t1, 50);
    fe8_mul(&t0, &t1, &t0);             /* z^(2^250-1) */
    fe8_sqn(&t0, 2);
    fe8_mul(r, &t0, &z);                /* z^(2^252-3) */
}

/* vals[0..7] (radix-51) -> pow2523 of each, in place */
TM_IFMA_TARGET static void pow2523_x8(fe *vals) {
    uint64_t limbs[8][5];
    uint8_t buf[32];
    for (int e = 0; e < 8; e++) {
        fe_tobytes(buf, vals[e]);
        fe52_frombytes(limbs[e], buf);
    }
    fe8 x;
    for (int k = 0; k < 5; k++) {
        uint64_t lane[8];
        for (int e = 0; e < 8; e++) lane[e] = limbs[e][k];
        x.l[k] = _mm512_loadu_si512((const void *)lane);
    }
    fe8 out;
    fe8_pow2523(&out, &x);
    for (int k = 0; k < 5; k++) {
        uint64_t lane[8];
        _mm512_storeu_si512((void *)lane, out.l[k]);
        for (int e = 0; e < 8; e++) limbs[e][k] = lane[e];
    }
    for (int e = 0; e < 8; e++) {
        fe52_tobytes(buf, limbs[e]);
        fe_frombytes(vals[e], buf);
    }
}

static int have_ifma(void) {
    static int cached = -1;
    if (cached < 0) {
        const char *off = getenv("TM_TPU_NO_IFMA");
        cached = !(off && off[0]) &&
                 __builtin_cpu_supports("avx512ifma") &&
                 __builtin_cpu_supports("avx512f") &&
                 __builtin_cpu_supports("avx512dq");
    }
    return cached;
}

#else /* !TM_HAVE_IFMA_BUILD */

static int have_ifma(void) { return 0; }

static void pow2523_x8(fe *vals) { (void)vals; }

#endif

/* pow2523 over an array: IFMA 8-way where possible, scalar remainder */
static void pow2523_many(fe *vals, size_t n) {
    size_t i = 0;
    if (have_ifma())
        for (; i + 8 <= n; i += 8) pow2523_x8(vals + i);
    for (; i < n; i++) fe_pow2523(vals[i], vals[i]);
}

/* extended (twisted Edwards) coordinates, mirrors ed25519_math.Point */
typedef struct { fe X, Y, Z, T; } ge;

static void ge_identity(ge *r) {
    fe_zero(r->X);
    fe_one(r->Y);
    fe_one(r->Z);
    fe_zero(r->T);
}

/* unified add-2008-hwcd-3 (complete for a=-1, d nonsquare — same
 * formula as ed25519_math.point_add, valid for P==Q and small order) */
static void ge_add(ge *r, const ge *p, const ge *q) {
    fe a, b, c, d, e, f, g, h, t1, t2;
    fe_sub(t1, p->Y, p->X);
    fe_sub(t2, q->Y, q->X);
    fe_carry(t1);
    fe_carry(t2);
    fe_mul(a, t1, t2);
    fe_add(t1, p->Y, p->X);
    fe_add(t2, q->Y, q->X);
    fe_mul(b, t1, t2);
    fe_mul(c, p->T, FE_2D);
    fe_mul(c, c, q->T);
    fe_mul(d, p->Z, q->Z);
    fe_add(d, d, d);
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_carry(e);
    fe_carry(f);
    fe_carry(g);
    fe_carry(h);
    fe_mul(r->X, e, f);
    fe_mul(r->Y, g, h);
    fe_mul(r->Z, f, g);
    fe_mul(r->T, e, h);
}

/* Cached-operand form of a Z=1 point (decoded/negated terms and the
 * basepoint all have Z=1): q_cached = (Y-X, Y+X, 2d*T). Addition
 * against it costs 7 muls instead of 9 — same hwcd-3 formula with the
 * two operand-prep muls and the Z2 mul hoisted out (Dv = 2*Z1). */
typedef struct { fe YmX, YpX, T2d; } ge_cached;

static void ge_to_cached(ge_cached *c, const ge *p) {
    fe_sub(c->YmX, p->Y, p->X);
    fe_carry(c->YmX);
    fe_add(c->YpX, p->Y, p->X);
    fe_carry(c->YpX);
    fe_mul(c->T2d, p->T, FE_2D);
}

static void ge_add_cached(ge *r, const ge *p, const ge_cached *q) {
    fe a, b, c, d, e, f, g, h, t1;
    fe_sub(t1, p->Y, p->X);
    fe_carry(t1);
    fe_mul(a, t1, q->YmX);
    fe_add(t1, p->Y, p->X);
    fe_mul(b, t1, q->YpX);
    fe_mul(c, p->T, q->T2d);
    fe_add(d, p->Z, p->Z);       /* Z2 == 1 */
    fe_carry(d);
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_carry(e);
    fe_carry(f);
    fe_carry(g);
    fe_carry(h);
    fe_mul(r->X, e, f);
    fe_mul(r->Y, g, h);
    fe_mul(r->Z, f, g);
    fe_mul(r->T, e, h);
}

/* dbl-2008-hwcd, mirrors ed25519_math.point_double */
static void ge_dbl(ge *r, const ge *p) {
    fe a, b, c, h, e, g, f, t;
    fe_sq(a, p->X);
    fe_sq(b, p->Y);
    fe_sq(c, p->Z);
    fe_add(c, c, c);
    fe_carry(c);
    fe_add(h, a, b);
    fe_carry(h);
    fe_add(t, p->X, p->Y);
    fe_carry(t);
    fe_sq(t, t);
    fe_sub(e, h, t);
    fe_sub(g, a, b);
    fe_add(f, c, g);
    fe_carry(e);
    fe_carry(g);
    fe_carry(f);
    fe_mul(r->X, e, f);
    fe_mul(r->Y, g, h);
    fe_mul(r->Z, f, g);
    fe_mul(r->T, e, h);
}

static void ge_neg(ge *r, const ge *p) {
    fe_neg(r->X, p->X);
    fe_carry(r->X);
    fe_copy(r->Y, p->Y);
    fe_copy(r->Z, p->Z);
    fe_neg(r->T, p->T);
    fe_carry(r->T);
}

/* ZIP-215 decompression, mirroring ed25519_math.decompress/_recover_x:
 * non-canonical y accepted (reduced mod p); x recovered via the
 * combined sqrt; "-0" (x == 0 with sign bit 1) rejected. Split into
 * prelude -> pow2523 -> finish so the dominant power can be computed
 * for 8 points at once (the IFMA batch path); the scalar wrapper at
 * the bottom preserves the one-shot form. */
static void zip215_pre(const uint8_t *s, fe u, fe v, fe powin) {
    fe y, y2, t;
    fe_frombytes(y, s);
    fe_sq(y2, y);
    fe_one(u);
    fe_sub(u, y2, u);
    fe_carry(u);                 /* u = y^2 - 1 */
    fe_mul(v, y2, FE_D);
    fe_one(t);
    fe_add(v, v, t);
    fe_carry(v);                 /* v = d*y^2 + 1 */
    fe_sq(t, v);
    fe_mul(t, t, v);             /* v^3 */
    fe_sq(powin, t);
    fe_mul(powin, powin, v);     /* v^7 */
    fe_mul(powin, powin, u);     /* u*v^7 */
}

static int zip215_fin(ge *r, const uint8_t *s, const fe u, const fe v,
                      const fe powed) {
    fe v3, x, vx2, y;
    int sign = s[31] >> 7;
    fe_sq(v3, v);
    fe_mul(v3, v3, v);           /* v^3 */
    fe_mul(x, powed, v3);
    fe_mul(x, x, u);             /* x = u*v^3*(u*v^7)^((p-5)/8) */

    fe_sq(vx2, x);
    fe_mul(vx2, vx2, v);         /* v*x^2 */
    if (!fe_eq(vx2, u)) {
        fe nu;
        fe_neg(nu, u);
        if (!fe_eq(vx2, nu)) return 0;  /* u/v is not a square */
        fe_mul(x, x, FE_SQRTM1);        /* now v*x^2 == u */
    }

    uint8_t xb[32];
    fe_tobytes(xb, x);
    int xzero = 1;
    for (int i = 0; i < 32; i++) xzero &= (xb[i] == 0);
    if (xzero && sign) return 0; /* "-0" rejected (RFC 8032 + ZIP-215) */
    if ((xb[0] & 1) != sign) {
        fe_neg(x, x);
        fe_carry(x);
    }
    fe_frombytes(y, s);
    fe_copy(r->X, x);
    fe_copy(r->Y, y);
    fe_one(r->Z);
    fe_mul(r->T, x, y);
    return 1;
}

/* uniform prelude/finish adapters so the batch driver can run the
 * pow2523 stage for the whole batch at once: slots a..d hold the
 * per-curve intermediates (zip215: a=u, b=v; ristretto: a=u1, b=u2,
 * c=v, d=vu) */
typedef struct { fe a, b, c, d; } pre_t;

static int zip215_pre2(const uint8_t *s, pre_t *p, fe powin) {
    zip215_pre(s, p->a, p->b, powin);
    return 1;
}

static int zip215_fin2(ge *r, const uint8_t *s, const pre_t *p,
                       const fe powed) {
    return zip215_fin(r, s, p->a, p->b, powed);
}

/* ristretto255 decode (RFC 9496 §4.3.1, mirrors crypto/ristretto.py
 * decode): canonical nonneg s -> extended point representative in 2E.
 * Split into prelude -> pow2523 -> finish like the ZIP-215 decoder;
 * the power input is vu^7 (sqrt_ratio with u=1: r = vu^3*(vu^7)^e). */
static int rist_pre(const uint8_t *bytes, fe u1, fe u2, fe v, fe vu,
                    fe powin) {
    fe s, one, ss, u2s, du1;
    uint8_t canon[32];
    fe_frombytes(s, bytes);
    fe_tobytes(canon, s);
    /* canonical: no high bit, value < p (re-encode matches), even */
    if ((bytes[31] & 0x80) || memcmp(canon, bytes, 32) != 0) return 0;
    if (bytes[0] & 1) return 0;
    fe_one(one);
    fe_sq(ss, s);
    fe_sub(u1, one, ss);
    fe_carry(u1);                /* 1 - s^2 */
    fe_add(u2, one, ss);
    fe_carry(u2);                /* 1 + s^2 */
    fe_sq(u2s, u2);
    fe_sq(du1, u1);
    fe_mul(du1, du1, FE_D);      /* D*u1^2 */
    fe_neg(v, du1);
    fe_carry(v);
    fe_sub(v, v, u2s);
    fe_carry(v);                 /* -D*u1^2 - u2^2 */
    fe_mul(vu, v, u2s);
    fe_sq(powin, vu);
    fe_mul(powin, powin, vu);    /* vu^3 */
    fe_sq(powin, powin);
    fe_mul(powin, powin, vu);    /* vu^7 */
    return 1;
}

static int rist_fin(ge *r, const uint8_t *bytes, const fe u1, const fe u2,
                    const fe v, const fe vu, const fe powed) {
    fe s, one, invsq, check, none, nonei, dx, dy, x, y, tt, s2;
    fe_frombytes(s, bytes);
    fe_one(one);
    fe_sq(invsq, vu);
    fe_mul(invsq, invsq, vu);    /* vu^3 */
    fe_mul(invsq, invsq, powed); /* vu^3*(vu^7)^((p-5)/8) */
    /* sqrt_ratio_m1(1, vu) checks (mirrors fe_sqrt_ratio_m1 u=1) */
    fe_sq(check, invsq);
    fe_mul(check, check, vu);    /* vu*r^2 */
    int correct = fe_eq(check, one);
    fe_neg(none, one);
    fe_carry(none);
    int flipped = fe_eq(check, none);
    fe_mul(nonei, none, FE_SQRTM1);
    int flipped_i = fe_eq(check, nonei);
    if (flipped || flipped_i) fe_mul(invsq, invsq, FE_SQRTM1);
    uint8_t ib[32];
    fe_tobytes(ib, invsq);
    if (ib[0] & 1) {             /* |r| */
        fe_neg(invsq, invsq);
        fe_carry(invsq);
    }
    int was_square = correct || flipped;
    fe_mul(dx, invsq, u2);
    fe_mul(dy, invsq, dx);
    fe_mul(dy, dy, v);
    fe_add(s2, s, s);
    fe_carry(s2);
    fe_mul(x, s2, dx);
    uint8_t xb[32];
    fe_tobytes(xb, x);
    if (xb[0] & 1) {             /* |x| */
        fe_neg(x, x);
        fe_carry(x);
    }
    fe_mul(y, u1, dy);
    fe_mul(tt, x, y);
    uint8_t tb[32];
    fe_tobytes(tb, tt);
    if (!was_square || (tb[0] & 1) || fe_iszero(y)) return 0;
    fe_copy(r->X, x);
    fe_copy(r->Y, y);
    fe_one(r->Z);
    fe_copy(r->T, tt);
    return 1;
}

static int rist_pre2(const uint8_t *s, pre_t *p, fe powin) {
    return rist_pre(s, p->a, p->b, p->c, p->d, powin);
}

static int rist_fin2(ge *r, const uint8_t *s, const pre_t *p,
                     const fe powed) {
    return rist_fin(r, s, p->a, p->b, p->c, p->d, powed);
}

/* ---- ristretto255 encode (RFC 9496 §4.3.2) -------------------------
 *
 * The inverse of rist_pre/rist_fin, needed by the sign/keygen path
 * (R = r*B and A = a*B leave the library as canonical 32-byte
 * encodings). Mirrors crypto/ristretto.py encode() — that Python
 * implementation is the differential oracle in the tests. */

/* 1/sqrt(a-d) = sqrt_ratio_m1(1, a-d) for a = -1, nonneg root
 * (value from crypto/ristretto.py _INVSQRT_A_MINUS_D) */
static const fe FE_INVSQRT_AMD = {
    0x0fdaa805d40eaULL, 0x2eb482e57d339ULL, 0x007610274bc58ULL,
    0x6510b613dc8ffULL, 0x786c8905cfaffULL};

static int fe_isneg(const fe a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    return b[0] & 1;
}

/* r = |1/sqrt(v)| via sqrt_ratio_m1(1, v): r = v^3*(v^7)^((p-5)/8)
 * with the sqrt(-1) fixups; returns was_square. Single-shot form of
 * the inline sequence in rist_fin (which takes a batched power). */
static int fe_invsqrt(fe r, const fe v) {
    fe powin, powed, check, one, none, nonei;
    fe_sq(powin, v);
    fe_mul(powin, powin, v);     /* v^3 */
    fe_sq(powin, powin);
    fe_mul(powin, powin, v);     /* v^7 */
    fe_pow2523(powed, powin);
    fe_sq(r, v);
    fe_mul(r, r, v);             /* v^3 */
    fe_mul(r, r, powed);         /* v^3*(v^7)^((p-5)/8) */
    fe_sq(check, r);
    fe_mul(check, check, v);     /* v*r^2 */
    fe_one(one);
    int correct = fe_eq(check, one);
    fe_neg(none, one);
    fe_carry(none);
    int flipped = fe_eq(check, none);
    fe_mul(nonei, none, FE_SQRTM1);
    int flipped_i = fe_eq(check, nonei);
    if (flipped || flipped_i) fe_mul(r, r, FE_SQRTM1);
    if (fe_isneg(r)) {           /* |r| */
        fe_neg(r, r);
        fe_carry(r);
    }
    return correct || flipped;
}

static void rist_encode(uint8_t out[32], const ge *p) {
    fe u1, u2, t1, invsq, den1, den2, zinv, x, y, den_inv, tmp, s;
    fe_add(t1, p->Z, p->Y);
    fe_carry(t1);
    fe_sub(u1, p->Z, p->Y);
    fe_carry(u1);
    fe_mul(u1, t1, u1);          /* (Z+Y)(Z-Y) */
    fe_mul(u2, p->X, p->Y);
    fe_sq(tmp, u2);
    fe_mul(tmp, tmp, u1);        /* u1*u2^2 */
    fe_invsqrt(invsq, tmp);      /* square for every valid point */
    fe_mul(den1, invsq, u1);
    fe_mul(den2, invsq, u2);
    fe_mul(zinv, den1, den2);
    fe_mul(zinv, zinv, p->T);
    fe_mul(tmp, p->T, zinv);
    if (fe_isneg(tmp)) {         /* rotate */
        fe ix, iy;
        fe_mul(ix, p->X, FE_SQRTM1);
        fe_mul(iy, p->Y, FE_SQRTM1);
        fe_copy(x, iy);
        fe_copy(y, ix);
        fe_mul(den_inv, den1, FE_INVSQRT_AMD);
    } else {
        fe_copy(x, p->X);
        fe_copy(y, p->Y);
        fe_copy(den_inv, den2);
    }
    fe_mul(tmp, x, zinv);
    if (fe_isneg(tmp)) {
        fe_neg(y, y);
        fe_carry(y);
    }
    fe_sub(s, p->Z, y);
    fe_carry(s);
    fe_mul(s, den_inv, s);
    if (fe_isneg(s)) {           /* |s| */
        fe_neg(s, s);
        fe_carry(s);
    }
    fe_tobytes(out, s);
}

/* ---- decoded-point cache -------------------------------------------
 *
 * The reference caches 4096 expanded public keys for repeated
 * verification (crypto/ed25519/ed25519.go:50-56, curve25519-voi's
 * cache.Verifier): consensus re-verifies the same validator set every
 * height and light sync re-verifies the same ~150 keys per header, so
 * the decompression (dominated by the pow2523 sqrt) is pure rework.
 * Here the cache lives at the decode seam of the batch driver: A_i
 * (pubkey) slots consult it; R_i (nonce) slots never repeat and skip
 * it. Keyed by the EXACT 32-byte encoding plus a curve id — ZIP-215
 * accepts non-canonical encodings that decode differently from their
 * canonical forms, and the same bytes under the ristretto decoder give
 * an unrelated point, so both must be part of the identity.
 *
 * 4-way set-associative, 8192 sets (32768 entries, ~7.6 MB): a 10k
 * validator set loads the sets at lambda=1.22, where Poisson overflow
 * past 4 ways — each overflow is a repeated miss every height — is
 * <1% of keys (at 4096 sets it measured 35% eviction churn).
 * Round-robin eviction per set,
 * lazily allocated. Guarded by a dependency-free C11 spinlock: ctypes
 * releases the GIL during calls, so two Python threads can be in the
 * library at once; the critical sections are memcmp/memcpy-short.
 * TM_TPU_NO_PKCACHE=1 disables (A/B switch, like TM_TPU_NO_IFMA). */

#include <stdatomic.h>

#define PKC_SETS 8192u /* power of two */
#define PKC_WAYS 4u

typedef struct {
    uint8_t key[32];
    uint8_t curve;  /* 1 = zip215, 2 = ristretto255 */
    uint8_t valid;
    ge pt;          /* decoded extended point, Z = 1 */
} pkc_entry;

static pkc_entry *pkc_table; /* PKC_SETS * PKC_WAYS, lazy */
static uint8_t pkc_rr[PKC_SETS];
static atomic_flag pkc_lock = ATOMIC_FLAG_INIT;
/* hits = lookups served from the table; misses = fresh successful
 * decodes of uncached keys (counted at insert, so a batch that aborts
 * on an undecodable encoding doesn't skew the ratio); inserts tracks
 * misses except under alloc failure; evictions = overwritten ways. */
static uint64_t pkc_stats[4]; /* hits, misses, inserts, evictions */

static void pkc_acquire(void) {
    while (atomic_flag_test_and_set_explicit(&pkc_lock,
                                             memory_order_acquire)) {
    }
}

static void pkc_release(void) {
    atomic_flag_clear_explicit(&pkc_lock, memory_order_release);
}

static int pkc_enabled(void) {
    static int cached = -1;
    if (cached < 0) {
        const char *off = getenv("TM_TPU_NO_PKCACHE");
        cached = !(off && off[0]);
    }
    return cached;
}

static unsigned pkc_set(const uint8_t *key, uint8_t curve) {
    /* point encodings are near-uniform bytes; fold + one mix step */
    uint64_t h = load64_le(key) ^ load64_le(key + 8) ^
                 load64_le(key + 16) ^ load64_le(key + 24);
    h ^= (uint64_t)curve * 0x9e3779b97f4a7c15ULL;
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdULL;
    h ^= h >> 33;
    return (unsigned)(h & (PKC_SETS - 1));
}

/* 1 = hit (out filled), 0 = miss. Never allocates. */
static int pkc_get(uint8_t curve, const uint8_t *key, ge *out) {
    if (!pkc_enabled()) return 0;
    int hit = 0;
    pkc_acquire();
    if (pkc_table) {
        pkc_entry *set = pkc_table + (size_t)pkc_set(key, curve) * PKC_WAYS;
        for (unsigned w = 0; w < PKC_WAYS; w++) {
            if (set[w].valid && set[w].curve == curve &&
                memcmp(set[w].key, key, 32) == 0) {
                *out = set[w].pt;
                hit = 1;
                break;
            }
        }
    }
    if (hit) pkc_stats[0]++;
    pkc_release();
    return hit;
}

static void pkc_put(uint8_t curve, const uint8_t *key, const ge *pt) {
    if (!pkc_enabled()) return;
    pkc_acquire();
    pkc_stats[1]++; /* a completed fresh decode == the real miss */
    if (!pkc_table) {
        pkc_table = calloc((size_t)PKC_SETS * PKC_WAYS, sizeof(pkc_entry));
        if (!pkc_table) { /* allocation failure: stay cacheless */
            pkc_release();
            return;
        }
    }
    unsigned si = pkc_set(key, curve);
    pkc_entry *set = pkc_table + (size_t)si * PKC_WAYS;
    unsigned victim = PKC_WAYS;
    for (unsigned w = 0; w < PKC_WAYS; w++) {
        if (set[w].valid && set[w].curve == curve &&
            memcmp(set[w].key, key, 32) == 0) {
            victim = w; /* refresh in place */
            break;
        }
        if (victim == PKC_WAYS && !set[w].valid) victim = w;
    }
    if (victim == PKC_WAYS) {
        victim = pkc_rr[si];
        pkc_rr[si] = (uint8_t)((pkc_rr[si] + 1) % PKC_WAYS);
        pkc_stats[3]++;
    }
    memcpy(set[victim].key, key, 32);
    set[victim].curve = curve;
    set[victim].pt = *pt;
    set[victim].valid = 1;
    pkc_stats[2]++;
    pkc_release();
}

/* test/observability hooks */
void tm_pk_cache_stats(uint64_t out[4]) {
    pkc_acquire();
    memcpy(out, pkc_stats, sizeof(pkc_stats));
    pkc_release();
}

void tm_pk_cache_clear(void) {
    pkc_acquire();
    if (pkc_table)
        memset(pkc_table, 0,
               (size_t)PKC_SETS * PKC_WAYS * sizeof(pkc_entry));
    memset(pkc_rr, 0, sizeof(pkc_rr));
    memset(pkc_stats, 0, sizeof(pkc_stats));
    pkc_release();
}

/* little-endian bit-window extraction: `width` bits starting at
 * `bitpos` (width <= 16, so at most 3 bytes are touched) */
static inline unsigned get_window(const uint8_t *scalar, int bitpos,
                                  int width) {
    int byte = bitpos >> 3, shift = bitpos & 7;
    unsigned v = scalar[byte];
    if (byte + 1 < 32) v |= (unsigned)scalar[byte + 1] << 8;
    if (shift + width > 16 && byte + 2 < 32)
        v |= (unsigned)scalar[byte + 2] << 16;
    return (v >> shift) & ((1u << width) - 1);
}

/* Pippenger with `width`-bit windows: per-term cost ~(256/width) adds
 * plus a fixed 2*2^width-add bucket aggregation per window — the
 * large-batch MSM. width 8 suits mid-size batches, width 11 the
 * 8192-signature calls (bucket array must stay L2-resident). */
static int ge_msm_pippenger(ge *result, const uint8_t *scalars,
                            const ge *pts, size_t n, int width) {
    int nbuckets = (1 << width) - 1;
    int nwindows = (253 + width - 1) / width;
    ge *buckets = malloc((size_t)nbuckets * sizeof(ge));
    /* terms are Z=1 (decoded points / the basepoint): precompute the
     * cached form once so every bucket add costs 7 muls, not 9 */
    ge_cached *cpts = malloc(n * sizeof(ge_cached));
    if (!buckets || !cpts) {
        free(buckets);
        free(cpts);
        return 0;
    }
    for (size_t i = 0; i < n; i++) ge_to_cached(&cpts[i], &pts[i]);
    ge_identity(result);
    for (int w = nwindows - 1; w >= 0; w--) {
        if (w != nwindows - 1)
            for (int k = 0; k < width; k++) ge_dbl(result, result);
        for (int d = 0; d < nbuckets; d++) ge_identity(&buckets[d]);
        for (size_t i = 0; i < n; i++) {
            unsigned d = get_window(scalars + i * 32, w * width, width);
            if (d)
                ge_add_cached(&buckets[d - 1], &buckets[d - 1], &cpts[i]);
        }
        ge run, acc;
        ge_identity(&run);
        ge_identity(&acc);
        for (int d = nbuckets - 1; d >= 0; d--) {
            ge_add(&run, &run, &buckets[d]);
            ge_add(&acc, &acc, &run);
        }
        ge_add(result, result, &acc);
    }
    free(buckets);
    free(cpts);
    return 1;
}

/* Straus with 4-bit windows and per-term tables: ~78 adds per term
 * with only a ~250-doubling fixed cost — wins below ~1000 terms
 * (commit-sized batches and single verifies). */
static int ge_msm_straus(ge *result, const uint8_t *scalars,
                         const ge *pts, size_t n) {
    /* tables[i][d-1] = d * pts[i] for d in 1..15 */
    ge *tables = malloc(n * 15 * sizeof(ge));
    if (!tables) return 0;
    for (size_t i = 0; i < n; i++) {
        ge *t = tables + i * 15;
        t[0] = pts[i];
        for (int d = 1; d < 15; d++) ge_add(&t[d], &t[d - 1], &pts[i]);
    }
    ge_identity(result);
    for (int w = 63; w >= 0; w--) {
        if (w != 63)
            for (int k = 0; k < 4; k++) ge_dbl(result, result);
        int byte = w >> 1;
        for (size_t i = 0; i < n; i++) {
            int b = scalars[i * 32 + byte];
            int d = (w & 1) ? (b >> 4) : (b & 0x0f);
            if (d) ge_add(result, result, &tables[i * 15 + d - 1]);
        }
    }
    free(tables);
    return 1;
}

/* MSM dispatch by term count (total adds, ~offsets included):
 *   Straus w4      ~78n + 250        — small batches and singles
 *   Pippenger w8   ~64n + 16k        — mid batches
 *   Pippenger w11  ~23n + 94k        — big batches (8192-sig calls);
 *                  w13 models fewer adds but its 1.3 MB bucket array
 *                  thrashes L2 and measured SLOWER — don't "fix" this
 * Crossovers: Straus->w8 at ~1.1k terms, w8->w11 at ~3.4k terms.
 * Scalars are 32-byte little-endian (< L < 2^253). */
static int ge_msm(ge *result, const uint8_t *scalars, const ge *pts,
                  size_t n) {
    if (n < 1024 && ge_msm_straus(result, scalars, pts, n)) return 1;
    if (n >= 3400 && ge_msm_pippenger(result, scalars, pts, n, 11))
        return 1;
    if (ge_msm_pippenger(result, scalars, pts, n, 8)) return 1;
    return ge_msm_straus(result, scalars, pts, n);
}

/* Shared driver: decode all A_i/R_i (prelude pass, batched pow2523,
 * finish pass), then check
 * [8](zb*B + sum a_i*(-A_i) + sum z_i*(-R_i)) == identity.
 * A_i slots go through the decoded-point cache (curve tags the
 * decoder); R_i nonces never repeat, so they always decode. Only the
 * cache misses enter the batched pow2523 stage — the point of the
 * cache is skipping that power for keys seen last height. */
static int batch_verify_common(
    const uint8_t *pk_bytes, const uint8_t *r_bytes, const uint8_t *zb,
    const uint8_t *a_scalars, const uint8_t *z_scalars, uint64_t n,
    uint8_t curve, int (*pre)(const uint8_t *, pre_t *, fe),
    int (*fin)(ge *, const uint8_t *, const pre_t *, const fe)) {
    size_t nterms = 2 * (size_t)n + 1;
    size_t npts = 2 * (size_t)n;
    ge *pts = malloc(nterms * sizeof(ge));
    uint8_t *scalars = malloc(nterms * 32);
    pre_t *pres = malloc(npts * sizeof(pre_t));
    fe *pows = malloc(npts * sizeof(fe));
    uint32_t *need = malloc(npts * sizeof(uint32_t));
    size_t nneed = 0;
    int rc = -1;
    if (!pts || !scalars || !pres || !pows || !need) goto done;

    /* term 0: zb * B */
    fe_copy(pts[0].X, FE_BX);
    fe_copy(pts[0].Y, FE_BY);
    fe_one(pts[0].Z);
    fe_copy(pts[0].T, FE_BT);
    memcpy(scalars, zb, 32);

    /* pass 1: cache lookups + preludes (canonicality + everything
     * before the power). Term slot i = A_i, n+i = R_i; pres/pows are
     * compact over the slots that actually need a decode. */
    for (uint64_t i = 0; i < n; i++) {
        ge cached;
        if (pkc_get(curve, pk_bytes + 32 * i, &cached)) {
            ge_neg(&pts[1 + i], &cached);
        } else {
            if (!pre(pk_bytes + 32 * i, &pres[nneed], pows[nneed]))
                goto done;
            need[nneed++] = (uint32_t)i;
        }
        if (!pre(r_bytes + 32 * i, &pres[nneed], pows[nneed])) goto done;
        need[nneed++] = (uint32_t)(n + i);
        memcpy(scalars + 32 * (1 + i), a_scalars + 32 * i, 32);
        memcpy(scalars + 32 * (1 + n + i), z_scalars + 32 * i, 32);
    }

    /* pass 2: the sqrt/division powers for the misses (8-way IFMA
     * lanes when the host supports it) */
    pow2523_many(pows, nneed);

    /* pass 3: finish decoding, negate into the term array, insert
     * fresh A_i decodes into the cache */
    for (size_t j = 0; j < nneed; j++) {
        uint32_t slot = need[j];
        const uint8_t *enc = slot < n ? pk_bytes + 32 * (size_t)slot
                                      : r_bytes + 32 * ((size_t)slot - n);
        ge t;
        if (!fin(&t, enc, &pres[j], pows[j])) goto done;
        if (slot < n) pkc_put(curve, enc, &t);
        ge_neg(&pts[1 + slot], &t);
    }

    {
        ge sum;
        if (!ge_msm(&sum, scalars, pts, nterms)) goto done; /* rc -1 */
        /* cofactored: [8] * sum must be the identity */
        ge_dbl(&sum, &sum);
        ge_dbl(&sum, &sum);
        ge_dbl(&sum, &sum);
        /* identity in extended coords: X == 0 and Y == Z */
        rc = (fe_iszero(sum.X) && fe_eq(sum.Y, sum.Z)) ? 1 : 0;
    }

done:
    free(pts);
    free(scalars);
    free(pres);
    free(pows);
    free(need);
    return rc;
}

/* See file header for the contract. */
int tm_ed25519_batch_verify(const uint8_t *pk_bytes, const uint8_t *r_bytes,
                            const uint8_t *zb, const uint8_t *a_scalars,
                            const uint8_t *z_scalars, uint64_t n) {
    return batch_verify_common(pk_bytes, r_bytes, zb, a_scalars, z_scalars,
                               n, 1, zip215_pre2, zip215_fin2);
}

/* Whole-batch ed25519 verify with the host prep done natively: the
 * challenge hashes k_i = SHA512(R|A|M) mod L, the random-linear-
 * combination products a_i = z_i*k_i and zb = sum z_i*s_i mod L, and
 * the cofactored batch equation — one call, no per-signature Python.
 * sigs = n*64 (R||s); msgs = concatenated messages with n+1 offsets;
 * rand16 = n*16 random weights (caller-supplied so the RLC randomness
 * stays under the caller's control). Limb loads/stores go through the
 * endian-neutral byte helpers like the rest of the file. Returns
 * 1/0/-1 like the others;
 * a non-canonical s (>= L) returns 0 (invalid somewhere — caller
 * falls back per-signature for the bitmap). */
int tm_ed25519_verify_full(const uint8_t *pks, const uint8_t *sigs,
                           const uint8_t *msgs, const uint64_t *moffs,
                           const uint8_t *rand16, uint64_t n) {
    uint8_t *a_sc = malloc(n * 32);
    uint8_t *z_sc = malloc(n * 32);
    uint8_t *r_b = malloc(n * 32);
    if (!a_sc || !z_sc || !r_b) {
        free(a_sc);
        free(z_sc);
        free(r_b);
        return -1;
    }
    int rc;
    uint64_t zb[4] = {0, 0, 0, 0};
    for (uint64_t i = 0; i < n; i++) {
        const uint8_t *sig = sigs + 64 * i;
        uint64_t s[4];
        sc4_frombytes(s, sig + 32);
        if (sc4_gte(s, SC_L)) {
            rc = 0; /* non-canonical s: invalid under ZIP-215 */
            goto done;
        }
        uint8_t dig[64];
        sha512_3(dig, sig, 32, pks + 32 * i, 32, msgs + moffs[i],
                 (size_t)(moffs[i + 1] - moffs[i]));
        uint64_t d8[8], k[4], z[2], a[4], zs[4];
        for (int w = 0; w < 8; w++) d8[w] = load64_le(dig + 8 * w);
        sc_mod_l(k, d8, 8);
        z[0] = load64_le(rand16 + 16 * i);
        z[1] = load64_le(rand16 + 16 * i + 8);
        sc_mulmod(a, k, z, 2);
        sc4_tobytes(a_sc + 32 * i, a);
        sc_mulmod(zs, s, z, 2);
        sc_addmod(zb, zb, zs);
        memset(z_sc + 32 * i, 0, 32);
        memcpy(z_sc + 32 * i, rand16 + 16 * i, 16);
        memcpy(r_b + 32 * i, sig, 32);
    }
    uint8_t zb_bytes[32];
    sc4_tobytes(zb_bytes, zb);
    rc = batch_verify_common(pks, r_b, zb_bytes, a_sc, z_sc, n, 1,
                             zip215_pre2, zip215_fin2);
done:
    free(a_sc);
    free(z_sc);
    free(r_b);
    return rc;
}

/* test hooks: differential checks of the scalar/hash building blocks
 * against Python (tests/test_crypto.py) */
void tm_sc_mod_l_test(const uint8_t *x64, uint8_t *out32) {
    uint64_t xl[8], r[4];
    for (int w = 0; w < 8; w++) xl[w] = load64_le(x64 + 8 * w);
    sc_mod_l(r, xl, 8);
    sc4_tobytes(out32, r);
}

void tm_sha512_test(const uint8_t *a, uint64_t na, uint8_t *out64) {
    sha512_3(out64, a, (size_t)na, NULL, 0, NULL, 0);
}

/* sr25519: same batch equation over ristretto255 representatives
 * (schnorrkel verify is s*B - k*A == R as ristretto POINTS, i.e. equal
 * cosets mod the 4-torsion). Soundness of the cofactored check: all
 * decoded representatives lie in 2E, and 2E ∩ E[8] is exactly the
 * 4-torsion set ristretto quotients by — so for decoded inputs,
 * [8]*(sum) == identity  <=>  every per-signature coset equation
 * holds (w.h.p. over the random z_i), the same argument schnorrkel's
 * own batch verification uses. Challenges k_i (merlin transcripts)
 * and all scalar products arrive precomputed, like the ed25519 entry. */
int tm_sr25519_batch_verify(const uint8_t *pk_bytes, const uint8_t *r_bytes,
                            const uint8_t *zb, const uint8_t *a_scalars,
                            const uint8_t *z_scalars, uint64_t n) {
    return batch_verify_common(pk_bytes, r_bytes, zb, a_scalars, z_scalars,
                               n, 2, rist_pre2, rist_fin2);
}

/* ---- Keccak-f[1600] + STROBE-128 + merlin (sr25519 challenges) -----
 *
 * The full-native sr25519 entry needs the schnorrkel Fiat-Shamir
 * challenge k = merlin_transcript(msg, pk, R) mod L computed here, the
 * way tm_ed25519_verify_full owns its SHA-512 challenges — otherwise
 * every batch pays ~3 us/sig of Python transcript work
 * (crypto/merlin.py is the differential oracle; merlin spec
 * merlin.cool, STROBE spec strobe.sourceforge.io; reference consumer:
 * crypto/sr25519/batch.go via curve25519-voi's schnorrkel). Keccak
 * round constants / rotation schedule are the published FIPS-202
 * values (keccakf_core.h, the ONE permutation shared with keccakf.c).
 * Lanes go through the endian-neutral byte helpers like the rest of
 * the file. */

#include "keccakf_core.h"

static inline void store64_le(uint8_t *b, uint64_t v) {
    for (int i = 0; i < 8; i++) b[i] = (uint8_t)(v >> (8 * i));
}

/* STROBE-128: rate 166, the merlin subset (meta-AD, AD, PRF).
 * Mirrors crypto/merlin.py _Strobe128 exactly — that implementation
 * reproduces merlin's published test vector and is the differential
 * oracle for this one (tests/test_sr25519.py). */
#define STROBE_R 166u
#define SF_I 0x01u
#define SF_A 0x02u
#define SF_C 0x04u
#define SF_M 0x10u
#define SF_K 0x20u

/* No cur_flags field: the Python oracle keeps it only to validate
 * 'more'-continuations, and every STROBE call here is internal with a
 * fixed operation pattern — there is no continuation to validate. */
typedef struct {
    uint8_t st[200];
    unsigned pos, pos_begin;
} strobe_t;

static void strobe_runf(strobe_t *s) {
    uint64_t lanes[25];
    s->st[s->pos] ^= (uint8_t)s->pos_begin;
    s->st[s->pos + 1] ^= 0x04;
    s->st[STROBE_R + 1] ^= 0x80;
    for (int i = 0; i < 25; i++) lanes[i] = load64_le(s->st + 8 * i);
    tm_keccakf_core(lanes);
    for (int i = 0; i < 25; i++) store64_le(s->st + 8 * i, lanes[i]);
    s->pos = 0;
    s->pos_begin = 0;
}

static void strobe_absorb(strobe_t *s, const uint8_t *d, size_t n) {
    for (size_t i = 0; i < n; i++) {
        s->st[s->pos++] ^= d[i];
        if (s->pos == STROBE_R) strobe_runf(s);
    }
}

static void strobe_begin(strobe_t *s, uint8_t flags) {
    uint8_t hdr[2];
    hdr[0] = (uint8_t)s->pos_begin;
    hdr[1] = flags;
    s->pos_begin = s->pos + 1;
    strobe_absorb(s, hdr, 2);
    if ((flags & (SF_C | SF_K)) && s->pos != 0) strobe_runf(s);
}

static void strobe_meta_ad(strobe_t *s, const uint8_t *d, size_t n,
                           int more) {
    if (!more) strobe_begin(s, SF_M | SF_A);
    strobe_absorb(s, d, n);
}

static void strobe_ad(strobe_t *s, const uint8_t *d, size_t n) {
    strobe_begin(s, SF_A);
    strobe_absorb(s, d, n);
}

static void strobe_prf(strobe_t *s, uint8_t *out, size_t n) {
    strobe_begin(s, SF_I | SF_A | SF_C);
    size_t got = 0;
    while (got < n) {
        size_t take = n - got;
        if (take > STROBE_R - s->pos) take = STROBE_R - s->pos;
        memcpy(out + got, s->st + s->pos, take);
        memset(s->st + s->pos, 0, take);
        s->pos += take;
        got += take;
        if (s->pos == STROBE_R) strobe_runf(s);
    }
}

static void merlin_append(strobe_t *s, const char *label, size_t llen,
                          const uint8_t *msg, size_t mlen) {
    uint8_t le[4];
    le[0] = (uint8_t)mlen;
    le[1] = (uint8_t)(mlen >> 8);
    le[2] = (uint8_t)(mlen >> 16);
    le[3] = (uint8_t)(mlen >> 24);
    strobe_meta_ad(s, (const uint8_t *)label, llen, 0);
    strobe_meta_ad(s, le, 4, 1);
    strobe_ad(s, msg, mlen);
}

/* The constant schnorrkel signing-context prefix:
 * merlin Transcript("SigningContext") + append_message("", "")
 * (crypto/sr25519.py _signing_transcript; reference privkey.go:16).
 * Rebuilt per batch call — 3 permutations, negligible — so there is
 * no shared mutable state to lock. */
static void merlin_signing_prefix(strobe_t *s) {
    memset(s, 0, sizeof(*s));
    s->st[0] = 1;
    s->st[1] = STROBE_R + 2;
    s->st[2] = 1;
    s->st[3] = 0;
    s->st[4] = 1;
    s->st[5] = 96;
    memcpy(s->st + 6, "STROBEv1.0.2", 12);
    {
        uint64_t lanes[25];
        for (int i = 0; i < 25; i++) lanes[i] = load64_le(s->st + 8 * i);
        tm_keccakf_core(lanes);
        for (int i = 0; i < 25; i++) store64_le(s->st + 8 * i, lanes[i]);
    }
    strobe_meta_ad(s, (const uint8_t *)"Merlin v1.0", 11, 0);
    merlin_append(s, "dom-sep", 7, (const uint8_t *)"SigningContext", 14);
    merlin_append(s, "", 0, (const uint8_t *)"", 0);
}

/* k = merlin challenge mod L for one (pk, R, msg) triple, from a
 * caller-provided copy of the signing prefix. */
static void sr_challenge(const strobe_t *prefix, const uint8_t *pk,
                         const uint8_t *r, const uint8_t *msg, size_t mlen,
                         uint64_t k[4]) {
    strobe_t t = *prefix;
    uint8_t wide[64], le[4] = {64, 0, 0, 0};
    uint64_t d8[8];
    merlin_append(&t, "sign-bytes", 10, msg, mlen);
    merlin_append(&t, "proto-name", 10, (const uint8_t *)"Schnorr-sig", 11);
    merlin_append(&t, "sign:pk", 7, pk, 32);
    merlin_append(&t, "sign:R", 6, r, 32);
    strobe_meta_ad(&t, (const uint8_t *)"sign:c", 6, 0);
    strobe_meta_ad(&t, le, 4, 1);
    strobe_prf(&t, wide, 64);
    for (int w = 0; w < 8; w++) d8[w] = load64_le(wide + 8 * w);
    sc_mod_l(k, d8, 8);
}

/* differential test hook: the C challenge vs crypto/sr25519._challenge */
/* k = merlin challenge for (pk, R, msg) under the signing context —
 * the production sign-path entry (crypto/sr25519.py sign()). The
 * fixed prefix is rebuilt per call: one STROBE init + Keccak-f
 * permutation (~1 us), not worth a locked static cache. */
void tm_sr25519_challenge(const uint8_t *pk, const uint8_t *r,
                          const uint8_t *msg, uint64_t mlen,
                          uint8_t *out32) {
    strobe_t prefix;
    uint64_t k[4];
    merlin_signing_prefix(&prefix);
    sr_challenge(&prefix, pk, r, msg, (size_t)mlen, k);
    sc4_tobytes(out32, k);
}

/* differential test hook (tests/test_sr25519.py): same computation,
 * kept under the historical name */
void tm_sr25519_challenge_test(const uint8_t *pk, const uint8_t *r,
                               const uint8_t *msg, uint64_t mlen,
                               uint8_t *out32) {
    tm_sr25519_challenge(pk, r, msg, mlen, out32);
}

/* Whole-batch sr25519 verify with the host prep done natively — the
 * sr25519 analog of tm_ed25519_verify_full: schnorrkel signature
 * parsing (v1 marker bit, s < L), merlin challenges, RLC products,
 * and the cofactored equation over ristretto decoding, in one call.
 * sigs = n*64 (R||s with the marker bit in s[31]); msgs/moffs/rand16
 * as in the ed25519 entry. Returns 1 all-valid / 0 invalid-somewhere
 * (incl. malformed signatures — caller falls back per-signature for
 * the bitmap) / -1 alloc failure. */
int tm_sr25519_verify_full(const uint8_t *pks, const uint8_t *sigs,
                           const uint8_t *msgs, const uint64_t *moffs,
                           const uint8_t *rand16, uint64_t n) {
    uint8_t *a_sc = malloc(n * 32);
    uint8_t *z_sc = malloc(n * 32);
    uint8_t *r_b = malloc(n * 32);
    if (!a_sc || !z_sc || !r_b) {
        free(a_sc);
        free(z_sc);
        free(r_b);
        return -1;
    }
    int rc;
    uint64_t zb[4] = {0, 0, 0, 0};
    strobe_t prefix;
    merlin_signing_prefix(&prefix);
    for (uint64_t i = 0; i < n; i++) {
        const uint8_t *sig = sigs + 64 * i;
        uint8_t sb[32];
        uint64_t s[4], k[4], z[2], a[4], zs[4];
        if (!(sig[63] & 0x80)) {
            rc = 0; /* pre-v0.1.1 signature without the marker */
            goto done;
        }
        memcpy(sb, sig + 32, 32);
        sb[31] &= 0x7f;
        sc4_frombytes(s, sb);
        if (sc4_gte(s, SC_L)) {
            rc = 0; /* non-canonical s */
            goto done;
        }
        sr_challenge(&prefix, pks + 32 * i, sig, msgs + moffs[i],
                     (size_t)(moffs[i + 1] - moffs[i]), k);
        z[0] = load64_le(rand16 + 16 * i);
        z[1] = load64_le(rand16 + 16 * i + 8);
        sc_mulmod(a, k, z, 2);
        sc4_tobytes(a_sc + 32 * i, a);
        sc_mulmod(zs, s, z, 2);
        sc_addmod(zb, zb, zs);
        memset(z_sc + 32 * i, 0, 32);
        memcpy(z_sc + 32 * i, rand16 + 16 * i, 16);
        memcpy(r_b + 32 * i, sig, 32);
    }
    {
        uint8_t zb_bytes[32];
        sc4_tobytes(zb_bytes, zb);
        rc = batch_verify_common(pks, r_b, zb_bytes, a_sc, z_sc, n, 2,
                                 rist_pre2, rist_fin2);
    }
done:
    free(a_sc);
    free(z_sc);
    free(r_b);
    return rc;
}

/* ---- constant-time fixed-base multiply (secret-scalar path) --------
 *
 * The verify-side MSMs (Straus/Pippenger above) branch and index
 * tables by scalar digits — fine there, those scalars are public
 * (signatures, RLC weights). Sign/keygen scalars are the Schnorr
 * witness and the private key: partial nonce leakage across many
 * signatures is lattice-recoverable, so this path uses a branchless
 * 16-way select and an unconditional complete addition per window —
 * digit-independent control flow and memory access pattern. */

static uint64_t ct_eq_u64(uint64_t a, uint64_t b) {
    uint64_t d = a ^ b;
    return 1 & ((d - 1) >> 63); /* 1 iff d == 0 */
}

static void fe_cmov(fe r, const fe a, uint64_t cond) {
    uint64_t mask = (uint64_t)0 - cond;
    for (int i = 0; i < 5; i++) r[i] ^= mask & (r[i] ^ a[i]);
}

static void ge_cmov(ge *r, const ge *a, uint64_t cond) {
    fe_cmov(r->X, a->X, cond);
    fe_cmov(r->Y, a->Y, cond);
    fe_cmov(r->Z, a->Z, cond);
    fe_cmov(r->T, a->T, cond);
}

/* d*B for d = 0..15 — basepoint multiples are compile-time-constant
 * values, built once on first use (building them per sign call cost
 * ~14 redundant point adds). 0=empty, 1=building, 2=ready; the table
 * contents are public, only the SELECTION below is secret. */
static ge BASE_TABLE16[16];
static atomic_int base_table_state;

static void base_table_init(void) {
    if (atomic_load_explicit(&base_table_state, memory_order_acquire) == 2)
        return;
    int expected = 0;
    if (atomic_compare_exchange_strong(&base_table_state, &expected, 1)) {
        ge_identity(&BASE_TABLE16[0]);
        fe_copy(BASE_TABLE16[1].X, FE_BX);
        fe_copy(BASE_TABLE16[1].Y, FE_BY);
        fe_one(BASE_TABLE16[1].Z);
        fe_copy(BASE_TABLE16[1].T, FE_BT);
        for (int d = 2; d < 16; d++)
            ge_add(&BASE_TABLE16[d], &BASE_TABLE16[d - 1], &BASE_TABLE16[1]);
        atomic_store_explicit(&base_table_state, 2, memory_order_release);
    } else {
        while (atomic_load_explicit(&base_table_state, memory_order_acquire)
               != 2) {
        }
    }
}

/* R = k*B, 4-bit windows MSB-first; the unified ge_add is complete
 * (a = -1 HWCD), so adding the selected entry — identity included —
 * needs no digit-dependent branch. */
static void ge_basemul_ct(ge *r, const uint8_t *scalar) {
    base_table_init();
    ge_identity(r);
    for (int w = 63; w >= 0; w--) {
        if (w != 63)
            for (int k = 0; k < 4; k++) ge_dbl(r, r);
        int byte = w >> 1;
        uint64_t d = (w & 1) ? (uint64_t)(scalar[byte] >> 4)
                             : (uint64_t)(scalar[byte] & 0x0f);
        ge sel = BASE_TABLE16[0];
        for (uint64_t j = 1; j < 16; j++)
            ge_cmov(&sel, &BASE_TABLE16[j], ct_eq_u64(d, j));
        ge_add(r, r, &sel);
    }
}

/* Fixed-base scalar multiply + ristretto encode in one call:
 * out = encode(scalar * B). Serves the sr25519 sign/keygen hot spots
 * (R = r*B, A = a*B — schnorrkel's sign path does exactly these two
 * basepoint multiplies; reference surface: crypto/sr25519/privkey.go).
 * scalar: 32-byte little-endian, already reduced mod L. Returns 0
 * (kept int-returning for ABI stability with earlier revisions). */
int tm_ristretto_basemul(const uint8_t *scalar, uint8_t *out) {
    ge R;
    ge_basemul_ct(&R, scalar);
    rist_encode(out, &R);
    return 0;
}
