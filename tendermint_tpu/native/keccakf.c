/* Keccak-f[1600] permutation — the compute core of merlin/STROBE
 * transcripts (sr25519 Fiat-Shamir, crypto/merlin.py).
 *
 * The reference gets this for free from curve25519-voi's native Go+asm
 * (go.mod:23); a pure-Python permutation costs ~1 ms per call, which
 * would cap the sr25519 device verify path at ~1k sigs/s of host prep.
 * This file is compiled on demand by tendermint_tpu.native (cc -O2
 * -shared) and called through ctypes; Python remains the fallback.
 *
 * The permutation itself lives in keccakf_core.h, shared with
 * ed25519_batch.c's in-kernel STROBE so the two compilation units can
 * never diverge.
 */
#include "keccakf_core.h"

void tm_keccakf(uint64_t st[25]) { tm_keccakf_core(st); }

/* batch variant: n contiguous 25-lane states, one call's ctypes
 * overhead amortized across a whole signature batch. */
void tm_keccakf_n(uint64_t *st, long n) {
    for (long i = 0; i < n; i++)
        tm_keccakf_core(st + 25 * i);
}
