/* Keccak-f[1600] permutation — the compute core of merlin/STROBE
 * transcripts (sr25519 Fiat-Shamir, crypto/merlin.py).
 *
 * The reference gets this for free from curve25519-voi's native Go+asm
 * (go.mod:23); a pure-Python permutation costs ~1 ms per call, which
 * would cap the sr25519 device verify path at ~1k sigs/s of host prep.
 * This file is compiled on demand by tendermint_tpu.native (cc -O2
 * -shared) and called through ctypes; Python remains the fallback.
 *
 * Unrolled x5 in the round body; no dependencies beyond stdint.
 */
#include <stdint.h>

#define ROTL64(v, n) (((v) << (n)) | ((v) >> (64 - (n))))

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

/* lane order: st[x + 5*y] (row-major y), little-endian u64 — matches the
 * 200-byte STROBE state viewed as <25Q. */
void tm_keccakf(uint64_t st[25]) {
    uint64_t bc[5], t;
    for (int round = 0; round < 24; round++) {
        /* theta */
        for (int i = 0; i < 5; i++)
            bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
        for (int i = 0; i < 5; i++) {
            t = bc[(i + 4) % 5] ^ ROTL64(bc[(i + 1) % 5], 1);
            for (int j = 0; j < 25; j += 5)
                st[j + i] ^= t;
        }
        /* rho + pi */
        {
            static const int piln[24] = {10, 7,  11, 17, 18, 3,  5,  16,
                                         8,  21, 24, 4,  15, 23, 19, 13,
                                         12, 2,  20, 14, 22, 9,  6,  1};
            static const int rotc[24] = {1,  3,  6,  10, 15, 21, 28, 36,
                                         45, 55, 2,  14, 27, 41, 56, 8,
                                         25, 43, 62, 18, 39, 61, 20, 44};
            t = st[1];
            for (int i = 0; i < 24; i++) {
                int j = piln[i];
                bc[0] = st[j];
                st[j] = ROTL64(t, rotc[i]);
                t = bc[0];
            }
        }
        /* chi */
        for (int j = 0; j < 25; j += 5) {
            for (int i = 0; i < 5; i++)
                bc[i] = st[j + i];
            for (int i = 0; i < 5; i++)
                st[j + i] = bc[i] ^ ((~bc[(i + 1) % 5]) & bc[(i + 2) % 5]);
        }
        /* iota */
        st[0] ^= RC[round];
    }
}

/* batch variant: n contiguous 25-lane states, one call's ctypes
 * overhead amortized across a whole signature batch. */
void tm_keccakf_n(uint64_t *st, long n) {
    for (long i = 0; i < n; i++)
        tm_keccakf(st + 25 * i);
}
