"""Native (C) components, compiled on demand with the system compiler.

The reference's runtime leans on native code through curve25519-voi's
Go+assembly crypto (go.mod:23) and optional cgo DB backends
(config/config.go:182-194). Here the TPU handles the curve math, but a
few host-side primitives still need native speed — first among them
Keccak-f[1600] for merlin/STROBE transcripts (crypto/merlin.py), where
pure Python costs ~1 ms per permutation.

Design: tiny dependency-free C files next to this module, compiled
lazily to ``~/.cache/tendermint_tpu/`` (keyed by source hash, so edits
recompile and concurrent processes converge on the same artifact) and
loaded with ctypes. Every consumer keeps a pure-Python fallback; a
missing or broken toolchain degrades performance, never correctness.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

__all__ = ["load", "keccakf_lib", "signbytes_lib", "ed25519_batch_lib"]

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_LIBS: dict = {}


def _cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    d = os.path.join(base, "tendermint_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str) -> Optional[ctypes.CDLL]:
    """Compile-and-load ``<name>.c`` from this directory; returns the
    CDLL, or None when disabled (TM_TPU_NO_NATIVE=1), the compiler is
    missing, or compilation fails. Results (including failure) are
    cached per process."""
    if name in _LIBS:
        return _LIBS[name]
    lib = None
    if not os.environ.get("TM_TPU_NO_NATIVE"):
        try:
            lib = _build(name)
        except Exception:
            lib = None
    # tmlive: bounded=keyed by native library name — a fixed in-tree
    # set (one .c source per kernel); one CDLL handle per name
    _LIBS[name] = lib
    return lib


def _build(name: str) -> Optional[ctypes.CDLL]:
    src = os.path.join(_SRC_DIR, f"{name}.c")
    with open(src, "rb") as f:
        code = f.read()
    cc = os.environ.get("CC", "cc")
    flags = [cc, "-O3", "-funroll-loops", "-shared", "-fPIC"]
    # the cache key covers compiler, flags, AND every local header
    # (keccakf_core.h is #included by two units), so neither a flag
    # nor a header change can silently reuse a stale artifact
    hdr = b""
    for h in sorted(os.listdir(_SRC_DIR)):
        if h.endswith(".h"):
            with open(os.path.join(_SRC_DIR, h), "rb") as f:
                hdr += f.read()
    tag = hashlib.sha256(
        code + b"|" + hdr + b"|" + " ".join(flags).encode()
    ).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"{name}-{tag}.so")
    if not os.path.exists(out):
        # compile to a temp name then atomically rename, so concurrent
        # processes never load a half-written .so
        fd, tmp = tempfile.mkstemp(
            suffix=".so", dir=os.path.dirname(out)
        )
        os.close(fd)
        try:
            subprocess.run(
                flags + ["-o", tmp, src],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return ctypes.CDLL(out)


def signbytes_lib():
    """The sign-bytes assembler with argtypes set, or None. Exposes
    ``tm_vote_sign_bytes_batch`` (see signbytes.c for the contract)."""
    lib = load("signbytes")
    if lib is None:
        return None
    if not getattr(lib, "_tm_configured", False):
        lib.tm_vote_sign_bytes_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.c_uint8,
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.c_void_p,
        ]
        lib.tm_vote_sign_bytes_batch.restype = ctypes.c_long
        lib._tm_configured = True
    return lib


def keccakf_lib():
    """The keccakf library with argtypes set, or None. Exposes
    ``tm_keccakf(uint64_t st[25])`` and ``tm_keccakf_n(uint64_t*, long)``
    over the 200-byte STROBE state (little-endian u64 lanes)."""
    lib = load("keccakf")
    if lib is None:
        return None
    if not getattr(lib, "_tm_configured", False):
        lib.tm_keccakf.argtypes = [ctypes.c_void_p]
        lib.tm_keccakf.restype = None
        lib.tm_keccakf_n.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.tm_keccakf_n.restype = None
        lib._tm_configured = True
    return lib


def ed25519_batch_lib():
    """The ed25519 batch-equation library with argtypes set, or None.
    Exposes ``tm_ed25519_batch_verify(pk_bytes, r_bytes, zb, a_scalars,
    z_scalars, n) -> int`` (1 accept / 0 equation-reject / -1 decode
    failure) — see native/ed25519_batch.c for the contract."""
    lib = load("ed25519_batch")
    if lib is None:
        return None
    if not getattr(lib, "_tm_configured", False):
        argtypes = [ctypes.c_char_p] * 5 + [ctypes.c_uint64]
        lib.tm_ed25519_batch_verify.argtypes = argtypes
        lib.tm_ed25519_batch_verify.restype = ctypes.c_int
        # same equation over ristretto255 decoding (sr25519/schnorrkel)
        lib.tm_sr25519_batch_verify.argtypes = argtypes
        lib.tm_sr25519_batch_verify.restype = ctypes.c_int
        # whole-batch entry: SHA-512 challenges + mod-L scalar products
        # + the equation in one native call (no per-signature Python)
        lib.tm_ed25519_verify_full.argtypes = [
            ctypes.c_char_p,                  # pks n*32
            ctypes.c_char_p,                  # sigs n*64
            ctypes.c_char_p,                  # msgs blob
            ctypes.POINTER(ctypes.c_uint64),  # n+1 offsets
            ctypes.c_char_p,                  # rand n*16
            ctypes.c_uint64,
        ]
        lib.tm_ed25519_verify_full.restype = ctypes.c_int
        # the sr25519 analog: schnorrkel parsing + merlin challenges
        # (STROBE-128 in C) + RLC products + the ristretto equation
        lib.tm_sr25519_verify_full.argtypes = (
            lib.tm_ed25519_verify_full.argtypes
        )
        lib.tm_sr25519_verify_full.restype = ctypes.c_int
        # differential hook: C merlin challenge vs crypto/sr25519.py
        lib.tm_sr25519_challenge_test.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_char_p,
        ]
        lib.tm_sr25519_challenge_test.restype = None
        # production sign-path challenge (same computation; the _test
        # name is the historical differential hook)
        lib.tm_sr25519_challenge.argtypes = (
            lib.tm_sr25519_challenge_test.argtypes
        )
        lib.tm_sr25519_challenge.restype = None
        # decoded-point cache observability (hits/misses/inserts/
        # evictions) + reset — the repeated-validator-set optimization
        # (reference: crypto/ed25519/ed25519.go:50-56 cacheSize 4096)
        lib.tm_pk_cache_stats.argtypes = [
            ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.tm_pk_cache_stats.restype = None
        lib.tm_pk_cache_clear.argtypes = []
        lib.tm_pk_cache_clear.restype = None
        # fixed-base multiply + ristretto encode (sr25519 sign/keygen)
        lib.tm_ristretto_basemul.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.tm_ristretto_basemul.restype = ctypes.c_int
        lib._tm_configured = True
    return lib


def ristretto_basemul(scalar_le32: bytes) -> Optional[bytes]:
    """encode(scalar*B) through the native library, or None when
    native is unavailable. scalar: 32-byte little-endian, < L."""
    # the C side unconditionally reads 32 bytes — a shorter buffer
    # from a future caller would be an out-of-bounds read (ADVICE r5)
    if len(scalar_le32) != 32:
        raise ValueError(
            f"scalar must be exactly 32 bytes, got {len(scalar_le32)}"
        )
    lib = ed25519_batch_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(32)
    # tmct: ct-ok — FFI status code only: the native basemul is a
    # fixed-window constant-structure ladder, and rc reflects library
    # availability/buffer validity, never scalar bits
    if lib.tm_ristretto_basemul(scalar_le32, out) != 0:
        return None
    return out.raw


def sr25519_challenge(pub: bytes, r: bytes, msg: bytes) -> Optional[bytes]:
    """The merlin signing-context challenge k for (pub, R, msg) as 32
    little-endian bytes (reduced mod L), or None when native is
    unavailable — the sign-path twin of ristretto_basemul."""
    # C reads exactly 32 bytes of pub and R (msg carries its length)
    if len(pub) != 32:
        raise ValueError(f"pub must be exactly 32 bytes, got {len(pub)}")
    if len(r) != 32:
        raise ValueError(f"R must be exactly 32 bytes, got {len(r)}")
    lib = ed25519_batch_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(32)
    lib.tm_sr25519_challenge(pub, r, msg, len(msg), out)
    return out.raw


def pk_cache_stats() -> Optional[dict]:
    """Decoded-point cache counters from the native batch library, or
    None when native is unavailable."""
    lib = ed25519_batch_lib()
    if lib is None:
        return None
    out = (ctypes.c_uint64 * 4)()
    lib.tm_pk_cache_stats(out)
    return {
        "hits": out[0],
        "misses": out[1],
        "inserts": out[2],
        "evictions": out[3],
    }
