"""Database key migration — legacy string-prefixed keys to the
binary-prefix layout.

reference: scripts/keymigrate/migrate.go + the `tendermint key-migrate`
command (cmd/tendermint/commands/key_migrate.go). The reference
translates its v0.34 ASCII key formats (``H:1``, ``P:1:0``, ``C:0``,
``SC:1``, ``BH:<hex>``, ``validatorsKey:…``, ``stateKey``) into the
v0.35 orderedcode layout; this framework's current layout is the
analogous binary one (prefix byte + big-endian height —
store/block_store.py, state/store.py), so the same legacy formats
migrate into it. Values are carried over unchanged — the wire
encodings already match the reference's protos — except where the
legacy VALUE format differed (``BH:`` stored the height as ASCII
decimal; it becomes the 8-byte big-endian the hash index reads).

Migration is resumable and idempotent: legacy keys are detected by
prefix, so a re-run (or a crash partway) skips everything already
translated, matching the reference's "safe to resume" contract
(migrate.go:40-44).
"""

from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional, Tuple

from ..state import store as state_store
from ..store import block_store
from .kv import KVStore

__all__ = ["migrate_db", "CONTEXTS"]


def _int(b: bytes) -> int:
    return int(b.decode())


def _h(height: int) -> bytes:
    return struct.pack(">q", height)


def _migrate_blockstore(key: bytes) -> Optional[Tuple[bytes, Callable]]:
    """legacy key -> (new key, value translator) or None if not legacy.
    reference formats: migrate.go:116-160."""
    ident = lambda v: v  # noqa: E731
    if key.startswith(b"H:"):
        return block_store._meta_key(_int(key[2:])), ident
    if key.startswith(b"P:"):
        height, _, part = key[2:].partition(b":")
        return (
            block_store._part_key(_int(height), _int(part)),
            ident,
        )
    if key.startswith(b"C:"):
        return block_store._commit_key(_int(key[2:])), ident
    if key.startswith(b"SC:"):
        # the current layout keeps only the LATEST seen commit under a
        # single key; migrate_db resolves the max-height winner
        return block_store._seen_commit_key(), ident
    if key.startswith(b"BH:"):
        # legacy: hex hash key, ASCII-decimal height value
        return (
            block_store._hash_key(bytes.fromhex(key[3:].decode())),
            lambda v: _h(_int(v)),
        )
    return None


def _migrate_state(key: bytes) -> Optional[Tuple[bytes, Callable]]:
    ident = lambda v: v  # noqa: E731
    if key == b"stateKey":
        return state_store._STATE, ident
    if key.startswith(b"validatorsKey:"):
        return state_store._vals_key(_int(key[14:])), ident
    if key.startswith(b"consensusParamsKey:"):
        return state_store._params_key(_int(key[19:])), ident
    if key.startswith(b"abciResponsesKey:"):
        return state_store._abci_key(_int(key[17:])), ident
    return None


CONTEXTS: Dict[str, Callable] = {
    "blockstore": _migrate_blockstore,
    "state": _migrate_state,
}


def migrate_db(db: KVStore, context: str) -> int:
    """Translate every legacy-format key in `db`; returns the count.
    Unknown contexts (tx_index, evidence, light, peerstore — born in
    the current layout here) are no-ops, mirroring the reference's
    per-context dispatch."""
    fn = CONTEXTS.get(context)
    if fn is None:
        return 0
    moves: List[Tuple[bytes, bytes, bytes]] = []  # old, new, value
    seen_commit_best = None  # (height, old_key, value)
    for key, value in list(db.iterate(None, None)):
        try:
            res = fn(bytes(key))
        except (ValueError, UnicodeDecodeError):
            continue  # not a well-formed legacy key: leave it alone
        if res is None:
            continue
        new_key, xform = res
        if context == "blockstore" and bytes(key).startswith(b"SC:"):
            height = _int(bytes(key)[3:])
            if (
                seen_commit_best is None
                or height > seen_commit_best[0]
            ):
                if seen_commit_best is not None:
                    # the previous best is superseded: delete only
                    moves.append((seen_commit_best[1], b"", b""))
                seen_commit_best = (height, bytes(key), value)
            else:
                moves.append((bytes(key), b"", b""))  # delete only
            continue
        moves.append((bytes(key), new_key, xform(value)))
    if seen_commit_best is not None:
        _, old_key, value = seen_commit_best
        moves.append(
            (old_key, block_store._seen_commit_key(), value)
        )
    migrated = 0
    for old_key, new_key, value in moves:
        if new_key:
            db.set(new_key, value)
            migrated += 1
        db.delete(old_key)  # delete-only: superseded SC: tombstones
    return migrated
