"""Persistence: KV backends and the block store."""

from .block_store import BlockStore  # noqa: F401
from .kv import Batch, KVStore, MemKV, SqliteKV, open_db  # noqa: F401

__all__ = ["BlockStore", "Batch", "KVStore", "MemKV", "SqliteKV", "open_db"]
