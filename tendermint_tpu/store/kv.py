"""Embedded ordered key-value store — the persistence substrate.

The reference uses tm-db (goleveldb default, optional C++ backends via
build tags — config/config.go:179-197). Here the interface is the same
shape (get/set/delete/ordered iteration/atomic batch) with two
backends: in-memory (tests, the reference's memdb) and SQLite (stdlib,
durable, transactional — the embedded default, playing goleveldb's
role).
"""

from __future__ import annotations

import sqlite3
import threading
from abc import ABC, abstractmethod
from typing import Iterator, List, Optional, Tuple

__all__ = [
    "KVStore",
    "MemKV",
    "SqliteKV",
    "Batch",
    "open_db",
    "register_backend",
]


class Batch:
    """Write batch applied atomically via KVStore.write_batch."""

    def __init__(self) -> None:
        self.ops: List[Tuple[str, bytes, Optional[bytes]]] = []

    def set(self, key: bytes, value: bytes) -> None:
        self.ops.append(("set", bytes(key), bytes(value)))

    def delete(self, key: bytes) -> None:
        self.ops.append(("del", bytes(key), None))

    def __len__(self) -> int:
        return len(self.ops)


class KVStore(ABC):
    @abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    @abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abstractmethod
    def iterate(
        self,
        start: Optional[bytes] = None,
        end: Optional[bytes] = None,
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        """Ordered [start, end) iteration, like tm-db's Iterator."""
        ...

    @abstractmethod
    def write_batch(self, batch: Batch) -> None: ...

    @abstractmethod
    def close(self) -> None: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def first_key(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Optional[bytes]:
        for k, _v in self.iterate(start, end):
            return k
        return None

    def last_key(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Optional[bytes]:
        for k, _v in self.iterate(start, end, reverse=True):
            return k
        return None


class MemKV(KVStore):
    """Sorted in-memory store (reference analog: tm-db memdb)."""

    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(key, None)

    def iterate(self, start=None, end=None, reverse=False):
        with self._lock:
            keys = sorted(self._data.keys())
        if start is not None:
            keys = [k for k in keys if k >= start]
        if end is not None:
            keys = [k for k in keys if k < end]
        if reverse:
            keys = list(reversed(keys))
        for k in keys:
            v = self._data.get(k)
            if v is not None:
                yield k, v

    def write_batch(self, batch: Batch) -> None:
        with self._lock:
            for op, k, v in batch.ops:
                if op == "set":
                    self._data[k] = v  # type: ignore[assignment]
                else:
                    self._data.pop(k, None)

    def close(self) -> None:
        pass


class SqliteKV(KVStore):
    """SQLite-backed ordered KV (durable default backend).

    WAL mode for concurrent readers; BLOB keys preserve bytewise order
    so iteration semantics match the in-memory backend.
    """

    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv "
                "(k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID"
            )
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def _range_query(self, select, start, end, reverse, limit=None):
        q = select
        cond, args = [], []
        if start is not None:
            cond.append("k >= ?")
            args.append(bytes(start))
        if end is not None:
            cond.append("k < ?")
            args.append(bytes(end))
        if cond:
            q += " WHERE " + " AND ".join(cond)
        q += " ORDER BY k" + (" DESC" if reverse else "")
        if limit is not None:
            q += f" LIMIT {int(limit)}"
        return q, args

    def iterate(self, start=None, end=None, reverse=False):
        q, args = self._range_query("SELECT k, v FROM kv", start, end, reverse)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        for k, v in rows:
            yield bytes(k), bytes(v)

    def first_key(self, start=None, end=None):
        q, args = self._range_query("SELECT k FROM kv", start, end, False, 1)
        with self._lock:
            row = self._conn.execute(q, args).fetchone()
        return bytes(row[0]) if row else None

    def last_key(self, start=None, end=None):
        q, args = self._range_query("SELECT k FROM kv", start, end, True, 1)
        with self._lock:
            row = self._conn.execute(q, args).fetchone()
        return bytes(row[0]) if row else None

    def write_batch(self, batch: Batch) -> None:
        with self._lock:
            for op, k, v in batch.ops:
                if op == "set":
                    self._conn.execute(
                        "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                        (k, v),
                    )
                else:
                    self._conn.execute("DELETE FROM kv WHERE k = ?", (k,))
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


# Pluggable engine registry. The reference exposes five engines
# selected by `db-backend` (config/config.go:179-197, goleveldb /
# cleveldb / boltdb / rocksdb / badgerdb via build tags); here the
# same config knob resolves through this registry. Built-ins are
# memdb + sqlite — a DELIBERATE cut: sqlite (stdlib, transactional,
# ordered) covers the embedded-durable role of all five Go engines on
# one box, and nothing else ships in this image. Deployments wanting
# a different engine register a factory before node start:
#
#     from tendermint_tpu.store.kv import register_backend
#     register_backend("rocksdb", lambda name, db_dir: MyRocksKV(...))
#
# and set `db-backend = "rocksdb"` in config.toml.
_BACKENDS: dict = {}


def register_backend(name: str, factory) -> None:
    """Register `factory(name, db_dir) -> KVStore` under a config
    `db-backend` value. Re-registering a name replaces it (tests)."""
    # tmlint: disable=lock-global-mutation — registration happens at
    # import / before node start, single-threaded by contract
    _BACKENDS[name] = factory


register_backend("memdb", lambda _name, _db_dir: MemKV())
register_backend("mem", _BACKENDS["memdb"])


def _sqlite_factory(name: str, db_dir: str) -> KVStore:
    import os

    os.makedirs(db_dir, exist_ok=True)
    return SqliteKV(os.path.join(db_dir, f"{name}.sqlite"))


register_backend("sqlite", _sqlite_factory)
# the reference's default engine name maps to our durable default, so
# a config.toml written for the reference works unchanged
register_backend("goleveldb", _sqlite_factory)
register_backend("default", _sqlite_factory)


def open_db(name: str, backend: str, db_dir: str) -> KVStore:
    """Backend selection (reference analog: config/config.go:179-197)."""
    factory = _BACKENDS.get(backend)
    if factory is None:
        raise ValueError(
            f"unknown db backend {backend!r}; registered: "
            f"{sorted(_BACKENDS)}"
        )
    return factory(name, db_dir)
