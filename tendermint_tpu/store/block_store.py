"""BlockStore — blocks persisted as meta + parts + commits.

Reference: internal/store/store.go (LoadBlock :131, PruneBlocks :307,
SaveBlock :449, SaveSignedHeader :533; key scheme :584-640). Keys here
are prefix byte + big-endian height so KV iteration orders by height,
the same property the reference gets from orderedcode.
"""

from __future__ import annotations

import struct
import threading
from typing import List, Optional, Tuple

from ..types.block import Block
from ..types.block_id import BlockID
from ..types.block_meta import BlockMeta
from ..types.commit import Commit
from ..types.light import SignedHeader
from ..types.part_set import Part, PartSet
from .kv import Batch, KVStore

__all__ = ["BlockStore"]

_META = b"\x00"
_PART = b"\x01"
_COMMIT = b"\x02"
_SEEN_COMMIT = b"\x03"
_HASH = b"\x04"


def _meta_key(height: int) -> bytes:
    return _META + struct.pack(">q", height)


def _part_key(height: int, index: int) -> bytes:
    return _PART + struct.pack(">qi", height, index)


def _commit_key(height: int) -> bytes:
    return _COMMIT + struct.pack(">q", height)


def _seen_commit_key() -> bytes:
    return _SEEN_COMMIT


def _hash_key(h: bytes) -> bytes:
    return _HASH + h


class BlockStore:
    def __init__(self, db: KVStore) -> None:
        self._db = db
        self._lock = threading.Lock()

    # -- range info --

    def base(self) -> int:
        """Lowest stored height, 0 if empty
        (reference: internal/store/store.go:44)."""
        k = self._db.first_key(_meta_key(1), _meta_key((1 << 62)))
        if k is None:
            return 0
        return struct.unpack(">q", k[1:9])[0]

    def height(self) -> int:
        """Highest stored height, 0 if empty."""
        k = self._db.last_key(_meta_key(1), _meta_key((1 << 62)))
        if k is None:
            return 0
        return struct.unpack(">q", k[1:9])[0]

    def size(self) -> int:
        h = self.height()
        return 0 if h == 0 else h - self.base() + 1

    # -- loads --

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        data = self._db.get(_meta_key(height))
        return BlockMeta.from_proto(data) if data is not None else None

    def load_block_meta_by_hash(self, h: bytes) -> Optional[BlockMeta]:
        height_bytes = self._db.get(_hash_key(h))
        if height_bytes is None:
            return None
        return self.load_block_meta(struct.unpack(">q", height_bytes)[0])

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        buf = b""
        for i in range(meta.block_id.part_set_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            buf += part.bytes
        return Block.from_proto(buf)

    def load_block_by_hash(self, h: bytes) -> Optional[Block]:
        meta = self.load_block_meta_by_hash(h)
        if meta is None:
            return None
        return self.load_block(meta.header.height)

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        data = self._db.get(_part_key(height, index))
        return Part.from_proto(data) if data is not None else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The commit for `height` as included in block height+1."""
        data = self._db.get(_commit_key(height))
        return Commit.from_proto(data) if data is not None else None

    def load_seen_commit(self) -> Optional[Commit]:
        """Locally-seen commit for the latest height (may differ in
        round from the canonical LastCommit)."""
        data = self._db.get(_seen_commit_key())
        return Commit.from_proto(data) if data is not None else None

    # -- saves --

    def save_block(
        self, block: Block, block_parts: PartSet, seen_commit: Commit
    ) -> None:
        """reference: internal/store/store.go:449-530."""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        with self._lock:
            height = block.header.height
            expected = self.height() + 1
            if self.height() > 0 and height != expected:
                raise ValueError(
                    f"cannot save block at height {height}, expected "
                    f"{expected}"
                )
            if not block_parts.is_complete():
                raise ValueError(
                    "cannot save complete block with incomplete parts"
                )
            batch = Batch()
            meta = BlockMeta(
                block_id=BlockID(
                    hash=block.hash(),
                    part_set_header=block_parts.header(),
                ),
                block_size=block.size(),
                header=block.header,
                num_txs=len(block.txs),
            )
            batch.set(_meta_key(height), meta.to_proto())
            batch.set(
                _hash_key(block.hash()), struct.pack(">q", height)
            )
            for i in range(block_parts.total):
                part = block_parts.get_part(i)
                batch.set(_part_key(height, i), part.to_proto())
            if block.last_commit is not None:
                batch.set(
                    _commit_key(height - 1),
                    block.last_commit.to_proto(),
                )
            batch.set(_seen_commit_key(), seen_commit.to_proto())
            self._db.write_batch(batch)

    def save_signed_header(
        self, sh: SignedHeader, block_id: BlockID
    ) -> None:
        """Backfill (reverse-sync) storage of header+commit without the
        full block (reference: internal/store/store.go:533-570)."""
        height = sh.header.height
        with self._lock:
            if self.load_block_meta(height) is not None:
                raise ValueError(
                    f"block meta already exists at height {height}"
                )
            batch = Batch()
            meta = BlockMeta(
                block_id=block_id, block_size=-1, header=sh.header, num_txs=-1
            )
            batch.set(_meta_key(height), meta.to_proto())
            batch.set(_commit_key(height - 1), sh.commit.to_proto())
            batch.set(_hash_key(sh.header.hash()), struct.pack(">q", height))
            self._db.write_batch(batch)

    def save_seen_commit(self, seen_commit: Commit) -> None:
        with self._lock:
            self._db.set(_seen_commit_key(), seen_commit.to_proto())

    # -- pruning --

    def prune_blocks(self, retain_height: int) -> int:
        """Remove all blocks below retain_height; returns count pruned
        (reference: internal/store/store.go:307-380)."""
        if retain_height <= 0:
            raise ValueError("height must be greater than 0")
        if retain_height > self.height():
            raise ValueError(
                f"height must be <= latest height {self.height()}"
            )
        base = self.base()
        if retain_height < base:
            return 0
        pruned = 0
        batch = Batch()
        for h in range(base, retain_height):
            meta = self.load_block_meta(h)
            if meta is None:
                continue
            batch.delete(_meta_key(h))
            batch.delete(_hash_key(meta.block_id.hash))
            batch.delete(_commit_key(h))
            for i in range(meta.block_id.part_set_header.total):
                batch.delete(_part_key(h, i))
            pruned += 1
        self._db.write_batch(batch)
        return pruned
