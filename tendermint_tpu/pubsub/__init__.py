"""Pubsub — query-addressed publish/subscribe.

reference: internal/pubsub/pubsub.go (:105 Server, :188 SubscribeWithArgs,
:292-344 publish fan-out). Subscribers register a client ID + compiled
query; published messages carry event tags and are delivered to every
subscription whose query matches. Each subscription owns a bounded queue;
a slow subscriber overflowing its queue is terminated with an error
(reference: internal/pubsub/subscription.go), keeping one laggard from
stalling consensus event publication.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..libs.service import Service
from .query import Query, compile_query  # noqa: F401

__all__ = [
    "Message",
    "Subscription",
    "Server",
    "SubscriptionError",
    "ERR_TERMINATED",
    "Query",
    "compile_query",
]

ERR_TERMINATED = "subscription terminated: queue overflow"

# Pushed into a subscription's queue on termination so a consumer blocked
# in `await get()` wakes immediately instead of polling. On queue-overflow
# termination the queue is full (consumer not blocked), so the sentinel
# being undeliverable there is fine: the consumer hits the terminated
# check after draining.
_SENTINEL = object()


class SubscriptionError(Exception):
    pass


@dataclass(frozen=True)
class Message:
    """What a subscriber receives: the payload plus the tag map it matched."""

    data: object
    events: Dict[str, List[str]] = field(default_factory=dict)


class Subscription:
    """A single subscriber feed with a bounded buffer."""

    def __init__(self, client_id: str, query: Query, limit: int = 100) -> None:
        self.client_id = client_id
        self.query = query
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max(1, limit))
        self._terminated: Optional[str] = None

    def _deliver(self, msg: Message) -> bool:
        if self._terminated:
            return False
        try:
            self._queue.put_nowait(msg)
            return True
        except asyncio.QueueFull:
            self._terminated = ERR_TERMINATED
            return False

    def _terminate(self, reason: str) -> None:
        if not self._terminated:
            self._terminated = reason
            try:
                self._queue.put_nowait(_SENTINEL)
            except asyncio.QueueFull:
                pass  # consumer isn't blocked; it'll see _terminated

    async def next(self) -> Message:
        """Await the next matching message; raises SubscriptionError once
        terminated and drained. Event-driven — no polling."""
        while True:
            try:
                msg = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                if self._terminated:
                    raise SubscriptionError(self._terminated)
                msg = await self._queue.get()
            if msg is _SENTINEL:
                raise SubscriptionError(self._terminated or "terminated")
            return msg

    def __aiter__(self):
        return self

    async def __anext__(self) -> Message:
        try:
            return await self.next()
        except SubscriptionError:
            raise StopAsyncIteration


class Server(Service):
    """The pubsub hub (reference: internal/pubsub/pubsub.go:105)."""

    def __init__(self, name: str = "pubsub") -> None:
        super().__init__(name=name)
        # (client_id, query string) → Subscription
        self._subs: Dict[Tuple[str, str], Subscription] = {}
        # publish-path index: query source → (compiled query, members).
        # Load subscribers are overwhelmingly N clients × few distinct
        # queries (tm.event='NewBlock' × hundreds), so the fan-out
        # evaluates each DISTINCT query once per publish and batch-
        # delivers one shared Message to the whole group — the
        # PR-16 profiler's top serving-side fix (the per-subscriber
        # Message allocation + per-subscriber query re-evaluation
        # dominated publish at 256 subscribers).
        self._groups: Dict[
            str, Tuple[Query, Dict[Tuple[str, str], Subscription]]
        ] = {}

    def subscribe(
        self, client_id: str, query: "Query | str", limit: int = 100
    ) -> Subscription:
        q = compile_query(query) if isinstance(query, str) else query
        key = (client_id, str(q))
        if key in self._subs:
            raise SubscriptionError(
                f"{client_id} already subscribed to {q}"
            )
        sub = Subscription(client_id, q, limit)
        self._subs[key] = sub
        group = self._groups.get(key[1])
        if group is None:
            # tmlive: bounded=one group per distinct live query source;
            # a group dies with its last member (_drop_key)
            self._groups[key[1]] = (q, {key: sub})
        else:
            group[1][key] = sub
        return sub

    def _drop_key(self, key: Tuple[str, str]) -> Optional[Subscription]:
        """Remove one subscription from both indexes."""
        sub = self._subs.pop(key, None)
        if sub is None:
            return None
        group = self._groups.get(key[1])
        if group is not None:
            group[1].pop(key, None)
            if not group[1]:
                del self._groups[key[1]]
        return sub

    def unsubscribe(self, client_id: str, query: "Query | str") -> None:
        qs = str(compile_query(query) if isinstance(query, str) else query)
        sub = self._drop_key((client_id, qs))
        if sub is None:
            raise SubscriptionError(f"{client_id} not subscribed to {qs}")
        sub._terminate("unsubscribed")

    def unsubscribe_all(self, client_id: str) -> None:
        keys = [k for k in self._subs if k[0] == client_id]
        if not keys:
            raise SubscriptionError(f"{client_id} has no subscriptions")
        for k in keys:
            self._drop_key(k)._terminate("unsubscribed")

    def num_clients(self) -> int:
        return len({cid for cid, _ in self._subs})

    def num_subscriptions(self) -> int:
        return len(self._subs)

    def publish(
        self, data: object, events: Optional[Dict[str, List[str]]] = None
    ) -> Tuple[int, int, int]:
        """Synchronous fan-out: delivery is put_nowait into bounded queues,
        so publishing never blocks the caller (the consensus hot loop).

        Returns `(matched, max_depth, dropped)` — subscriptions the
        message matched, the deepest subscriber queue after delivery
        (the fanout-lag signal: how far the slowest live subscriber is
        behind the publisher), and subscriptions terminated by overflow
        on this publish. Computed inside the fan-out loop the publisher
        already pays for, so the saturation signal costs one qsize()
        per matched subscriber."""
        events = events or {}
        dead: List[Tuple[str, str]] = []
        matched = 0
        max_depth = 0
        msg: Optional[Message] = None
        for source, (q, members) in self._groups.items():
            # one query evaluation per DISTINCT query, not per
            # subscriber — and one shared Message for every recipient
            # (it is frozen, and `events` was always the same dict
            # reference across recipients, so aliasing is unchanged)
            if not q.matches(events):
                continue
            if msg is None:
                msg = Message(data=data, events=events)
            for key, sub in members.items():
                matched += 1
                if not sub._deliver(msg):
                    dead.append(key)
                else:
                    depth = sub._queue.qsize()
                    if depth > max_depth:
                        max_depth = depth
        for key in dead:
            self._drop_key(key)
        return matched, max_depth, len(dead)

    def max_queue_depth(self) -> int:
        """Deepest subscriber queue right now (scrape-time gauge)."""
        depth = 0
        for sub in self._subs.values():
            d = sub._queue.qsize()
            if d > depth:
                depth = d
        return depth

    async def on_stop(self) -> None:
        for sub in self._subs.values():
            sub._terminate("server stopped")
        self._subs.clear()
        self._groups.clear()
