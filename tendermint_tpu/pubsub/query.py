"""Event-query language: `tm.event = 'Tx' AND tx.height = 5`.

reference: internal/pubsub/query/{query.go,syntax/} — a tiny conjunctive
language over event tags. Conditions: `tag = 'string'`, numeric
comparisons (= < <= > >=), `tag CONTAINS 'sub'`, `tag EXISTS`, joined by
AND. Events are flattened into a tag map `{"type.attr_key": [values...]}`;
a condition matches if ANY value for its tag satisfies it
(reference: internal/pubsub/query/query.go:157-191).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

__all__ = ["Query", "QuerySyntaxError", "compile_query", "query_for_event"]


class QuerySyntaxError(ValueError):
    pass


_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<op><=|>=|=|<|>)
      | (?P<and>\bAND\b)
      | (?P<exists>\bEXISTS\b)
      | (?P<contains>\bCONTAINS\b)
      | (?P<string>'[^']*')
      | (?P<number>-?\d+(?:\.\d+)?)
      | (?P<tag>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)

_OP_EQ = "="
_OP_LT = "<"
_OP_LE = "<="
_OP_GT = ">"
_OP_GE = ">="
_OP_CONTAINS = "CONTAINS"
_OP_EXISTS = "EXISTS"


@dataclass(frozen=True)
class _Condition:
    tag: str
    op: str
    arg: Optional[object]  # str for =/CONTAINS on strings, float for numerics

    def matches(self, values: Sequence[str]) -> bool:
        if self.op == _OP_EXISTS:
            return len(values) > 0
        for v in values:
            if self.op == _OP_CONTAINS:
                if str(self.arg) in v:
                    return True
            elif self.op == _OP_EQ and isinstance(self.arg, str):
                if v == self.arg:
                    return True
            else:  # numeric comparison
                try:
                    x = float(v)
                except ValueError:
                    continue
                t = float(self.arg)  # type: ignore[arg-type]
                if (
                    (self.op == _OP_EQ and x == t)
                    or (self.op == _OP_LT and x < t)
                    or (self.op == _OP_LE and x <= t)
                    or (self.op == _OP_GT and x > t)
                    or (self.op == _OP_GE and x >= t)
                ):
                    return True
        return False


class Query:
    """A compiled conjunctive query over event tags."""

    def __init__(self, source: str, conditions: List[_Condition]) -> None:
        self._source = source
        self._conditions = conditions

    def __str__(self) -> str:
        return self._source

    def __repr__(self) -> str:
        return f"Query({self._source!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self._source == other._source

    def __hash__(self) -> int:
        return hash(self._source)

    def matches(self, tags: Dict[str, List[str]]) -> bool:
        return all(c.matches(tags.get(c.tag, ())) for c in self._conditions)


def _tokenize(s: str):
    pos = 0
    out = []
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None or m.end() == pos:
            if s[pos:].strip():
                raise QuerySyntaxError(f"unexpected input at: {s[pos:]!r}")
            break
        pos = m.end()
        kind = m.lastgroup
        out.append((kind, m.group(kind)))
    return out


def compile_query(source: str) -> Query:
    tokens = _tokenize(source)
    if not tokens:
        raise QuerySyntaxError("empty query")
    conditions: List[_Condition] = []
    i = 0
    while i < len(tokens):
        kind, val = tokens[i]
        if kind != "tag":
            raise QuerySyntaxError(f"expected tag, got {val!r}")
        tag = val
        i += 1
        if i >= len(tokens):
            raise QuerySyntaxError(f"dangling tag {tag!r}")
        kind, val = tokens[i]
        if kind == "exists":
            conditions.append(_Condition(tag, _OP_EXISTS, None))
            i += 1
        elif kind == "contains":
            i += 1
            if i >= len(tokens) or tokens[i][0] != "string":
                raise QuerySyntaxError("CONTAINS needs a string operand")
            conditions.append(_Condition(tag, _OP_CONTAINS, tokens[i][1][1:-1]))
            i += 1
        elif kind == "op":
            op = val
            i += 1
            if i >= len(tokens):
                raise QuerySyntaxError(f"operator {op!r} needs an operand")
            okind, oval = tokens[i]
            if okind == "string":
                if op != _OP_EQ:
                    raise QuerySyntaxError(
                        f"operator {op!r} not valid for strings"
                    )
                conditions.append(_Condition(tag, _OP_EQ, oval[1:-1]))
            elif okind == "number":
                conditions.append(_Condition(tag, op, float(oval)))
            else:
                raise QuerySyntaxError(f"bad operand {oval!r}")
            i += 1
        else:
            raise QuerySyntaxError(f"expected operator after {tag!r}, got {val!r}")
        if i < len(tokens):
            kind, val = tokens[i]
            if kind != "and":
                raise QuerySyntaxError(f"expected AND, got {val!r}")
            i += 1
            if i >= len(tokens):
                raise QuerySyntaxError("dangling AND")
    return Query(source, conditions)


def query_for_event(event_value: str) -> Query:
    """reference: types/events.go QueryForEvent."""
    return compile_query(f"tm.event = '{event_value}'")
