"""Channel — a reactor's typed pipe into the router.

reference: internal/p2p/channel.go:66-153. Reactors send Envelopes (unicast
or broadcast) and iterate inbound envelopes; PeerErrors flow out-of-band to
trigger eviction.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional

from .types import ChannelDescriptor, Envelope, PeerError

__all__ = ["Channel"]


class Channel:
    def __init__(self, descriptor: ChannelDescriptor) -> None:
        self.descriptor = descriptor
        self.id = descriptor.channel_id
        self.name = descriptor.name or f"ch{descriptor.channel_id}"
        # reactor → router
        self.out_queue: asyncio.Queue[Envelope] = asyncio.Queue(
            maxsize=descriptor.send_queue_capacity
        )
        # router → reactor
        self.in_queue: asyncio.Queue[Envelope] = asyncio.Queue(
            maxsize=descriptor.recv_buffer_capacity
        )
        self.error_queue: asyncio.Queue[PeerError] = asyncio.Queue(maxsize=64)
        self._closed = False

    async def send(self, envelope: Envelope) -> None:
        await self.out_queue.put(envelope)

    def try_send(self, envelope: Envelope) -> bool:
        """Non-blocking send; drops on a full queue (gossip semantics)."""
        try:
            self.out_queue.put_nowait(envelope)
            return True
        except asyncio.QueueFull:
            return False

    async def send_error(self, peer_error: PeerError) -> None:
        await self.error_queue.put(peer_error)

    async def receive(self) -> Envelope:
        return await self.in_queue.get()

    def __aiter__(self) -> AsyncIterator[Envelope]:
        return self._iter()

    async def _iter(self):
        while True:
            yield await self.in_queue.get()

    # router side
    def deliver(self, envelope: Envelope) -> bool:
        """Inbound delivery; drops (with False) when the reactor lags."""
        try:
            self.in_queue.put_nowait(envelope)
            return True
        except asyncio.QueueFull:
            return False
