"""PEX — peer exchange reactor on channel 0x00.

reference: internal/p2p/pex/reactor.go (:26 ChannelID 0x00, request/
response flow with per-peer poll intervals and unsolicited-response
policing). Peers poll each other for known addresses and feed them to
the PeerManager; seed nodes exist primarily to run this protocol.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List

from ..encoding.proto import FieldReader, ProtoWriter
from ..libs.log import get_logger
from ..libs.service import Service
from .channel import Channel
from .peermanager import PeerManager, PeerStatus
from .types import ChannelDescriptor, Envelope, NodeID, PeerError

__all__ = [
    "PEX_CHANNEL_ID",
    "PexRequest",
    "PexResponse",
    "PexReactor",
    "pex_channel_descriptor",
]

PEX_CHANNEL_ID = 0x00
_MAX_ADDRESSES = 100  # reference: pex/reactor.go maxAddresses
_MIN_POLL_INTERVAL = 5.0
_MAX_POLL_INTERVAL = 600.0
_REQUEST_TIMEOUT = 30.0  # in-flight request expiry (droppable path)


@dataclass
class PexRequest:
    """reference: proto/tendermint/p2p/pex.pb.go PexRequest."""


@dataclass
class PexResponse:
    addresses: List[str] = field(default_factory=list)  # id@host:port URLs


class _Codec:
    """Message oneof: 1=request, 2=response{repeated url=1}."""

    @staticmethod
    def encode(msg) -> bytes:
        w = ProtoWriter()
        if isinstance(msg, PexRequest):
            w.message(1, b"")  # presence-carrying empty submessage
        elif isinstance(msg, PexResponse):
            inner = ProtoWriter()
            for url in msg.addresses:
                inner.bytes(1, url.encode())
            w.message(2, inner.finish())
        else:
            raise TypeError(f"not a pex message: {msg!r}")
        return w.finish()

    @staticmethod
    def decode(data: bytes):
        r = FieldReader(data)
        if r.get(1) is not None:
            return PexRequest()
        if r.get(2) is not None:
            inner = FieldReader(r.bytes(2))
            return PexResponse(
                addresses=[b.decode() for b in inner.get_all(1)]
            )
        raise ValueError("empty pex message")


def pex_channel_descriptor() -> ChannelDescriptor:
    """reference: pex/reactor.go ChannelDescriptor()."""
    return ChannelDescriptor(
        channel_id=PEX_CHANNEL_ID,
        message_type=_Codec,
        priority=1,
        send_queue_capacity=10,
        recv_message_capacity=256 * 1024,
        name="pex",
    )


class PexReactor(Service):
    """Polls peers for addresses; answers their polls.

    reference: pex/reactor.go. Per-peer poll interval grows as the
    address book fills (we learn less from each poll), resetting when
    responses still teach us new addresses.
    """

    def __init__(
        self,
        peer_manager: PeerManager,
        channel: Channel,
        peer_updates: asyncio.Queue,
    ) -> None:
        super().__init__(name="pex", logger=get_logger("pex"))
        self.peer_manager = peer_manager
        self.channel = channel
        self.peer_updates = peer_updates
        self._available: Dict[NodeID, float] = {}  # peer -> next poll time
        self._poll_interval: Dict[NodeID, float] = {}
        self._requested: Dict[NodeID, float] = {}  # in-flight request time
        self.total_added = 0

    async def on_start(self) -> None:
        self.spawn(self._receive_loop(), "recv")
        self.spawn(self._peer_update_loop(), "peer-updates")
        self.spawn(self._poll_loop(), "poll")

    # -- outbound polling --

    async def _poll_loop(self) -> None:
        while True:
            await asyncio.sleep(min(0.5, _MIN_POLL_INTERVAL / 2))
            now = time.monotonic()
            # expire in-flight requests: the request or its response may
            # ride a droppable queue, and a peer stuck in _requested
            # would never be polled again
            for pid, sent_at in list(self._requested.items()):
                if now - sent_at > _REQUEST_TIMEOUT:
                    del self._requested[pid]
            due = [
                pid for pid, when in self._available.items()
                if when <= now and pid not in self._requested
            ]
            if not due:
                continue
            pid = random.choice(due)
            self._requested[pid] = now
            interval = self._poll_interval.get(pid, _MIN_POLL_INTERVAL)
            self._available[pid] = now + interval
            await self.channel.send(Envelope(to=pid, message=PexRequest()))

    # -- inbound --

    async def _receive_loop(self) -> None:
        async for envelope in self.channel:
            msg = envelope.message
            if isinstance(msg, PexRequest):
                addresses = self.peer_manager.advertise(_MAX_ADDRESSES)
                await self.channel.send(
                    Envelope(
                        to=envelope.from_peer,
                        message=PexResponse(addresses=addresses),
                    )
                )
            elif isinstance(msg, PexResponse):
                await self._handle_response(envelope.from_peer, msg)

    async def _handle_response(self, pid: NodeID, msg: PexResponse) -> None:
        if pid not in self._requested:
            # unsolicited response: protocol violation
            # (reference: pex/reactor.go handlePexMessage)
            await self.channel.send_error(
                PeerError(node_id=pid, err="unsolicited pex response")
            )
            return
        del self._requested[pid]
        if len(msg.addresses) > _MAX_ADDRESSES:
            await self.channel.send_error(
                PeerError(node_id=pid, err="oversized pex response")
            )
            return
        added = 0
        for url in msg.addresses:
            try:
                if self.peer_manager.add(url):
                    added += 1
            except ValueError:
                await self.channel.send_error(
                    PeerError(node_id=pid, err=f"invalid pex address {url!r}")
                )
                return
        self.total_added += added
        # back off polls that teach us nothing; reset productive ones
        cur = self._poll_interval.get(pid, _MIN_POLL_INTERVAL)
        if added == 0:
            self._poll_interval[pid] = min(cur * 2, _MAX_POLL_INTERVAL)
        else:
            self._poll_interval[pid] = _MIN_POLL_INTERVAL

    async def _peer_update_loop(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP:
                self._available[update.node_id] = time.monotonic()
                self._poll_interval[update.node_id] = _MIN_POLL_INTERVAL
            else:
                self._available.pop(update.node_id, None)
                self._poll_interval.pop(update.node_id, None)
                self._requested.pop(update.node_id, None)
