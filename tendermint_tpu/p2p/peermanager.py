"""PeerManager — peer lifecycle state machine and address book.

reference: internal/p2p/peermanager.go (design comment :63-119, state
transitions :386-778, Subscribe :828, Advertise :793). The manager owns
which peers to dial, what to do on failure (exponential backoff), when to
evict, and who gets the connection slots (persistent peers always win).

States (implicit, like the reference):
  candidate → dialing → connected → ready → evicting → disconnected
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..libs.log import get_logger
from .types import NodeID, parse_node_address

__all__ = [
    "PeerManager",
    "PeerManagerOptions",
    "PeerUpdate",
    "PeerStatus",
    "AlreadyConnectedError",
    "CrossoverRejectError",
]


class AlreadyConnectedError(ValueError):
    """The peer already holds a live connection slot."""


class CrossoverRejectError(ValueError):
    """Inbound rejected: our own outbound dial to this peer is the
    canonical connection (we have the lower node ID)."""


class PeerStatus:
    UP = "up"
    DOWN = "down"


@dataclass
class PeerUpdate:
    node_id: NodeID
    status: str


@dataclass
class PeerManagerOptions:
    """reference: peermanager.go:121-175."""

    persistent_peers: List[str] = field(default_factory=list)
    max_connected: int = 16
    max_connected_upgrade: int = 4
    max_peers: int = 1000
    min_retry_time: float = 0.25
    max_retry_time: float = 600.0
    max_retry_time_persistent: float = 20.0


def backoff_delay(
    attempts: int, opts: PeerManagerOptions, persistent: bool, rng=random
) -> float:
    """Jittered capped exponential dial backoff: base · 2^(n−1) capped,
    then FULL jitter over [d/2, d] — concurrent boots (every localnet
    node dialing every other) decorrelate instead of retrying in
    lockstep, and the expected delay halves versus the old
    +10%-jitter schedule, which is what fixed the
    occasionally-tens-of-seconds in-process localnet boot (PR 12
    note). Computed ONCE per failure (stored as retry_at), so the
    schedule a failure message names is the schedule that ran."""
    if attempts <= 0:
        return 0.0
    cap = (
        opts.max_retry_time_persistent if persistent else opts.max_retry_time
    )
    delay = min(opts.min_retry_time * (2 ** min(attempts - 1, 16)), cap)
    return delay * (0.5 + 0.5 * rng.random())


@dataclass
class _Peer:
    node_id: NodeID
    addresses: Set[Tuple[str, int]] = field(default_factory=set)
    persistent: bool = False
    dial_attempts: int = 0
    last_dial_failure: float = 0.0
    retry_at: float = 0.0  # next dial not before this instant
    retry_delay_s: float = 0.0  # the delay behind retry_at (metrics)
    banned_until: float = 0.0  # shed/misbehaving peers sit out a window
    dialing: bool = False
    connected: bool = False
    ready: bool = False
    inbound: bool = False
    evicting: bool = False
    evict_reason: str = ""
    score: int = 0
    connected_at: float = 0.0


class PeerManager:
    def __init__(
        self,
        self_id: NodeID,
        options: Optional[PeerManagerOptions] = None,
        store=None,  # optional KVStore for address-book persistence
        metrics=None,  # optional P2PMetrics (dial-backoff histogram)
        clock=time.monotonic,  # injectable for backoff-schedule tests
    ) -> None:
        self.self_id = self_id
        self.opts = options or PeerManagerOptions()
        self.metrics = metrics
        self._clock = clock
        self.logger = get_logger("p2p.peermanager")
        self._peers: Dict[NodeID, _Peer] = {}
        self._subscribers: List[asyncio.Queue] = []
        self._evict_queue: asyncio.Queue[NodeID] = asyncio.Queue()
        self._wakeup = asyncio.Event()  # new candidates / freed slots
        self._store = store
        self._last_persist = 0.0
        self._dirty = False
        if store is not None:
            self._load()
        for addr in self.opts.persistent_peers:
            if addr:
                self.add(addr, persistent=True)

    # -- address book --

    def add(self, address: str, persistent: bool = False) -> bool:
        """Add a peer address; returns True if new
        (reference: peermanager.go:386-420)."""
        node_id, host, port = parse_node_address(address)
        if not node_id:
            raise ValueError(f"address {address!r} has no node ID")
        if node_id == self.self_id:
            return False
        peer = self._peers.get(node_id)
        if peer is None:
            if len(self._peers) >= self.opts.max_peers:
                return False
            peer = _Peer(node_id=node_id)
            self._peers[node_id] = peer
        new = (host, port) not in peer.addresses
        peer.addresses.add((host, port))
        peer.persistent = peer.persistent or persistent
        if new:
            self._persist()
            self._wakeup.set()
        return new

    def advertise(self, limit: int = 100) -> List[str]:
        """Addresses to share via PEX (reference: peermanager.go:793-826)."""
        out = []
        for peer in self._peers.values():
            for host, port in peer.addresses:
                out.append(f"{peer.node_id}@{host}:{port}")
        random.shuffle(out)
        return out[:limit]

    def peers(self) -> List[NodeID]:
        return [p.node_id for p in self._peers.values() if p.ready]

    def connection_inbound(self, node_id: NodeID) -> Optional[bool]:
        """Direction of the peer's live connection (None if not
        connected) — the router's crossover replacement guard."""
        peer = self._peers.get(node_id)
        if peer is None or not peer.connected:
            return None
        return peer.inbound

    def connected_peers(self) -> List[Tuple[NodeID, str]]:
        """(node_id, first known address) for every ready peer —
        the net_info RPC surface (reference: net.go:16-44)."""
        out = []
        for p in self._peers.values():
            if p.ready:
                addr = ""
                if p.addresses:
                    host, port = sorted(p.addresses)[0]
                    addr = f"{host}:{port}"
                out.append((p.node_id, addr))
        return out

    def num_connected(self) -> int:
        # a dialing peer holds a slot too, or we would over-dial
        return sum(
            1 for p in self._peers.values() if p.connected or p.dialing
        )

    # -- dialing --

    async def dial_next(self) -> Tuple[NodeID, str, int]:
        """Block until a peer should be dialed; marks it dialing
        (reference: peermanager.go DialNext/TryDialNext)."""
        while True:
            candidate = self._next_dial_candidate()
            if candidate is not None:
                peer, (host, port) = candidate
                peer.dialing = True  # reserve the slot
                peer.dial_attempts += 1
                return peer.node_id, host, port
            self._wakeup.clear()
            # wake on new peers, or poll for expired backoffs
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
            except asyncio.TimeoutError:
                pass

    def _next_dial_candidate(self) -> Optional[Tuple[_Peer, Tuple[str, int]]]:
        if self.num_connected() >= self.opts.max_connected:
            return None
        now = self._clock()
        best: Optional[_Peer] = None
        for peer in self._peers.values():
            if peer.connected or peer.dialing or not peer.addresses:
                continue
            if now < peer.retry_at or now < peer.banned_until:
                continue
            if best is None or (
                peer.persistent, peer.score, -peer.dial_attempts
            ) > (best.persistent, best.score, -best.dial_attempts):
                best = peer
        if best is None:
            return None
        # rotate through known addresses across retries so one stale
        # address can't shadow a live one
        addrs = sorted(best.addresses)
        return best, addrs[best.dial_attempts % len(addrs)]

    def dial_abandoned(self, node_id: NodeID) -> None:
        """Clear a dial reservation without the failure penalty — the
        dial was made redundant (e.g. a crossover resolved onto the
        peer's connection), not refused. No score dock, no backoff."""
        peer = self._peers.get(node_id)
        if peer is None:
            return
        peer.dialing = False
        self._wakeup.set()

    def dial_failed(self, node_id: NodeID) -> None:
        """reference: peermanager.go:499-530. Only clears the dialing
        reservation — a live inbound connection accepted during the dial
        (crossover) must keep its connected state. Schedules the next
        retry on the jittered capped exponential schedule (computed
        once, here, so the recorded delay is the one that runs)."""
        peer = self._peers.get(node_id)
        if peer is None:
            return
        peer.dialing = False
        peer.last_dial_failure = self._clock()
        peer.retry_delay_s = backoff_delay(
            peer.dial_attempts, self.opts, peer.persistent
        )
        peer.retry_at = peer.last_dial_failure + peer.retry_delay_s
        peer.score = max(peer.score - 1, -100)
        if self.metrics is not None:
            self.metrics.dial_backoff.observe(peer.retry_delay_s)
        self._wakeup.set()

    def dialed(self, node_id: NodeID) -> None:
        """Outbound connection established. Raises if the peer is
        already connected — a dial/accept crossover must keep the
        existing connection, not silently double-register
        (reference: peermanager.go:569 'peer is already connected')."""
        peer = self._peers.get(node_id)
        if peer is None:
            raise ValueError(f"dialed unknown peer {node_id}")
        if peer.connected:
            raise AlreadyConnectedError(
                f"peer {node_id} is already connected"
            )
        peer.dialing = False
        peer.dial_attempts = 0
        peer.retry_at = 0.0
        peer.connected = True
        peer.inbound = False

    def accepted(self, node_id: NodeID) -> None:
        """Inbound connection; may exceed capacity → schedule eviction of
        someone (reference: peermanager.go:585-640)."""
        if node_id == self.self_id:
            raise ValueError("rejecting connection from self")
        peer = self._peers.get(node_id)
        if peer is None:
            peer = _Peer(node_id=node_id)
            self._peers[node_id] = peer
        if self._clock() < peer.banned_until:
            # a shed peer sits out its ban window on BOTH paths: we
            # neither dial it nor let it immediately reconnect inbound
            raise ValueError(f"peer {node_id} is banned")
        if peer.connected:
            raise AlreadyConnectedError(
                f"peer {node_id} is already connected"
            )
        if peer.dialing and self.self_id < node_id:
            # Simultaneous dial (crossover): without a deterministic
            # winner both sides accept the other's inbound, both
            # dialed() calls raise, both connections close, and the
            # pair livelocks retrying. The canonical connection is the
            # one dialed BY the lower node ID: the lower side rejects
            # the inbound here and keeps its outbound; the higher side
            # replaces its outbound when the canonical inbound arrives
            # (router handles the replacement; reference concern:
            # peermanager.go:569,636 crossover).
            raise CrossoverRejectError(
                f"dial/accept crossover with {node_id}: "
                "lower node ID keeps its outbound dial"
            )
        # capacity check BEFORE reserving the slot, or a rejected inbound
        # peer would leak a phantom connected=True entry forever. This
        # peer's own dialing reservation (crossover) already occupies a
        # slot, so it must not count twice.
        occupied = self.num_connected() - (1 if peer.dialing else 0)
        if (
            occupied + 1
            > self.opts.max_connected + self.opts.max_connected_upgrade
        ):
            raise ValueError("already connected to maximum number of peers")
        peer.connected = True
        peer.inbound = True
        # a live inbound proves the peer is up: future dials (e.g.
        # after this connection drops) start from a fresh schedule
        # instead of inheriting backoff accrued while it was down
        peer.dial_attempts = 0
        peer.retry_at = 0.0
        if self.num_connected() > self.opts.max_connected:
            self._schedule_eviction()

    def ready(self, node_id: NodeID) -> None:
        """Peer handshaked and routed; notify subscribers
        (reference: peermanager.go:642-676)."""
        peer = self._peers.get(node_id)
        if peer is None or not peer.connected:
            return
        peer.ready = True
        peer.connected_at = self._clock()
        self._notify(PeerUpdate(node_id=node_id, status=PeerStatus.UP))

    def disconnected(self, node_id: NodeID) -> None:
        """reference: peermanager.go:696-736."""
        peer = self._peers.get(node_id)
        if peer is None:
            return
        was_ready = peer.ready
        was_evicting = peer.evicting
        # standing reflects SUSTAINED good service, not connection
        # events: +1 only after >=10 min of clean uptime (misbehavior
        # docks -10 via errored()). A reconnect-churning peer gains
        # nothing, so it can't farm eviction resistance or dial priority
        # (reference: peermanager.go scoring intent,
        # peermanager_scoring_test.go)
        if (
            was_ready
            and not was_evicting
            and peer.connected_at
            and self._clock() - peer.connected_at >= 600.0
        ):
            peer.score = min(peer.score + 1, 100)
        peer.connected_at = 0.0
        peer.connected = False
        peer.ready = False
        peer.evicting = False
        peer.evict_reason = ""
        if was_evicting:
            # evicted for misbehavior: apply dial backoff so we don't
            # immediately re-establish the same bad peer
            peer.dial_attempts += 1
            peer.last_dial_failure = self._clock()
            peer.retry_delay_s = backoff_delay(
                peer.dial_attempts, self.opts, peer.persistent
            )
            peer.retry_at = peer.last_dial_failure + peer.retry_delay_s
        if was_ready:
            self._notify(PeerUpdate(node_id=node_id, status=PeerStatus.DOWN))
        self._wakeup.set()

    def errored(self, node_id: NodeID, err: str) -> None:
        """Reactor-reported misbehavior → evict
        (reference: peermanager.go:678-694)."""
        peer = self._peers.get(node_id)
        if peer is None or not peer.connected or peer.evicting:
            return
        self.logger.info("evicting peer", peer=node_id, err=err)
        peer.evicting = True
        peer.evict_reason = "misbehavior"
        peer.score -= 10
        self._evict_queue.put_nowait(node_id)

    def shed_slow(self, node_id: NodeID, ban_s: float = 30.0) -> None:
        """The router detected a slow consumer (its send queues shed
        past the threshold): evict with reason `slow_peer` and sit the
        peer out for `ban_s` — an immediate redial/reconnect would
        rebuild the exact queue that just overflowed."""
        peer = self._peers.get(node_id)
        if peer is None or not peer.connected or peer.evicting:
            return
        self.logger.info(
            "shedding slow peer", peer=node_id, ban_s=ban_s
        )
        peer.evicting = True
        peer.evict_reason = "slow_peer"
        peer.score = max(peer.score - 2, -100)
        peer.banned_until = self._clock() + max(ban_s, 0.0)
        self._evict_queue.put_nowait(node_id)

    def ban(self, node_id: NodeID, duration_s: float) -> None:
        """Refuse to dial or accept this peer for `duration_s`."""
        peer = self._peers.get(node_id)
        if peer is None:
            return
        peer.banned_until = self._clock() + max(duration_s, 0.0)

    def evict_reason(self, node_id: NodeID) -> str:
        """Why the peer is being evicted ("" when not evicting) — the
        router stamps this on the disconnect metric and the goodbye
        frame so BOTH sides can attribute the drop."""
        peer = self._peers.get(node_id)
        return peer.evict_reason if peer is not None else ""

    async def evict_next(self) -> NodeID:
        """Next peer the router should disconnect
        (reference: peermanager.go EvictNext)."""
        return await self._evict_queue.get()

    def _schedule_eviction(self) -> None:
        """Pick the lowest-value connected peer to make room."""
        victims = [
            p for p in self._peers.values()
            if p.connected and not p.persistent and not p.evicting
        ]
        if not victims:
            return
        victim = min(victims, key=lambda p: p.score)
        victim.evicting = True
        victim.evict_reason = "capacity"
        self._evict_queue.put_nowait(victim.node_id)

    # -- subscriptions --

    def subscribe(self) -> asyncio.Queue:
        """Peer up/down feed, seeded with peers that are ALREADY up so a
        late subscriber (e.g. a reactor started after connections formed)
        doesn't miss them (reference: peermanager.go:828-870)."""
        q: asyncio.Queue = asyncio.Queue(maxsize=256)
        for p in self._peers.values():
            if p.ready:
                q.put_nowait(
                    PeerUpdate(node_id=p.node_id, status=PeerStatus.UP)
                )
        self._subscribers.append(q)
        return q

    def _notify(self, update: PeerUpdate) -> None:
        for q in self._subscribers:
            try:
                q.put_nowait(update)
            except asyncio.QueueFull:
                self.logger.error(
                    "peer update subscriber overflowed; dropping update"
                )

    # -- persistence (address book) --

    def _persist(self) -> None:
        """Debounced: serializing the full book per PEX address would be
        O(n²) during sync. flush() forces the write (router shutdown)."""
        if self._store is None:
            return
        if time.monotonic() - self._last_persist < 1.0:
            self._dirty = True
            return
        self._write_book()

    def flush(self) -> None:
        if self._store is not None and self._dirty:
            self._write_book()

    def _write_book(self) -> None:
        doc = {
            p.node_id: {
                "addresses": sorted(f"{h}:{pt}" for h, pt in p.addresses),
                "persistent": p.persistent,
                "score": p.score,
            }
            for p in self._peers.values()
        }
        self._store.set(b"peermanager/addressbook", json.dumps(doc).encode())
        self._last_persist = time.monotonic()
        self._dirty = False

    def _load(self) -> None:
        raw = self._store.get(b"peermanager/addressbook")
        if not raw:
            return
        doc = json.loads(raw.decode())
        for node_id, info in doc.items():
            peer = _Peer(node_id=node_id)
            for addr in info.get("addresses", []):
                host, _, port = addr.rpartition(":")
                peer.addresses.add((host, int(port)))
            peer.persistent = info.get("persistent", False)
            peer.score = info.get("score", 0)
            self._peers[node_id] = peer
