"""P2P stack — router, peer manager, transports, secret connection.

reference: internal/p2p/. The inter-host (DCN) fabric of the framework:
encrypted TCP gossip between validator nodes. Intra-host device-mesh
communication uses XLA collectives (tendermint_tpu/parallel), not this
stack — see SURVEY.md §2.4 for the mapping.
"""

from .channel import Channel
from .peermanager import (
    PeerManager,
    PeerManagerOptions,
    PeerStatus,
    PeerUpdate,
)
from .router import Router, RouterOptions
from .transport import (
    Connection,
    MemoryNetwork,
    MemoryTransport,
    TCPTransport,
    Transport,
)
from .types import (
    ChannelDescriptor,
    Envelope,
    NodeInfo,
    PeerError,
    node_id_from_pubkey,
    parse_node_address,
)

__all__ = [
    "Channel",
    "ChannelDescriptor",
    "Connection",
    "Envelope",
    "MemoryNetwork",
    "MemoryTransport",
    "NodeInfo",
    "PeerError",
    "PeerManager",
    "PeerManagerOptions",
    "PeerStatus",
    "PeerUpdate",
    "Router",
    "RouterOptions",
    "TCPTransport",
    "Transport",
    "node_id_from_pubkey",
    "parse_node_address",
]
