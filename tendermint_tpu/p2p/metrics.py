"""P2P metrics struct (reference: internal/p2p/metrics.go), per-node
when threaded from node assembly — see consensus/metrics.py for the
pattern.
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["P2PMetrics"]


class P2PMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.peers = r.gauge("p2p", "peers", "Number of connected peers.")
        self.bytes_sent = r.counter(
            "p2p",
            "message_send_bytes_total",
            "Bytes sent, by channel.",
            label_names=("ch",),
        )
        self.bytes_recv = r.counter(
            "p2p",
            "message_receive_bytes_total",
            "Bytes received, by channel.",
            label_names=("ch",),
        )
