"""P2P metrics struct (reference: internal/p2p/metrics.go), per-node
when threaded from node assembly — see consensus/metrics.py for the
pattern.
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["P2PMetrics"]


# dial-backoff delays span "retry immediately" to the 10-minute cap
_BACKOFF_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 60.0, 180.0, 600.0
)


class P2PMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.peers = r.gauge("p2p", "peers", "Number of connected peers.")
        self.bytes_sent = r.counter(
            "p2p",
            "message_send_bytes_total",
            "Bytes sent, by channel.",
            label_names=("ch",),
        )
        self.bytes_recv = r.counter(
            "p2p",
            "message_receive_bytes_total",
            "Bytes received, by channel.",
            label_names=("ch",),
        )
        # -- self-healing lifecycle (ISSUE 13) --
        # reason values come from the router's FIXED vocabulary
        # (router._PEER_REASONS; remote-reported reasons are sanitized
        # against it before becoming labels), never from the wire
        self.peer_disconnects = r.counter(
            "p2p",
            "peer_disconnects_total",
            "Peer disconnects, by reason (remote/* = peer-reported).",
            label_names=("reason",),
        )
        self.dial_backoff = r.histogram(
            "p2p",
            "dial_backoff_seconds",
            "Backoff delay scheduled after each failed dial.",
            buckets=_BACKOFF_BUCKETS,
        )
        self.send_queue_dropped = r.counter(
            "p2p",
            "send_queue_dropped_total",
            "Outbound messages shed by full per-peer channel queues.",
            label_names=("ch",),
        )
        self.net_faults = r.counter(
            "p2p",
            "net_faults_total",
            "Injected network faults applied (chaos runs only).",
            label_names=("point", "mode"),
        )
