"""SecretConnection — authenticated encryption for peer links.

reference: internal/p2p/conn/secret_connection.go. Station-to-Station:
X25519 ECDH (:289-301) → HKDF key derivation (:337-389) → per-direction
ChaCha20-Poly1305 AEAD frames with counter nonces (:455), identity proven
by an ed25519 signature over the derived challenge (:391-453).

Wire format (framework-local; not byte-compatible with the Go impl):
  handshake: 32-byte ephemeral X25519 pubkey each way (plaintext)
  then AEAD frames: 4-byte BE ciphertext length | ciphertext
  first frame each way: AuthSig{type=1, pubkey=2, sig=3} proto

Transcript binding: the identity signature covers the HKDF challenge,
which hashes the ECDH secret together with BOTH ephemeral keys — an
attacker interposing its own ephemerals cannot replay either proof.
Everything after the AuthSig frames (the transport's NodeInfo exchange,
transport.py:191-196, and all router traffic) rides the AEAD channel
keyed by that same transcript, so peer metadata is bound to the
handshake rather than trusted plaintext. The byte layout is pinned by
known-answer vectors in tests/test_conn_vectors.py; key derivation is
cross-checked there against an independent HMAC-based HKDF.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac as _hmac
import os
import struct
from typing import Optional, Tuple

try:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305,
    )
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    _HAVE_OPENSSL = True
except ImportError:  # no wheel: RFC 7748/5869/8439 fallbacks below
    _HAVE_OPENSSL = False
    from ..crypto.symmetric import (
        PureChaCha20Poly1305 as ChaCha20Poly1305,
    )

    class _RawOnly:  # stands in for Encoding/PublicFormat in the call
        Raw = None

    Encoding = PublicFormat = _RawOnly

from ..crypto.keys import PrivKey, PubKey, pubkey_from_type_and_bytes
from ..encoding.proto import FieldReader, ProtoWriter

__all__ = ["SecretConnection", "HandshakeError"]

MAX_FRAME = 1 << 22  # 4 MiB ciphertext cap per frame
_HKDF_INFO = b"TENDERMINT_TPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"

_X25519_P = 2**255 - 19


def _x25519_scalarmult(k: bytes, u: bytes) -> bytes:
    """RFC 7748 §5 X25519 (Montgomery ladder), the gated stand-in for
    the wheel's native implementation — both sides of a localnet
    handshake agree either way; the conn-vectors test pins the bytes."""
    k_int = int.from_bytes(
        bytes([k[0] & 248]) + k[1:31] + bytes([(k[31] & 127) | 64]),
        "little",
    )
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k_int >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _X25519_P
        aa = a * a % _X25519_P
        b = (x2 - z2) % _X25519_P
        bb = b * b % _X25519_P
        e = (aa - bb) % _X25519_P
        c = (x3 + z3) % _X25519_P
        d = (x3 - z3) % _X25519_P
        da = d * a % _X25519_P
        cb = c * b % _X25519_P
        x3 = (da + cb) % _X25519_P
        x3 = x3 * x3 % _X25519_P
        z3 = (da - cb) % _X25519_P
        z3 = x1 * (z3 * z3) % _X25519_P
        x2 = aa * bb % _X25519_P
        z2 = e * ((aa + 121665 * e) % _X25519_P) % _X25519_P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, _X25519_P - 2, _X25519_P) % _X25519_P
    return out.to_bytes(32, "little")


if not _HAVE_OPENSSL:

    class X25519PublicKey:  # type: ignore[no-redef]
        def __init__(self, data: bytes) -> None:
            if len(data) != 32:
                raise ValueError("x25519 pubkey must be 32 bytes")
            self._data = bytes(data)

        @classmethod
        def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
            return cls(data)

        def public_bytes(self, *_args) -> bytes:
            return self._data

    class X25519PrivateKey:  # type: ignore[no-redef]
        def __init__(self, k: bytes) -> None:
            self._k = k

        @classmethod
        def generate(cls) -> "X25519PrivateKey":
            return cls(os.urandom(32))

        def public_key(self) -> X25519PublicKey:
            return X25519PublicKey(
                _x25519_scalarmult(self._k, (9).to_bytes(32, "little"))
            )

        def exchange(self, peer: X25519PublicKey) -> bytes:
            out = _x25519_scalarmult(self._k, peer._data)
            if out == b"\x00" * 32:
                raise ValueError("x25519: low-order point")
            return out


class HandshakeError(Exception):
    pass


def _hkdf_sha256(ikm: bytes, length: int, info: bytes) -> bytes:
    if _HAVE_OPENSSL:
        return HKDF(
            algorithm=hashes.SHA256(), length=length, salt=None, info=info
        ).derive(ikm)
    # RFC 5869 with the zero salt the wheel defaults to
    prk = _hmac.new(b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = _hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


def _derive(shared: bytes, local_eph: bytes, remote_eph: bytes):
    """→ (send_key, recv_key, challenge). Key order is fixed by sorting
    the ephemeral pubkeys, so both sides agree without a role bit
    (reference: secret_connection.go deriveSecrets + sort32)."""
    okm = _hkdf_sha256(
        shared + min(local_eph, remote_eph) + max(local_eph, remote_eph),
        96,
        _HKDF_INFO,
    )
    key_a, key_b, challenge = okm[:32], okm[32:64], okm[64:]
    if local_eph < remote_eph:
        return key_a, key_b, challenge
    return key_b, key_a, challenge


def _auth_sig_bytes(pub: PubKey, sig: bytes) -> bytes:
    w = ProtoWriter()
    w.string(1, pub.type())
    w.bytes(2, pub.bytes())
    w.bytes(3, sig)
    return w.finish()


def _parse_auth_sig(data: bytes) -> Tuple[PubKey, bytes]:
    r = FieldReader(data)
    pub = pubkey_from_type_and_bytes(r.string(1), r.bytes(2))
    return pub, r.bytes(3)


class SecretConnection:
    """Encrypted, authenticated framed stream over an asyncio socket."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_key: bytes,
        recv_key: bytes,
        remote_pubkey: PubKey,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_nonce = 0
        self._recv_nonce = 0
        self.remote_pubkey = remote_pubkey
        self._write_lock = asyncio.Lock()

    # -- establishment --

    @classmethod
    async def handshake(
        cls,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        local_priv: PrivKey,
    ) -> "SecretConnection":
        """Mutual-auth handshake; symmetric (no initiator role)."""
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw
        )
        writer.write(eph_pub)
        await writer.drain()
        remote_eph = await reader.readexactly(32)
        if remote_eph == eph_pub:
            raise HandshakeError("remote echoed our ephemeral key")
        shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        send_key, recv_key, challenge = _derive(shared, eph_pub, remote_eph)

        conn = cls(
            reader, writer, send_key, recv_key, remote_pubkey=None  # set below
        )
        # Exchange identity proofs over the encrypted link
        sig = local_priv.sign(challenge)
        await conn.write_frame(_auth_sig_bytes(local_priv.pub_key(), sig))
        remote_pub, remote_sig = _parse_auth_sig(await conn.read_frame())
        if not remote_pub.verify_signature(challenge, remote_sig):
            raise HandshakeError("challenge signature verification failed")
        conn.remote_pubkey = remote_pub
        return conn

    # -- framed AEAD I/O --

    def _nonce(self, counter: int) -> bytes:
        return struct.pack("<Q", counter) + b"\x00\x00\x00\x00"

    async def write_frame(self, plaintext: bytes) -> None:
        async with self._write_lock:
            ct = self._send.encrypt(
                self._nonce(self._send_nonce), plaintext, None
            )
            self._send_nonce += 1
            self._writer.write(struct.pack(">I", len(ct)) + ct)
            await self._writer.drain()

    async def read_frame(self) -> bytes:
        hdr = await self._reader.readexactly(4)
        (length,) = struct.unpack(">I", hdr)
        if length > MAX_FRAME:
            raise HandshakeError(f"frame too large: {length}")
        ct = await self._reader.readexactly(length)
        pt = self._recv.decrypt(self._nonce(self._recv_nonce), ct, None)
        self._recv_nonce += 1
        return pt

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass
