"""In-process p2p network harness for reactor tests.

reference: internal/p2p/p2ptest/network.go — spins N router+peermanager
nodes wired over memory transports, fully connected.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ..crypto.ed25519 import PrivKeyEd25519
from ..libs.metrics import Registry
from .metrics import P2PMetrics
from .peermanager import PeerManager, PeerManagerOptions
from .router import Router, RouterOptions
from .transport import MemoryNetwork, MemoryTransport
from .types import ChannelDescriptor, NodeInfo, node_id_from_pubkey

__all__ = ["TestNetwork", "TestNode"]


class TestNode:
    __test__ = False  # not a pytest class

    def __init__(
        self,
        network: MemoryNetwork,
        index: int,
        chain_id: str,
        router_options: Optional[RouterOptions] = None,
    ) -> None:
        self.priv_key = PrivKeyEd25519.from_seed(
            index.to_bytes(2, "big") * 16
        )
        self.node_id = node_id_from_pubkey(self.priv_key.pub_key())
        self.addr = f"node{index}:26656"
        self.node_info = NodeInfo(
            node_id=self.node_id,
            listen_addr=self.addr,
            network=chain_id,
            moniker=f"node{index}",
        )
        self.transport = MemoryTransport(network, self.addr)
        # per-node registry so multi-node tests scrape disjoint series
        # (the same shape node assembly wires)
        self.registry = Registry()
        self.metrics = P2PMetrics(self.registry)
        self.peer_manager = PeerManager(
            self.node_id,
            PeerManagerOptions(max_connected=64),
            metrics=self.metrics,
        )
        self.router = Router(
            self.node_info,
            self.priv_key,
            self.peer_manager,
            self.transport,
            options=router_options,
            metrics=self.metrics,
        )

    def open_channel(self, descriptor: ChannelDescriptor):
        return self.router.open_channel(descriptor)


class TestNetwork:
    """N fully-connected in-memory nodes."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        n: int,
        chain_id: str = "test-chain",
        router_options: Optional[RouterOptions] = None,
    ) -> None:
        self.memory = MemoryNetwork()
        self.nodes = [
            TestNode(
                self.memory, i, chain_id, router_options=router_options
            )
            for i in range(n)
        ]

    async def start(self) -> None:
        for node in self.nodes:
            await node.router.start()
        # full mesh: every node dials every higher-index node
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1:]:
                a.peer_manager.add(f"{b.node_id}@{b.addr}")
        await self.wait_connected()

    async def wait_connected(self, timeout: float = 10.0) -> None:
        want = len(self.nodes) - 1

        async def all_up():
            while any(
                len(n.peer_manager.peers()) < want for n in self.nodes
            ):
                await asyncio.sleep(0.01)

        await asyncio.wait_for(all_up(), timeout=timeout)

    async def stop(self) -> None:
        for node in self.nodes:
            await node.router.stop()
