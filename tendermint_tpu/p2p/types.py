"""P2P core types — node identity, addresses, envelopes, channels IDs.

reference: types/node_id.go, types/node_info.go, types/netaddress.go,
internal/p2p/channel.go (Envelope, PeerError).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..crypto.keys import PubKey
from ..encoding.proto import FieldReader, ProtoWriter

__all__ = [
    "NodeID",
    "node_id_from_pubkey",
    "parse_node_address",
    "NodeInfo",
    "Envelope",
    "PeerError",
    "ChannelDescriptor",
]

NODE_ID_BYTES = 20

_NODE_ID_RE = re.compile(r"^[0-9a-f]{40}$")
_ADDR_RE = re.compile(
    r"^(?:(?P<proto>\w+)://)?(?:(?P<id>[0-9a-f]{40})@)?"
    r"(?P<host>[^:/@]+)(?::(?P<port>\d+))?$"
)


def node_id_from_pubkey(pub_key: PubKey) -> str:
    """Node ID = hex of the 20-byte address hash of the node key
    (reference: types/node_id.go NodeIDFromPubKey)."""
    return pub_key.address().hex()


def validate_node_id(node_id: str) -> None:
    if not _NODE_ID_RE.match(node_id):
        raise ValueError(f"invalid node ID {node_id!r}")


NodeID = str  # 40-char lowercase hex


def parse_node_address(addr: str) -> Tuple[NodeID, str, int]:
    """'id@host:port' (optionally with scheme) → (id, host, port)
    (reference: internal/p2p/address.go ParseNodeAddress)."""
    m = _ADDR_RE.match(addr.strip())
    if m is None:
        raise ValueError(f"invalid node address {addr!r}")
    node_id = m.group("id") or ""
    if node_id:
        validate_node_id(node_id)
    host = m.group("host")
    port = int(m.group("port") or 26656)
    return node_id, host, port


@dataclass
class NodeInfo:
    """What peers exchange during the handshake
    (reference: types/node_info.go:31-60)."""

    node_id: NodeID = ""
    listen_addr: str = ""
    network: str = ""  # chain ID
    version: str = ""
    channels: bytes = b""  # supported channel IDs, one byte each
    moniker: str = ""
    protocol_version_p2p: int = 0
    protocol_version_block: int = 0
    protocol_version_app: int = 0

    def validate_basic(self) -> None:
        validate_node_id(self.node_id)
        if len(self.channels) > 64:
            raise ValueError("too many channels")

    def compatible_with(self, other: "NodeInfo") -> None:
        """reference: types/node_info.go CompatibleWith."""
        if self.protocol_version_block != other.protocol_version_block:
            raise ValueError(
                f"peer is on a different block protocol: "
                f"{other.protocol_version_block} != "
                f"{self.protocol_version_block}"
            )
        if self.network != other.network:
            raise ValueError(
                f"peer is on a different network: {other.network!r} != "
                f"{self.network!r}"
            )
        if self.channels and other.channels:
            if not any(c in self.channels for c in other.channels):
                raise ValueError("no common channels")

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.string(1, self.node_id)
        w.string(2, self.listen_addr)
        w.string(3, self.network)
        w.string(4, self.version)
        w.bytes(5, self.channels)
        w.string(6, self.moniker)
        w.uint(7, self.protocol_version_p2p)
        w.uint(8, self.protocol_version_block)
        w.uint(9, self.protocol_version_app)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "NodeInfo":
        r = FieldReader(data)
        return cls(
            node_id=r.string(1),
            listen_addr=r.string(2),
            network=r.string(3),
            version=r.string(4),
            channels=r.bytes(5),
            moniker=r.string(6),
            protocol_version_p2p=r.uint(7),
            protocol_version_block=r.uint(8),
            protocol_version_app=r.uint(9),
        )


@dataclass
class Envelope:
    """One message on a channel (reference: internal/p2p/channel.go:15-28)."""

    message: object = None
    from_peer: NodeID = ""  # set on inbound
    to: NodeID = ""  # set on outbound (unless broadcast)
    broadcast: bool = False


@dataclass
class PeerError:
    """Reported by reactors to evict a misbehaving peer
    (reference: internal/p2p/channel.go:30-41)."""

    node_id: NodeID
    err: str
    fatal: bool = True


@dataclass
class ChannelDescriptor:
    """reference: internal/p2p/conn/connection.go ChannelDescriptor."""

    channel_id: int
    message_type: object  # class with to_proto/from_proto OR codec pair
    priority: int = 1
    send_queue_capacity: int = 64
    recv_message_capacity: int = 1 << 20
    recv_buffer_capacity: int = 128
    name: str = ""

    def encode(self, msg) -> bytes:
        # message_type is either a codec (encode/decode functions, e.g. the
        # consensus Message-oneof codec) or a dataclass with to/from_proto
        if hasattr(self.message_type, "encode"):
            return self.message_type.encode(msg)
        return msg.to_proto()

    def decode(self, data: bytes):
        if hasattr(self.message_type, "decode"):
            return self.message_type.decode(data)
        return self.message_type.from_proto(data)
