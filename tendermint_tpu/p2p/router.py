"""Router — the p2p message hub.

reference: internal/p2p/router.go (design comment :108-152). Reactors open
typed channels; the router dials/accepts peers via the transport, runs one
send and one receive task per peer, demuxes inbound messages by channel ID
into reactor queues, and routes outbound envelopes (unicast or broadcast)
onto per-peer queues. PeerManager decides who to dial and who to evict.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..crypto.keys import PrivKey
from ..libs.log import get_logger
from ..libs.service import Service
from .channel import Channel
from .peermanager import PeerManager
from .transport import Connection, Transport
from .types import ChannelDescriptor, Envelope, NodeID, NodeInfo

__all__ = ["Router", "RouterOptions"]


class RouterOptions:
    def __init__(
        self,
        handshake_timeout: float = 20.0,
        dial_timeout: float = 5.0,
        peer_queue_size: int = 128,
        num_concurrent_dials: int = 8,
    ) -> None:
        self.handshake_timeout = handshake_timeout
        self.dial_timeout = dial_timeout
        self.peer_queue_size = peer_queue_size
        self.num_concurrent_dials = num_concurrent_dials


class Router(Service):
    def __init__(
        self,
        node_info: NodeInfo,
        priv_key: PrivKey,
        peer_manager: PeerManager,
        transport: Transport,
        listen_addr: str = "",
        options: Optional[RouterOptions] = None,
    ) -> None:
        super().__init__(name="router", logger=get_logger("p2p.router"))
        self.node_info = node_info
        self.priv_key = priv_key
        self.peer_manager = peer_manager
        self.transport = transport
        self.listen_addr = listen_addr
        self.opts = options or RouterOptions()
        self._channels: Dict[int, Channel] = {}
        self._peer_queues: Dict[NodeID, asyncio.Queue] = {}
        self._peer_conns: Dict[NodeID, Connection] = {}
        self._peer_tasks: Dict[NodeID, list] = {}

    # -- reactor API --

    def open_channel(self, descriptor: ChannelDescriptor) -> Channel:
        """reference: router.go OpenChannel."""
        if descriptor.channel_id in self._channels:
            raise ValueError(
                f"channel {descriptor.channel_id} already open"
            )
        ch = Channel(descriptor)
        self._channels[descriptor.channel_id] = ch
        # advertise the channel in our NodeInfo
        if descriptor.channel_id not in self.node_info.channels:
            self.node_info.channels += bytes([descriptor.channel_id])
        self.spawn(self._route_channel_out(ch), f"ch{ch.id}-out")
        self.spawn(self._route_channel_errors(ch), f"ch{ch.id}-err")
        return ch

    def peer_ids(self):
        return list(self._peer_conns.keys())

    # -- lifecycle --

    async def on_start(self) -> None:
        if self.listen_addr:
            await self.transport.listen(self.listen_addr)
        # accept always runs: memory transports accept without listening
        self.spawn(self._accept_loop(), "accept")
        for _ in range(self.opts.num_concurrent_dials):
            self.spawn(self._dial_loop(), "dial")
        self.spawn(self._evict_loop(), "evict")

    async def on_stop(self) -> None:
        for node_id in list(self._peer_conns):
            self._close_peer(node_id)
        self.peer_manager.flush()  # write any debounced address book state
        await self.transport.close()

    # -- dialing / accepting (reference: router.go dialPeers/acceptPeers) --

    async def _dial_loop(self) -> None:
        while True:
            node_id, host, port = await self.peer_manager.dial_next()
            try:
                conn = await asyncio.wait_for(
                    self.transport.dial(host, port),
                    timeout=self.opts.dial_timeout,
                )
            except Exception as e:
                self.logger.debug(
                    "failed to dial peer", peer=node_id, err=str(e)
                )
                self.peer_manager.dial_failed(node_id)
                continue
            try:
                peer_info = await self._handshake(conn)
                if peer_info.node_id != node_id:
                    raise ConnectionError(
                        f"expected {node_id}, got {peer_info.node_id}"
                    )
                self.peer_manager.dialed(node_id)
            except Exception as e:
                self.logger.info(
                    "peer handshake failed", peer=node_id, err=str(e)
                )
                conn.close()
                self.peer_manager.dial_failed(node_id)
                continue
            self._start_peer(peer_info.node_id, conn)

    async def _accept_loop(self) -> None:
        while True:
            conn = await self.transport.accept()
            self.spawn(self._accept_one(conn), "accept-one")

    async def _accept_one(self, conn: Connection) -> None:
        try:
            peer_info = await self._handshake(conn)
            self.peer_manager.accepted(peer_info.node_id)
        except Exception as e:
            self.logger.debug("inbound handshake failed", err=str(e))
            conn.close()
            return
        self._start_peer(peer_info.node_id, conn)

    async def _handshake(self, conn: Connection) -> NodeInfo:
        peer_info, _peer_pub = await asyncio.wait_for(
            conn.handshake(self.node_info, self.priv_key),
            timeout=self.opts.handshake_timeout,
        )
        peer_info.validate_basic()
        if peer_info.node_id == self.node_info.node_id:
            raise ConnectionError("rejecting connection from self")
        self.node_info.compatible_with(peer_info)
        return peer_info

    # -- per-peer routines (reference: router.go routePeer) --

    def _start_peer(self, node_id: NodeID, conn: Connection) -> None:
        if node_id in self._peer_conns:
            # duplicate connection: keep the existing one. No
            # disconnected() — the live peer's state must not be torn
            # down (reactors would drop peer state while its connection
            # keeps delivering).
            conn.close()
            return
        self._peer_conns[node_id] = conn
        q: asyncio.Queue = asyncio.Queue(maxsize=self.opts.peer_queue_size)
        self._peer_queues[node_id] = q
        send_t = self.spawn(self._send_peer(node_id, conn, q), f"send-{node_id[:8]}")
        recv_t = self.spawn(self._recv_peer(node_id, conn), f"recv-{node_id[:8]}")
        self._peer_tasks[node_id] = [send_t, recv_t]
        self.peer_manager.ready(node_id)
        self.logger.info("peer connected", peer=node_id[:12], addr=conn.remote_addr)

    async def _send_peer(
        self, node_id: NodeID, conn: Connection, queue: asyncio.Queue
    ) -> None:
        while True:
            channel_id, payload = await queue.get()
            try:
                await conn.send(channel_id, payload)
            except asyncio.CancelledError:
                raise
            except ValueError as e:
                # our own oversized/bad payload: drop it, keep the peer
                self.logger.error(
                    "dropping unsendable message", ch=channel_id, err=str(e)
                )
            except Exception:
                # any transport failure means the connection is done; it
                # must never escape into Service fail-fast and kill the
                # whole router (single-peer failure ≠ node failure)
                self._peer_down(node_id)
                return

    async def _recv_peer(self, node_id: NodeID, conn: Connection) -> None:
        try:
            while True:
                channel_id, payload = await conn.receive()
                ch = self._channels.get(channel_id)
                if ch is None:
                    continue  # unknown channel: drop
                try:
                    msg = ch.descriptor.decode(payload)
                except Exception as e:
                    self.logger.info(
                        "peer sent invalid message; evicting",
                        peer=node_id[:12], ch=channel_id, err=str(e),
                    )
                    self.peer_manager.errored(node_id, f"bad message: {e}")
                    return
                if not ch.deliver(
                    Envelope(message=msg, from_peer=node_id)
                ):
                    self.logger.debug(
                        "reactor queue full; dropping message",
                        ch=channel_id,
                    )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # tampered AEAD frames (InvalidTag), oversized frames, resets —
            # all are peer-connection failures, not router failures
            self.logger.debug(
                "peer receive failed", peer=node_id[:12], err=str(e)
            )
            self._peer_down(node_id)

    def _peer_down(self, node_id: NodeID) -> None:
        if node_id not in self._peer_conns:
            return
        self._close_peer(node_id)
        self.peer_manager.disconnected(node_id)
        self.logger.info("peer disconnected", peer=node_id[:12])

    def _close_peer(self, node_id: NodeID) -> None:
        conn = self._peer_conns.pop(node_id, None)
        if conn is not None:
            conn.close()
        self._peer_queues.pop(node_id, None)
        for t in self._peer_tasks.pop(node_id, []):
            if not t.done() and t is not asyncio.current_task():
                t.cancel()
        self._tasks = [t for t in self._tasks if not t.done()]

    # -- outbound routing (reference: router.go routeChannel) --

    async def _route_channel_out(self, ch: Channel) -> None:
        while True:
            envelope = await ch.out_queue.get()
            try:
                payload = ch.descriptor.encode(envelope.message)
            except Exception as e:
                self.logger.error(
                    "failed to encode outbound message", ch=ch.id, err=str(e)
                )
                continue
            if envelope.broadcast:
                targets = list(self._peer_queues.keys())
            elif envelope.to:
                targets = [envelope.to]
            else:
                self.logger.error("outbound envelope has no destination")
                continue
            for node_id in targets:
                q = self._peer_queues.get(node_id)
                if q is None:
                    continue
                try:
                    q.put_nowait((ch.id, payload))
                except asyncio.QueueFull:
                    self.logger.debug(
                        "peer queue full; dropping message",
                        peer=node_id[:12], ch=ch.id,
                    )

    async def _route_channel_errors(self, ch: Channel) -> None:
        while True:
            peer_error = await ch.error_queue.get()
            self.peer_manager.errored(peer_error.node_id, peer_error.err)

    async def _evict_loop(self) -> None:
        """reference: router.go evictPeers."""
        while True:
            node_id = await self.peer_manager.evict_next()
            self._peer_down(node_id)
