"""Router — the p2p message hub.

reference: internal/p2p/router.go (design comment :108-152). Reactors open
typed channels; the router dials/accepts peers via the transport, runs one
send and one receive task per peer, demuxes inbound messages by channel ID
into reactor queues, and routes outbound envelopes (unicast or broadcast)
onto per-peer queues. PeerManager decides who to dial and who to evict.

The per-peer send path carries the reference MConnection's features
(conn/connection.go): per-channel queues drained by priority (votes
preempt block parts), token-bucket send/recv rate limiting
(:45-46 default rates), and ping/pong keepalive with an any-traffic
liveness deadline. They live here at the router layer rather than
inside a TCP framing class so every transport (memory included) gets
identical semantics — one scheduler, not one per transport.
"""

from __future__ import annotations

import asyncio
import time as _time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..crypto.keys import PrivKey
from ..libs.log import get_logger
from ..libs.service import Service
from .channel import Channel
from .metrics import P2PMetrics
from .peermanager import AlreadyConnectedError, PeerManager
from .transport import Connection, Transport
from .types import ChannelDescriptor, Envelope, NodeID, NodeInfo

__all__ = ["Router", "RouterOptions", "PING_CHANNEL_ID"]

# Reserved keepalive channel, handled by the router itself
# (reference: conn/connection.go channelTypePing/Pong packets).
PING_CHANNEL_ID = 0xFF
_PING = b"\x01"
_PONG = b"\x02"


class RouterOptions:
    def __init__(
        self,
        handshake_timeout: float = 20.0,
        dial_timeout: float = 5.0,
        peer_queue_size: int = 128,
        num_concurrent_dials: int = 8,
        send_rate: int = 5_120_000,  # bytes/s; reference default 500 KB/s
        recv_rate: int = 5_120_000,
        ping_interval: float = 30.0,
        pong_timeout: float = 15.0,
        max_incoming_per_ip: int = 100,  # attempts per tracking window
        incoming_window: float = 10.0,
    ) -> None:
        self.handshake_timeout = handshake_timeout
        self.dial_timeout = dial_timeout
        self.peer_queue_size = peer_queue_size
        self.num_concurrent_dials = num_concurrent_dials
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self.max_incoming_per_ip = max_incoming_per_ip
        self.incoming_window = incoming_window


class _RateLimiter:
    """Token bucket (reference: internal/libs/flowrate as used by
    conn/connection.go): await permission to move n bytes."""

    def __init__(self, rate: int) -> None:
        self.rate = rate
        self._tokens = float(rate)  # one-second burst
        self._last = _time.monotonic()

    async def wait(self, n: int) -> None:
        if self.rate <= 0:
            return
        now = _time.monotonic()
        self._tokens = min(
            self.rate, self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        self._tokens -= n
        if self._tokens < 0:
            await asyncio.sleep(-self._tokens / self.rate)


class _PeerSendQueue:
    """Per-channel FIFO queues drained highest-priority-first
    (reference: conn/connection.go sendRoutine + channel priorities).
    Bounded per channel by the descriptor's send_queue_capacity; a full
    channel drops the message (never blocks other channels)."""

    def __init__(self, default_capacity: int = 64) -> None:
        # channel_id -> (priority, capacity, deque)
        self._queues: Dict[int, Tuple[int, int, Deque[bytes]]] = {}
        self._ready = asyncio.Event()
        self._default_capacity = default_capacity
        self._pong_queued = False

    def register(self, descriptor: ChannelDescriptor) -> None:
        old = self._queues.get(descriptor.channel_id)
        self._queues[descriptor.channel_id] = (
            descriptor.priority,
            descriptor.send_queue_capacity,
            old[2] if old is not None else deque(),
        )

    def put(self, channel_id: int, payload: bytes) -> bool:
        entry = self._queues.get(channel_id)
        if entry is None:
            # late-opened or router-internal channel: default slot
            entry = (1, self._default_capacity, deque())
            self._queues[channel_id] = entry
        priority, capacity, q = entry
        if len(q) >= capacity:
            return False
        q.append(payload)
        self._ready.set()
        return True

    def put_keepalive(self, payload: bytes) -> None:
        """Ping/pong traffic: max priority. Pongs coalesce — at most ONE
        pending pong regardless of inbound ping rate (reference:
        conn/connection.go's size-1 pong channel; otherwise a peer
        streaming pings without reading grows this queue unboundedly)."""
        if payload == _PONG:
            if self._pong_queued:
                return
            self._pong_queued = True
        entry = self._queues.get(PING_CHANNEL_ID)
        if entry is None:
            entry = (1 << 30, 1 << 30, deque())
            self._queues[PING_CHANNEL_ID] = entry
        entry[2].append(payload)
        self._ready.set()

    async def get(self) -> Tuple[int, bytes]:
        while True:
            best = None
            for cid, (priority, _cap, q) in self._queues.items():
                if q and (best is None or priority > best[0]):
                    best = (priority, cid, q)
            if best is not None:
                _, cid, q = best
                payload = q.popleft()
                if cid == PING_CHANNEL_ID and payload == _PONG:
                    self._pong_queued = False
                if not any(
                    qq for _p, _c, qq in self._queues.values() if qq
                ):
                    self._ready.clear()
                return cid, payload
            self._ready.clear()
            await self._ready.wait()


class Router(Service):
    def __init__(
        self,
        node_info: NodeInfo,
        priv_key: PrivKey,
        peer_manager: PeerManager,
        transport: Transport,
        listen_addr: str = "",
        options: Optional[RouterOptions] = None,
        metrics: Optional[P2PMetrics] = None,
    ) -> None:
        super().__init__(name="router", logger=get_logger("p2p.router"))
        # reference: internal/p2p/metrics.go, threaded per node
        self.metrics = metrics if metrics is not None else P2PMetrics()
        self.node_info = node_info
        self.priv_key = priv_key
        self.peer_manager = peer_manager
        self.transport = transport
        self.listen_addr = listen_addr
        self.opts = options or RouterOptions()
        self._channels: Dict[int, Channel] = {}
        self._peer_queues: Dict[NodeID, _PeerSendQueue] = {}
        self._peer_conns: Dict[NodeID, Connection] = {}
        self._peer_tasks: Dict[NodeID, list] = {}
        self._peer_last_recv: Dict[NodeID, float] = {}
        # per-IP connection-attempt tracking
        # (reference: internal/p2p/conn_tracker.go)
        self._conn_tracker: Dict[str, Deque[float]] = {}
        # last crossover replacement per peer (churn rate limit)
        self._last_replacement: Dict[NodeID, float] = {}

    # -- reactor API --

    def open_channel(self, descriptor: ChannelDescriptor) -> Channel:
        """reference: router.go OpenChannel."""
        if descriptor.channel_id in self._channels:
            raise ValueError(
                f"channel {descriptor.channel_id} already open"
            )
        if descriptor.channel_id == PING_CHANNEL_ID:
            raise ValueError(
                f"channel {PING_CHANNEL_ID:#x} is reserved for keepalive"
            )
        ch = Channel(descriptor)
        self._channels[descriptor.channel_id] = ch
        # advertise the channel in our NodeInfo
        if descriptor.channel_id not in self.node_info.channels:
            self.node_info.channels += bytes([descriptor.channel_id])
        # register on queues of peers that connected before this channel
        # opened, so its priority/capacity take effect
        for q in self._peer_queues.values():
            q.register(descriptor)
        self.spawn(self._route_channel_out(ch), f"ch{ch.id}-out")
        self.spawn(self._route_channel_errors(ch), f"ch{ch.id}-err")
        return ch

    def peer_ids(self):
        return list(self._peer_conns.keys())

    # -- lifecycle --

    async def on_start(self) -> None:
        if self.listen_addr:
            await self.transport.listen(self.listen_addr)
        # accept always runs: memory transports accept without listening
        self.spawn(self._accept_loop(), "accept")
        # ONE dispatcher; per-dial concurrency is bounded by the
        # semaphore inside _dial_loop (spawning the loop N times would
        # square the configured dial bound)
        self.spawn(self._dial_loop(), "dial")
        self.spawn(self._evict_loop(), "evict")

    async def on_stop(self) -> None:
        for node_id in list(self._peer_conns):
            self._close_peer(node_id)
        self.peer_manager.flush()  # write any debounced address book state
        await self.transport.close()

    # -- dialing / accepting (reference: router.go dialPeers/acceptPeers) --

    async def _dial_loop(self) -> None:
        # dials run concurrently (bounded): a slow dial or handshake
        # must not head-of-line-block every other candidate
        # (reference: router.go dialPeers spawns per-candidate
        # goroutines under a capacity limit)
        sem = asyncio.Semaphore(self.opts.num_concurrent_dials)
        while True:
            # acquire the slot BEFORE taking a dial reservation:
            # dial_next marks the peer dialing, and a reservation that
            # sits queued behind a full semaphore would make accepted()
            # crossover-reject healthy inbounds for a dial that hasn't
            # even started
            await sem.acquire()
            node_id, host, port = await self.peer_manager.dial_next()
            self.spawn(
                self._dial_one(node_id, host, port, sem),
                f"dial-{node_id[:8]}",
            )

    async def _dial_one(
        self, node_id, host: str, port: int, sem: asyncio.Semaphore
    ) -> None:
        try:
            try:
                conn = await asyncio.wait_for(
                    self.transport.dial(host, port),
                    timeout=self.opts.dial_timeout,
                )
            except Exception as e:
                self.logger.debug(
                    "failed to dial peer", peer=node_id, err=str(e)
                )
                self.peer_manager.dial_failed(node_id)
                return
            try:
                peer_info = await self._handshake(conn)
                if peer_info.node_id != node_id:
                    raise ConnectionError(
                        f"expected {node_id}, got {peer_info.node_id}"
                    )
            except Exception as e:
                self.logger.info(
                    "peer handshake failed", peer=node_id, err=str(e)
                )
                conn.close()
                self.peer_manager.dial_failed(node_id)
                return
            try:
                self.peer_manager.dialed(node_id)
            except AlreadyConnectedError:
                # the peer's connection registered while we dialed —
                # the crossover resolved onto it. Drop this dial
                # WITHOUT the failure penalty: the peer is healthy and
                # connected, a score dock would skew eviction ordering
                conn.close()
                self.peer_manager.dial_abandoned(node_id)
                return
            except Exception as e:
                self.logger.info(
                    "dial rejected", peer=node_id, err=str(e)
                )
                conn.close()
                self.peer_manager.dial_failed(node_id)
                return
            self._start_peer(peer_info.node_id, conn)
        finally:
            sem.release()

    async def _accept_loop(self) -> None:
        while True:
            conn = await self.transport.accept()
            if not self._track_incoming(conn.remote_addr):
                self.logger.info(
                    "rejecting connection: too many attempts from IP",
                    addr=conn.remote_addr,
                )
                conn.close()
                continue
            self.spawn(self._accept_one(conn), "accept-one")

    def _track_incoming(self, remote_addr: str) -> bool:
        """Per-IP accept rate limiting
        (reference: internal/p2p/conn_tracker.go)."""
        ip = remote_addr.rsplit(":", 1)[0]
        now = _time.monotonic()
        window = self._conn_tracker.setdefault(ip, deque())
        while window and now - window[0] > self.opts.incoming_window:
            window.popleft()
        if len(self._conn_tracker) > 1024:
            # sweep drained windows so the tracker can't grow one entry
            # per distinct source IP ever seen
            for tracked_ip in list(self._conn_tracker):
                w = self._conn_tracker[tracked_ip]
                while w and now - w[0] > self.opts.incoming_window:
                    w.popleft()
                if not w and tracked_ip != ip:
                    del self._conn_tracker[tracked_ip]
        if len(window) >= self.opts.max_incoming_per_ip:
            return False
        window.append(now)
        return True

    async def _accept_one(self, conn: Connection) -> None:
        try:
            peer_info = await self._handshake(conn)
        except Exception as e:
            self.logger.debug("inbound handshake failed", err=str(e))
            conn.close()
            return
        nid = peer_info.node_id
        try:
            self.peer_manager.accepted(nid)
        except AlreadyConnectedError:
            now = _time.monotonic()
            if (
                self.node_info.node_id > nid
                and self.peer_manager.connection_inbound(nid) is False
                and now - self._last_replacement.get(nid, -1e9) > 30.0
            ):
                # dial/accept crossover, higher-ID side with its own
                # outbound already registered: the CANONICAL connection
                # is the lower-ID peer's outbound — this inbound.
                # Replace ours (see peermanager.CrossoverRejectError).
                # Only an existing OUTBOUND is replaced, at most once
                # per peer per 30s: a duplicate inbound must not let a
                # peer churn our reactor state in a loop.
                self._last_replacement[nid] = now
                self.logger.info(
                    "crossover: replacing outbound with canonical "
                    "inbound", peer=nid[:12],
                )
                self._peer_down(nid)
                try:
                    self.peer_manager.accepted(nid)
                except Exception as e:
                    self.logger.debug(
                        "crossover replacement failed", err=str(e)
                    )
                    conn.close()
                    return
            else:
                conn.close()
                return
        except Exception as e:
            self.logger.debug("inbound rejected", err=str(e))
            conn.close()
            return
        # record the peer's self-reported listen address so PEX can
        # advertise inbound peers too (reference: the handshake's
        # NodeInfo.ListenAddr feeding the address book)
        if peer_info.listen_addr:
            try:
                self.peer_manager.add(
                    f"{peer_info.node_id}@{peer_info.listen_addr}"
                )
            except ValueError:
                pass  # unparseable self-report: ignore
        self._start_peer(peer_info.node_id, conn)

    async def _handshake(self, conn: Connection) -> NodeInfo:
        peer_info, _peer_pub = await asyncio.wait_for(
            conn.handshake(self.node_info, self.priv_key),
            timeout=self.opts.handshake_timeout,
        )
        peer_info.validate_basic()
        if peer_info.node_id == self.node_info.node_id:
            raise ConnectionError("rejecting connection from self")
        self.node_info.compatible_with(peer_info)
        return peer_info

    # -- per-peer routines (reference: router.go routePeer) --

    def _start_peer(self, node_id: NodeID, conn: Connection) -> None:
        if node_id in self._peer_conns:
            # duplicate connection: keep the existing one. No
            # disconnected() — the live peer's state must not be torn
            # down (reactors would drop peer state while its connection
            # keeps delivering).
            conn.close()
            return
        self._peer_conns[node_id] = conn
        q = _PeerSendQueue(default_capacity=self.opts.peer_queue_size)
        for ch in self._channels.values():
            q.register(ch.descriptor)
        self._peer_queues[node_id] = q
        self._peer_last_recv[node_id] = _time.monotonic()
        send_t = self.spawn(self._send_peer(node_id, conn, q), f"send-{node_id[:8]}")
        recv_t = self.spawn(self._recv_peer(node_id, conn), f"recv-{node_id[:8]}")
        ping_t = self.spawn(self._ping_peer(node_id, q), f"ping-{node_id[:8]}")
        self._peer_tasks[node_id] = [send_t, recv_t, ping_t]
        self.metrics.peers.set(len(self._peer_conns))
        self.peer_manager.ready(node_id)
        self.logger.info("peer connected", peer=node_id[:12], addr=conn.remote_addr)

    async def _send_peer(
        self, node_id: NodeID, conn: Connection, queue: _PeerSendQueue
    ) -> None:
        limiter = _RateLimiter(self.opts.send_rate)
        while True:
            channel_id, payload = await queue.get()
            await limiter.wait(len(payload))
            self.metrics.bytes_sent.inc(len(payload), ch=channel_id)
            try:
                await conn.send(channel_id, payload)
            except asyncio.CancelledError:
                raise
            except ValueError as e:
                # our own oversized/bad payload: drop it, keep the peer
                self.logger.error(
                    "dropping unsendable message", ch=channel_id, err=str(e)
                )
            except Exception:
                # any transport failure means the connection is done; it
                # must never escape into Service fail-fast and kill the
                # whole router (single-peer failure ≠ node failure)
                self._peer_down(node_id)
                return

    async def _ping_peer(self, node_id: NodeID, queue: _PeerSendQueue) -> None:
        """Keepalive: ping on the reserved channel; ANY received traffic
        counts as liveness (reference: conn/connection.go pingRoutine +
        recv deadline)."""
        interval = self.opts.ping_interval
        if interval <= 0:
            return
        while True:
            await asyncio.sleep(interval)
            last = self._peer_last_recv.get(node_id)
            if last is None:
                return
            idle = _time.monotonic() - last
            if idle > interval + self.opts.pong_timeout:
                self.logger.info(
                    "peer unresponsive; disconnecting",
                    peer=node_id[:12], idle=round(idle, 1),
                )
                self._peer_down(node_id)
                return
            if idle > interval / 2:
                queue.put_keepalive(_PING)

    async def _recv_peer(self, node_id: NodeID, conn: Connection) -> None:
        limiter = _RateLimiter(self.opts.recv_rate)
        try:
            while True:
                channel_id, payload = await conn.receive()
                self._peer_last_recv[node_id] = _time.monotonic()
                self.metrics.bytes_recv.inc(len(payload), ch=channel_id)
                await limiter.wait(len(payload))
                if channel_id == PING_CHANNEL_ID:
                    if payload == _PING:
                        q = self._peer_queues.get(node_id)
                        if q is not None:
                            q.put_keepalive(_PONG)
                    continue  # pong needs no action: any traffic is liveness
                ch = self._channels.get(channel_id)
                if ch is None:
                    continue  # unknown channel: drop
                try:
                    msg = ch.descriptor.decode(payload)
                except Exception as e:
                    self.logger.info(
                        "peer sent invalid message; evicting",
                        peer=node_id[:12], ch=channel_id, err=str(e),
                    )
                    self.peer_manager.errored(node_id, f"bad message: {e}")
                    return
                if not ch.deliver(
                    Envelope(message=msg, from_peer=node_id)
                ):
                    self.logger.debug(
                        "reactor queue full; dropping message",
                        ch=channel_id,
                    )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # tampered AEAD frames (InvalidTag), oversized frames, resets —
            # all are peer-connection failures, not router failures
            self.logger.debug(
                "peer receive failed", peer=node_id[:12], err=str(e)
            )
            self._peer_down(node_id)

    def _peer_down(self, node_id: NodeID) -> None:
        if node_id not in self._peer_conns:
            return
        self._close_peer(node_id)
        self.peer_manager.disconnected(node_id)
        self.logger.info("peer disconnected", peer=node_id[:12])

    def _close_peer(self, node_id: NodeID) -> None:
        conn = self._peer_conns.pop(node_id, None)
        if conn is not None:
            conn.close()
        self._peer_queues.pop(node_id, None)
        self._peer_last_recv.pop(node_id, None)
        self.metrics.peers.set(len(self._peer_conns))
        for t in self._peer_tasks.pop(node_id, []):
            if not t.done() and t is not asyncio.current_task():
                t.cancel()

    # -- outbound routing (reference: router.go routeChannel) --

    async def _route_channel_out(self, ch: Channel) -> None:
        while True:
            envelope = await ch.out_queue.get()
            try:
                payload = ch.descriptor.encode(envelope.message)
            except Exception as e:
                self.logger.error(
                    "failed to encode outbound message", ch=ch.id, err=str(e)
                )
                continue
            if envelope.broadcast:
                targets = list(self._peer_queues.keys())
            elif envelope.to:
                targets = [envelope.to]
            else:
                self.logger.error("outbound envelope has no destination")
                continue
            for node_id in targets:
                q = self._peer_queues.get(node_id)
                if q is None:
                    continue
                if not q.put(ch.id, payload):
                    self.logger.debug(
                        "peer channel queue full; dropping message",
                        peer=node_id[:12], ch=ch.id,
                    )

    async def _route_channel_errors(self, ch: Channel) -> None:
        while True:
            peer_error = await ch.error_queue.get()
            self.peer_manager.errored(peer_error.node_id, peer_error.err)

    async def _evict_loop(self) -> None:
        """reference: router.go evictPeers."""
        while True:
            node_id = await self.peer_manager.evict_next()
            self._peer_down(node_id)
