"""Router — the p2p message hub.

reference: internal/p2p/router.go (design comment :108-152). Reactors open
typed channels; the router dials/accepts peers via the transport, runs one
send and one receive task per peer, demuxes inbound messages by channel ID
into reactor queues, and routes outbound envelopes (unicast or broadcast)
onto per-peer queues. PeerManager decides who to dial and who to evict.

The per-peer send path carries the reference MConnection's features
(conn/connection.go): per-channel queues drained by priority (votes
preempt block parts), token-bucket send/recv rate limiting
(:45-46 default rates), and ping/pong keepalive with an any-traffic
liveness deadline. They live here at the router layer rather than
inside a TCP framing class so every transport (memory included) gets
identical semantics — one scheduler, not one per transport.
"""

from __future__ import annotations

import asyncio
import time as _time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..crypto import faults
from ..crypto.keys import PrivKey
from ..libs.log import get_logger
from ..libs.service import Service
from .channel import Channel
from .metrics import P2PMetrics
from .peermanager import AlreadyConnectedError, PeerManager
from .transport import Connection, Transport
from .types import ChannelDescriptor, Envelope, NodeID, NodeInfo

__all__ = ["Router", "RouterOptions", "PING_CHANNEL_ID"]

# Reserved keepalive channel, handled by the router itself
# (reference: conn/connection.go channelTypePing/Pong packets).
PING_CHANNEL_ID = 0xFF
_PING = b"\x01"
_PONG = b"\x02"
# goodbye control frame: 0x03 + utf-8 reason. Sent best-effort before a
# LOCALLY-decided disconnect (eviction, shed, shutdown) so the other
# side's logs/metrics carry the reason instead of a bare reset — a shed
# slow peer used to look identical to a crashed one from the far side.
_BYE = b"\x03"

# the FIXED disconnect-reason vocabulary. Metrics labels only ever come
# from this set (a remote-reported reason outside it becomes "other"),
# so a hostile peer cannot mint label cardinality through BYE frames.
_PEER_REASONS = frozenset(
    {
        "misbehavior",  # reactor/decoder reported bad messages
        "slow_peer",  # send queues shed past the slow-peer threshold
        "capacity",  # evicted to make room (over max_connected)
        "evicted",  # eviction with no recorded reason
        "unresponsive",  # keepalive deadline passed with no traffic
        "send_error",  # transport send failed mid-write
        "recv_error",  # transport receive failed / connection lost
        "crossover",  # replaced by the canonical crossover connection
        "shutdown",  # local node stopping
        "other",
    }
)


class RouterOptions:
    def __init__(
        self,
        handshake_timeout: float = 20.0,
        dial_timeout: float = 5.0,
        peer_queue_size: int = 128,
        num_concurrent_dials: int = 8,
        send_rate: int = 5_120_000,  # bytes/s; reference default 500 KB/s
        recv_rate: int = 5_120_000,
        ping_interval: float = 30.0,
        pong_timeout: float = 15.0,
        max_incoming_per_ip: int = 100,  # attempts per tracking window
        incoming_window: float = 10.0,
        slow_peer_drop_threshold: int = 64,  # queue sheds per window...
        slow_peer_window_s: float = 10.0,  # ...before the peer is shed
        slow_peer_ban_s: float = 30.0,  # sit-out window after a shed
    ) -> None:
        self.handshake_timeout = handshake_timeout
        self.dial_timeout = dial_timeout
        self.peer_queue_size = peer_queue_size
        self.num_concurrent_dials = num_concurrent_dials
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self.max_incoming_per_ip = max_incoming_per_ip
        self.incoming_window = incoming_window
        self.slow_peer_drop_threshold = slow_peer_drop_threshold
        self.slow_peer_window_s = slow_peer_window_s
        self.slow_peer_ban_s = slow_peer_ban_s


def _peer_net_labels(peer_info: NodeInfo) -> tuple:
    """The labels TM_TPU_PARTITION members / p2p rule filters match a
    PEER against: moniker + node ID (and the self-reported listen
    host, the same identity the memory transport dials)."""
    host = (
        peer_info.listen_addr.rsplit(":", 1)[0]
        if peer_info.listen_addr
        else ""
    )
    return tuple(
        x for x in (peer_info.moniker, peer_info.node_id, host) if x
    )


class _RateLimiter:
    """Token bucket (reference: internal/libs/flowrate as used by
    conn/connection.go): await permission to move n bytes."""

    def __init__(self, rate: int) -> None:
        self.rate = rate
        self._tokens = float(rate)  # one-second burst
        self._last = _time.monotonic()

    async def wait(self, n: int) -> None:
        if self.rate <= 0:
            return
        now = _time.monotonic()
        self._tokens = min(
            self.rate, self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        self._tokens -= n
        if self._tokens < 0:
            await asyncio.sleep(-self._tokens / self.rate)


class _PeerSendQueue:
    """Per-channel FIFO queues drained highest-priority-first
    (reference: conn/connection.go sendRoutine + channel priorities).
    Bounded per channel by the descriptor's send_queue_capacity; a full
    channel drops the message (never blocks other channels)."""

    def __init__(self, default_capacity: int = 64) -> None:
        # channel_id -> (priority, capacity, deque)
        self._queues: Dict[int, Tuple[int, int, Deque[bytes]]] = {}
        self._ready = asyncio.Event()
        self._default_capacity = default_capacity
        self._pong_queued = False

    def register(self, descriptor: ChannelDescriptor) -> None:
        old = self._queues.get(descriptor.channel_id)
        self._queues[descriptor.channel_id] = (
            descriptor.priority,
            descriptor.send_queue_capacity,
            old[2] if old is not None else deque(),
        )

    def put(self, channel_id: int, payload: bytes) -> bool:
        entry = self._queues.get(channel_id)
        if entry is None:
            # late-opened or router-internal channel: default slot
            entry = (1, self._default_capacity, deque())
            self._queues[channel_id] = entry
        priority, capacity, q = entry
        if len(q) >= capacity:
            return False
        q.append(payload)
        self._ready.set()
        return True

    def put_keepalive(self, payload: bytes) -> None:
        """Ping/pong traffic: max priority. Pongs coalesce — at most ONE
        pending pong regardless of inbound ping rate (reference:
        conn/connection.go's size-1 pong channel; otherwise a peer
        streaming pings without reading grows this queue unboundedly)."""
        if payload == _PONG:
            if self._pong_queued:
                return
            self._pong_queued = True
        entry = self._queues.get(PING_CHANNEL_ID)
        if entry is None:
            entry = (1 << 30, 1 << 30, deque())
            self._queues[PING_CHANNEL_ID] = entry
        entry[2].append(payload)
        self._ready.set()

    def pending(self) -> bool:
        """Any frame queued on any channel? (The reorder fault only
        parks a frame when a successor is actually waiting to swap
        with — holding the LAST frame of a burst would turn reorder
        into a drop.)"""
        return any(q for _p, _c, q in self._queues.values())

    async def get(self) -> Tuple[int, bytes]:
        while True:
            best = None
            for cid, (priority, _cap, q) in self._queues.items():
                if q and (best is None or priority > best[0]):
                    best = (priority, cid, q)
            if best is not None:
                _, cid, q = best
                payload = q.popleft()
                if cid == PING_CHANNEL_ID and payload == _PONG:
                    self._pong_queued = False
                if not any(
                    qq for _p, _c, qq in self._queues.values() if qq
                ):
                    self._ready.clear()
                return cid, payload
            self._ready.clear()
            await self._ready.wait()


class Router(Service):
    def __init__(
        self,
        node_info: NodeInfo,
        priv_key: PrivKey,
        peer_manager: PeerManager,
        transport: Transport,
        listen_addr: str = "",
        options: Optional[RouterOptions] = None,
        metrics: Optional[P2PMetrics] = None,
    ) -> None:
        super().__init__(name="router", logger=get_logger("p2p.router"))
        # reference: internal/p2p/metrics.go, threaded per node
        self.metrics = metrics if metrics is not None else P2PMetrics()
        self.node_info = node_info
        self.priv_key = priv_key
        self.peer_manager = peer_manager
        self.transport = transport
        self.listen_addr = listen_addr
        self.opts = options or RouterOptions()
        self._channels: Dict[int, Channel] = {}
        self._peer_queues: Dict[NodeID, _PeerSendQueue] = {}
        self._peer_conns: Dict[NodeID, Connection] = {}
        self._peer_tasks: Dict[NodeID, list] = {}
        self._peer_last_recv: Dict[NodeID, float] = {}
        # net-fault-plane identities: what TM_TPU_PARTITION members and
        # p2p.* rule src=/dst= filters match against
        self._net_labels = _peer_net_labels(node_info)
        # the transport consults the dial fault point with our labels
        self.transport.local_labels = self._net_labels
        # per-peer labels (moniker, node_id), learned at handshake;
        # entries removed in _close_peer
        self._peer_labels: Dict[NodeID, tuple] = {}
        # remote-reported disconnect reasons (BYE frames), consumed by
        # the _peer_down that follows the peer's close
        self._peer_bye: Dict[NodeID, str] = {}
        # slow-peer detection: recent send-queue drop instants per peer
        # (each deque pruned to slow_peer_window_s; removed on close)
        self._send_drops: Dict[NodeID, Deque[float]] = {}
        # per-IP connection-attempt tracking
        # (reference: internal/p2p/conn_tracker.go)
        self._conn_tracker: Dict[str, Deque[float]] = {}
        # last crossover replacement per peer (churn rate limit)
        self._last_replacement: Dict[NodeID, float] = {}

    # -- reactor API --

    def open_channel(self, descriptor: ChannelDescriptor) -> Channel:
        """reference: router.go OpenChannel."""
        if descriptor.channel_id in self._channels:
            raise ValueError(
                f"channel {descriptor.channel_id} already open"
            )
        if descriptor.channel_id == PING_CHANNEL_ID:
            raise ValueError(
                f"channel {PING_CHANNEL_ID:#x} is reserved for keepalive"
            )
        ch = Channel(descriptor)
        self._channels[descriptor.channel_id] = ch
        # advertise the channel in our NodeInfo
        if descriptor.channel_id not in self.node_info.channels:
            self.node_info.channels += bytes([descriptor.channel_id])
        # register on queues of peers that connected before this channel
        # opened, so its priority/capacity take effect
        for q in self._peer_queues.values():
            q.register(descriptor)
        self.spawn(self._route_channel_out(ch), f"ch{ch.id}-out")
        self.spawn(self._route_channel_errors(ch), f"ch{ch.id}-err")
        return ch

    def peer_ids(self):
        return list(self._peer_conns.keys())

    # -- lifecycle --

    async def on_start(self) -> None:
        if self.listen_addr:
            await self.transport.listen(self.listen_addr)
        # accept always runs: memory transports accept without listening
        self.spawn(self._accept_loop(), "accept")
        # ONE dispatcher; per-dial concurrency is bounded by the
        # semaphore inside _dial_loop (spawning the loop N times would
        # square the configured dial bound)
        self.spawn(self._dial_loop(), "dial")
        self.spawn(self._evict_loop(), "evict")

    async def on_stop(self) -> None:
        # announce the shutdown to every peer and AWAIT the goodbyes
        # here — a task spawned mid-stop can be cancelled before its
        # first tick (on_stop's remaining awaits never yield), which
        # would both swallow the frame and leak the conn. Bounded:
        # 0.5 s per frame, sent concurrently.
        if self._peer_conns:
            await asyncio.gather(
                *(
                    self._send_bye(conn, "shutdown")
                    for conn in self._peer_conns.values()
                ),
                return_exceptions=True,
            )
        for node_id in list(self._peer_conns):
            self._close_peer(node_id)
        self.peer_manager.flush()  # write any debounced address book state
        await self.transport.close()

    # -- dialing / accepting (reference: router.go dialPeers/acceptPeers) --

    async def _dial_loop(self) -> None:
        # dials run concurrently (bounded): a slow dial or handshake
        # must not head-of-line-block every other candidate
        # (reference: router.go dialPeers spawns per-candidate
        # goroutines under a capacity limit)
        sem = asyncio.Semaphore(self.opts.num_concurrent_dials)
        while True:
            # acquire the slot BEFORE taking a dial reservation:
            # dial_next marks the peer dialing, and a reservation that
            # sits queued behind a full semaphore would make accepted()
            # crossover-reject healthy inbounds for a dial that hasn't
            # even started
            await sem.acquire()
            node_id, host, port = await self.peer_manager.dial_next()
            self.spawn(
                self._dial_one(node_id, host, port, sem),
                f"dial-{node_id[:8]}",
            )

    async def _dial_one(
        self, node_id, host: str, port: int, sem: asyncio.Semaphore
    ) -> None:
        try:
            try:
                conn = await asyncio.wait_for(
                    self.transport.dial(host, port),
                    timeout=self.opts.dial_timeout,
                )
            except Exception as e:
                self.logger.debug(
                    "failed to dial peer", peer=node_id, err=str(e)
                )
                self.peer_manager.dial_failed(node_id)
                return
            try:
                peer_info = await self._handshake(conn)
                if peer_info.node_id != node_id:
                    raise ConnectionError(
                        f"expected {node_id}, got {peer_info.node_id}"
                    )
                if faults.net_armed() and faults.partition_blocked(
                    self._net_labels, _peer_net_labels(peer_info)
                ):
                    # the moniker learned at handshake put the peer on
                    # the far side of the partition (host-level labels
                    # alone — TCP nets — can't tell nodes apart)
                    raise ConnectionError("injected partition")
            except Exception as e:
                self.logger.info(
                    "peer handshake failed", peer=node_id, err=str(e)
                )
                conn.close()
                self.peer_manager.dial_failed(node_id)
                return
            try:
                self.peer_manager.dialed(node_id)
            except AlreadyConnectedError:
                # the peer's connection registered while we dialed —
                # the crossover resolved onto it. Drop this dial
                # WITHOUT the failure penalty: the peer is healthy and
                # connected, a score dock would skew eviction ordering
                conn.close()
                self.peer_manager.dial_abandoned(node_id)
                return
            except Exception as e:
                self.logger.info(
                    "dial rejected", peer=node_id, err=str(e)
                )
                conn.close()
                self.peer_manager.dial_failed(node_id)
                return
            self._start_peer(peer_info, conn)
        finally:
            sem.release()

    async def _accept_loop(self) -> None:
        while True:
            conn = await self.transport.accept()
            if not self._track_incoming(conn.remote_addr):
                self.logger.info(
                    "rejecting connection: too many attempts from IP",
                    addr=conn.remote_addr,
                )
                conn.close()
                continue
            self.spawn(self._accept_one(conn), "accept-one")

    def _track_incoming(self, remote_addr: str) -> bool:
        """Per-IP accept rate limiting
        (reference: internal/p2p/conn_tracker.go)."""
        ip = remote_addr.rsplit(":", 1)[0]
        now = _time.monotonic()
        window = self._conn_tracker.setdefault(ip, deque())
        while window and now - window[0] > self.opts.incoming_window:
            window.popleft()
        if len(self._conn_tracker) > 1024:
            # sweep drained windows so the tracker can't grow one entry
            # per distinct source IP ever seen
            for tracked_ip in list(self._conn_tracker):
                w = self._conn_tracker[tracked_ip]
                while w and now - w[0] > self.opts.incoming_window:
                    w.popleft()
                if not w and tracked_ip != ip:
                    del self._conn_tracker[tracked_ip]
        if len(window) >= self.opts.max_incoming_per_ip:
            return False
        window.append(now)
        return True

    async def _accept_one(self, conn: Connection) -> None:
        try:
            peer_info = await self._handshake(conn)
        except Exception as e:
            self.logger.debug("inbound handshake failed", err=str(e))
            conn.close()
            return
        if faults.net_armed() and faults.partition_blocked(
            _peer_net_labels(peer_info), self._net_labels
        ):
            self.logger.debug(
                "rejecting inbound: injected partition",
                peer=peer_info.node_id[:12],
            )
            conn.close()
            return
        nid = peer_info.node_id
        try:
            self.peer_manager.accepted(nid)
        except AlreadyConnectedError:
            now = _time.monotonic()
            if (
                self.node_info.node_id > nid
                and self.peer_manager.connection_inbound(nid) is False
                and now - self._last_replacement.get(nid, -1e9) > 30.0
            ):
                # dial/accept crossover, higher-ID side with its own
                # outbound already registered: the CANONICAL connection
                # is the lower-ID peer's outbound — this inbound.
                # Replace ours (see peermanager.CrossoverRejectError).
                # Only an existing OUTBOUND is replaced, at most once
                # per peer per 30s: a duplicate inbound must not let a
                # peer churn our reactor state in a loop.
                self._last_replacement[nid] = now
                self.logger.info(
                    "crossover: replacing outbound with canonical "
                    "inbound", peer=nid[:12],
                )
                self._peer_down(nid, reason="crossover")
                try:
                    self.peer_manager.accepted(nid)
                except Exception as e:
                    self.logger.debug(
                        "crossover replacement failed", err=str(e)
                    )
                    conn.close()
                    return
            else:
                conn.close()
                return
        except Exception as e:
            self.logger.debug("inbound rejected", err=str(e))
            conn.close()
            return
        # record the peer's self-reported listen address so PEX can
        # advertise inbound peers too (reference: the handshake's
        # NodeInfo.ListenAddr feeding the address book)
        if peer_info.listen_addr:
            try:
                self.peer_manager.add(
                    f"{peer_info.node_id}@{peer_info.listen_addr}"
                )
            except ValueError:
                pass  # unparseable self-report: ignore
        self._start_peer(peer_info, conn)

    async def _handshake(self, conn: Connection) -> NodeInfo:
        peer_info, _peer_pub = await asyncio.wait_for(
            conn.handshake(self.node_info, self.priv_key),
            timeout=self.opts.handshake_timeout,
        )
        peer_info.validate_basic()
        if peer_info.node_id == self.node_info.node_id:
            raise ConnectionError("rejecting connection from self")
        self.node_info.compatible_with(peer_info)
        return peer_info

    # -- per-peer routines (reference: router.go routePeer) --

    def _start_peer(self, peer_info: NodeInfo, conn: Connection) -> None:
        node_id = peer_info.node_id
        if not self.is_running:
            # a dial/accept that finished its handshake while stop()
            # was tearing the router down must not spawn fresh peer
            # tasks — they would outlive the cancel sweep and park
            # stop() forever on their queues
            conn.close()
            return
        if node_id in self._peer_conns:
            # duplicate connection: keep the existing one. No
            # disconnected() — the live peer's state must not be torn
            # down (reactors would drop peer state while its connection
            # keeps delivering).
            conn.close()
            return
        self._peer_conns[node_id] = conn
        self._peer_labels[node_id] = _peer_net_labels(peer_info)
        q = _PeerSendQueue(default_capacity=self.opts.peer_queue_size)
        for ch in self._channels.values():
            q.register(ch.descriptor)
        self._peer_queues[node_id] = q
        self._peer_last_recv[node_id] = _time.monotonic()
        send_t = self.spawn(self._send_peer(node_id, conn, q), f"send-{node_id[:8]}")
        recv_t = self.spawn(self._recv_peer(node_id, conn), f"recv-{node_id[:8]}")
        ping_t = self.spawn(self._ping_peer(node_id, q), f"ping-{node_id[:8]}")
        self._peer_tasks[node_id] = [send_t, recv_t, ping_t]
        self.metrics.peers.set(len(self._peer_conns))
        self.peer_manager.ready(node_id)
        self.logger.info("peer connected", peer=node_id[:12], addr=conn.remote_addr)

    def _link_labels(self, point: str, node_id: NodeID):
        labels = self._peer_labels.get(node_id, (node_id,))
        if point == "p2p.send":
            return self._net_labels, labels
        return labels, self._net_labels

    def _partition_cut(self, point: str, node_id: NodeID) -> bool:
        """Is this link cut by the live partition? Counted per frame.
        Callers gate on faults.net_armed()."""
        src, dst = self._link_labels(point, node_id)
        if faults.partition_blocked(src, dst):
            self.metrics.net_faults.inc(point=point, mode="partition")
            return True
        return False

    async def _consult_net_rules(
        self, point: str, node_id: NodeID, channel_id: int
    ):
        """One p2p.send / p2p.recv per-message RULE consult (the
        partition check is separate — on the recv side it must run
        BEFORE the liveness stamp, rules after). Returns
        (drop, extra_copies, reorder) after paying any injected delay.
        Callers gate on faults.net_armed() so the unarmed hot path
        never reaches here."""
        src, dst = self._link_labels(point, node_id)
        plan = faults.net_plan(point, src=src, dst=dst, ch=channel_id)
        if plan is None:
            return False, 0, False
        if plan.delay_s > 0:
            self.metrics.net_faults.inc(point=point, mode="delay")
            await asyncio.sleep(plan.delay_s)
        if plan.drop:
            self.metrics.net_faults.inc(point=point, mode="drop")
            return True, 0, False
        if plan.dup:
            self.metrics.net_faults.inc(point=point, mode="duplicate")
        if plan.reorder:
            self.metrics.net_faults.inc(point=point, mode="reorder")
        return False, plan.dup, plan.reorder

    async def _send_peer(
        self, node_id: NodeID, conn: Connection, queue: _PeerSendQueue
    ) -> None:
        limiter = _RateLimiter(self.opts.send_rate)
        held = None  # reorder fault: message parked behind its successor
        while True:
            channel_id, payload = await queue.get()
            batch = [(channel_id, payload)]
            if faults.net_armed():
                if self._partition_cut("p2p.send", node_id):
                    held = None  # the cut link eats the parked frame too
                    continue
                drop, dup, reorder = await self._consult_net_rules(
                    "p2p.send", node_id, channel_id
                )
                if drop:
                    if held is None:
                        continue
                    # the dropped frame dies but the PARKED one was
                    # only reordered: flush it now, or a dropped
                    # successor at the end of a burst would turn
                    # reorder into a silent drop
                    batch, held = [held], None
                else:
                    if reorder and held is None and queue.pending():
                        # park ONLY when a successor is already queued
                        # — holding the last frame of a burst would
                        # await a successor that never comes
                        # (reorder ≠ drop)
                        held = (channel_id, payload)
                        continue
                    batch += [(channel_id, payload)] * dup
            if held is not None:
                batch.append(held)  # swapped behind its successor
                held = None
            for cid, pl in batch:
                await limiter.wait(len(pl))
                self.metrics.bytes_sent.inc(len(pl), ch=cid)
                try:
                    await conn.send(cid, pl)
                except asyncio.CancelledError:
                    raise
                except ValueError as e:
                    # our own oversized/bad payload: drop it, keep the
                    # peer
                    self.logger.error(
                        "dropping unsendable message", ch=cid, err=str(e)
                    )
                except Exception:
                    # any transport failure means the connection is
                    # done; it must never escape into Service fail-fast
                    # and kill the whole router (single-peer failure ≠
                    # node failure)
                    self._peer_down(node_id, reason="send_error")
                    return

    async def _ping_peer(self, node_id: NodeID, queue: _PeerSendQueue) -> None:
        """Keepalive: ping on the reserved channel; ANY received traffic
        counts as liveness (reference: conn/connection.go pingRoutine +
        recv deadline)."""
        interval = self.opts.ping_interval
        if interval <= 0:
            return
        while True:
            await asyncio.sleep(interval)
            last = self._peer_last_recv.get(node_id)
            if last is None:
                return
            idle = _time.monotonic() - last
            if idle > interval + self.opts.pong_timeout:
                self.logger.info(
                    "peer unresponsive; disconnecting",
                    peer=node_id[:12], idle=round(idle, 1),
                )
                self._peer_down(node_id, reason="unresponsive")
                return
            if idle > interval / 2:
                queue.put_keepalive(_PING)

    def _deliver_inbound(
        self, node_id: NodeID, channel_id: int, payload: bytes
    ) -> bool:
        """Demux one received frame into its reactor queue. Returns
        False when the peer must be dropped (invalid message)."""
        if channel_id == PING_CHANNEL_ID:
            if payload == _PING:
                q = self._peer_queues.get(node_id)
                if q is not None:
                    q.put_keepalive(_PONG)
            elif payload[:1] == _BYE:
                # the peer told us WHY it is about to hang up; stash it
                # so the imminent _peer_down attributes the close.
                # Sanitized against the fixed vocabulary: wire bytes
                # never become a metrics label.
                said = payload[1:64].decode("utf-8", "replace")
                reason = said if said in _PEER_REASONS else "other"
                self._peer_bye[node_id] = f"remote/{reason}"
                self.logger.info(
                    "peer announced disconnect",
                    peer=node_id[:12], reason=reason,
                )
            # pongs need no action: any traffic is liveness
            return True
        ch = self._channels.get(channel_id)
        if ch is None:
            return True  # unknown channel: drop
        try:
            msg = ch.descriptor.decode(payload)
        except Exception as e:
            self.logger.info(
                "peer sent invalid message; evicting",
                peer=node_id[:12], ch=channel_id, err=str(e),
            )
            self.peer_manager.errored(node_id, f"bad message: {e}")
            return False
        if not ch.deliver(Envelope(message=msg, from_peer=node_id)):
            self.logger.debug(
                "reactor queue full; dropping message", ch=channel_id
            )
        return True

    async def _recv_peer(self, node_id: NodeID, conn: Connection) -> None:
        limiter = _RateLimiter(self.opts.recv_rate)
        held = None  # reorder fault: frame parked behind its successor

        def flush_held() -> None:
            # timer-driven flush for a parked frame whose successor
            # never came: reorder delays, it never silently drops.
            # Runs as a loop callback so conn.receive() is never
            # cancelled mid-read (a cancel there loses the racing
            # frame on the memory transport and desyncs the
            # length-prefixed TCP stream). The send side guards with
            # queue.pending() instead; inbound traffic can't be
            # peeked, hence the deadline.
            nonlocal held
            if held is None:
                return
            cid, pl = held
            held = None
            self.metrics.bytes_recv.inc(len(pl), ch=cid)
            self._deliver_inbound(node_id, cid, pl)

        try:
            while True:
                channel_id, payload = await conn.receive()
                # ONLY the partition check runs before the liveness
                # stamp: a fully-cut peer must go stale and trip the
                # keepalive deadline, exactly like a real one. A
                # rule-dropped/held frame still ARRIVED — a lossy link
                # delivers bytes, so it must not fake unresponsiveness
                if faults.net_armed() and self._partition_cut(
                    "p2p.recv", node_id
                ):
                    held = None  # the cut link eats a parked frame too
                    continue
                self._peer_last_recv[node_id] = _time.monotonic()
                batch = [(channel_id, payload)]
                if faults.net_armed():
                    drop, dup, reorder = await self._consult_net_rules(
                        "p2p.recv", node_id, channel_id
                    )
                    if drop:
                        continue  # held (if any) flushes on its timer
                    if reorder and held is None:
                        held = (channel_id, payload)
                        asyncio.get_running_loop().call_later(
                            0.5, flush_held
                        )
                        continue
                    batch += [(channel_id, payload)] * dup
                if held is not None:
                    batch.append(held)
                    held = None
                for cid, pl in batch:
                    self.metrics.bytes_recv.inc(len(pl), ch=cid)
                    await limiter.wait(len(pl))
                    if not self._deliver_inbound(node_id, cid, pl):
                        return
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # tampered AEAD frames (InvalidTag), oversized frames, resets —
            # all are peer-connection failures, not router failures
            self.logger.debug(
                "peer receive failed", peer=node_id[:12], err=str(e)
            )
            self._peer_down(node_id, reason="recv_error")

    def _peer_down(
        self,
        node_id: NodeID,
        reason: str = "other",
        notify: bool = False,
    ) -> None:
        """Tear down a peer. `reason` labels the disconnect metric and
        the log line; a BYE the peer sent first wins the attribution.
        `notify=True` sends the reason to the peer (best-effort) for
        LOCALLY-decided disconnects (evictions, shed, shutdown)."""
        if node_id not in self._peer_conns:
            return
        reason = self._peer_bye.pop(node_id, reason)
        self._close_peer(node_id, bye_reason=reason if notify else None)
        self.metrics.peer_disconnects.inc(reason=reason)
        self.peer_manager.disconnected(node_id)
        self.logger.info(
            "peer disconnected", peer=node_id[:12], reason=reason
        )

    async def _send_bye(self, conn: Connection, reason: str) -> None:
        """Best-effort goodbye frame — bounded so a wedged transport
        can't hold the caller open."""
        try:
            await asyncio.wait_for(
                conn.send(PING_CHANNEL_ID, _BYE + reason.encode()),
                timeout=0.5,
            )
        except Exception:
            pass

    async def _say_bye(self, conn: Connection, reason: str) -> None:
        """Goodbye frame, then close (the eviction path's spawned
        teardown)."""
        try:
            await self._send_bye(conn, reason)
        finally:
            conn.close()

    def _close_peer(
        self, node_id: NodeID, bye_reason: Optional[str] = None
    ) -> None:
        conn = self._peer_conns.pop(node_id, None)
        if conn is not None:
            if bye_reason is not None and self.is_running:
                # eviction path: the loop keeps running, the spawned
                # bye gets its tick. During stop, spawning is unsafe
                # (a task cancelled before its first tick never runs
                # its finally and would leak the conn) — on_stop sends
                # its shutdown byes inline instead.
                self.spawn(
                    self._say_bye(conn, bye_reason),
                    f"bye-{node_id[:8]}",
                )
            else:
                conn.close()
        self._peer_queues.pop(node_id, None)
        self._peer_last_recv.pop(node_id, None)
        self._peer_labels.pop(node_id, None)
        self._peer_bye.pop(node_id, None)
        self._send_drops.pop(node_id, None)
        self.metrics.peers.set(len(self._peer_conns))
        for t in self._peer_tasks.pop(node_id, []):
            if not t.done() and t is not asyncio.current_task():
                t.cancel()

    # -- outbound routing (reference: router.go routeChannel) --

    async def _route_channel_out(self, ch: Channel) -> None:
        while True:
            envelope = await ch.out_queue.get()
            try:
                payload = ch.descriptor.encode(envelope.message)
            except Exception as e:
                self.logger.error(
                    "failed to encode outbound message", ch=ch.id, err=str(e)
                )
                continue
            if envelope.broadcast:
                targets = list(self._peer_queues.keys())
            elif envelope.to:
                targets = [envelope.to]
            else:
                self.logger.error("outbound envelope has no destination")
                continue
            for node_id in targets:
                q = self._peer_queues.get(node_id)
                if q is None:
                    continue
                if not q.put(ch.id, payload):
                    self.logger.debug(
                        "peer channel queue full; dropping message",
                        peer=node_id[:12], ch=ch.id,
                    )
                    self.metrics.send_queue_dropped.inc(ch=ch.id)
                    self._note_send_drop(node_id)

    def _note_send_drop(self, node_id: NodeID) -> None:
        """Slow-peer detection: a peer whose queues shed more than
        `slow_peer_drop_threshold` messages inside
        `slow_peer_window_s` is not consuming — evict it with reason
        `slow_peer` and ban it for the sit-out window rather than
        letting its queues shed forever (bounded memory was already
        guaranteed; bounded USELESS WORK was not)."""
        if node_id not in self._peer_conns:
            return
        now = _time.monotonic()
        window = self._send_drops.setdefault(node_id, deque())
        cutoff = now - self.opts.slow_peer_window_s
        while window and window[0] < cutoff:
            window.popleft()
        window.append(now)
        if len(window) >= self.opts.slow_peer_drop_threshold:
            window.clear()
            self.peer_manager.shed_slow(
                node_id, ban_s=self.opts.slow_peer_ban_s
            )

    async def _route_channel_errors(self, ch: Channel) -> None:
        while True:
            peer_error = await ch.error_queue.get()
            self.peer_manager.errored(peer_error.node_id, peer_error.err)

    async def _evict_loop(self) -> None:
        """reference: router.go evictPeers."""
        while True:
            node_id = await self.peer_manager.evict_next()
            reason = self.peer_manager.evict_reason(node_id) or "evicted"
            self._peer_down(node_id, reason=reason, notify=True)
