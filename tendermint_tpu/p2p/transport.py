"""Transports — how raw (channel_id, bytes) messages move between nodes.

reference: internal/p2p/transport.go (interface), transport_memory.go
(in-process network for tests), transport_mconn.go (TCP + secret conn).

A Connection carries framed (channel_id, payload) messages after a
handshake that exchanges NodeInfo and proves node-key ownership.
"""

from __future__ import annotations

import asyncio
import struct
from abc import ABC, abstractmethod
from typing import Dict, Optional, Tuple

from ..crypto import faults
from ..crypto.keys import PrivKey, PubKey
from ..encoding.proto import decode_varint, encode_varint
from ..libs.log import get_logger
from .conn import SecretConnection
from .types import NodeID, NodeInfo, node_id_from_pubkey

__all__ = [
    "Connection",
    "Transport",
    "MemoryNetwork",
    "MemoryTransport",
    "TCPTransport",
]

MAX_MSG_SIZE = 1 << 22  # 4 MiB


class Connection(ABC):
    """An established peer link (reference: transport.go Connection)."""

    @abstractmethod
    async def handshake(
        self, node_info: NodeInfo, priv_key: PrivKey
    ) -> Tuple[NodeInfo, PubKey]:
        """Exchange NodeInfo; returns (peer_info, peer_pubkey)."""

    @abstractmethod
    async def send(self, channel_id: int, payload: bytes) -> None: ...

    @abstractmethod
    async def receive(self) -> Tuple[int, bytes]: ...

    @abstractmethod
    def close(self) -> None: ...

    @property
    @abstractmethod
    def remote_addr(self) -> str: ...


async def consult_dial_plane(src_labels: tuple, host: str, port: int):
    """The `p2p.dial` fault point, shared by every transport: a `drop`
    rule or a live partition turns the dial into ConnectionError (the
    same failure a dead peer produces, so the dial-backoff machinery
    is exercised, not bypassed), a `delay` rule slows it. Callers gate
    on faults.net_armed() — unarmed dials never reach here."""
    dst = (host, f"{host}:{port}")
    if faults.partition_blocked(src_labels, dst):
        raise ConnectionError(
            f"injected partition: dial to {host}:{port} blocked"
        )
    plan = faults.net_plan("p2p.dial", src=src_labels, dst=dst)
    if plan is not None:
        if plan.delay_s > 0:
            await asyncio.sleep(plan.delay_s)
        if plan.drop:
            raise ConnectionError(
                f"injected dial drop: {host}:{port}"
            )


class Transport(ABC):
    """reference: transport.go Transport."""

    # net-fault-plane identity of the dialing node (moniker, node ID,
    # listen host) — the router stamps this so `p2p.dial` rules and
    # partitions can match the SOURCE side
    local_labels: tuple = ()

    @abstractmethod
    async def listen(self, addr: str) -> None: ...

    @abstractmethod
    async def accept(self) -> Connection: ...

    @abstractmethod
    async def dial(self, host: str, port: int) -> Connection: ...

    @abstractmethod
    async def close(self) -> None: ...


# ---------------------------------------------------------------------------
# Memory transport (tests; reference: transport_memory.go)


class _MemoryConnection(Connection):
    def __init__(self, local_addr: str, remote_addr_: str) -> None:
        self._send_q: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._recv_q: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._local_addr = local_addr
        self._remote_addr = remote_addr_
        self._closed = asyncio.Event()
        self.peer: Optional[_MemoryConnection] = None

    @staticmethod
    def pair(a_addr: str, b_addr: str):
        a = _MemoryConnection(a_addr, b_addr)
        b = _MemoryConnection(b_addr, a_addr)
        a.peer, b.peer = b, a
        b._recv_q, a._recv_q = a._send_q, b._send_q
        return a, b

    async def handshake(self, node_info, priv_key):
        await self._send_q.put(("_handshake", (node_info, priv_key.pub_key())))
        kind, (peer_info, peer_pub) = await self._recv_q.get()
        if kind != "_handshake":
            raise RuntimeError("expected handshake message")
        return peer_info, peer_pub

    async def send(self, channel_id: int, payload: bytes) -> None:
        if self._closed.is_set():
            raise ConnectionError("connection closed")
        await self._send_q.put((channel_id, payload))

    async def receive(self) -> Tuple[int, bytes]:
        get = asyncio.ensure_future(self._recv_q.get())
        closed = asyncio.ensure_future(self._closed.wait())
        try:
            done, _pending = await asyncio.wait(
                {get, closed}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            # also runs when THIS task is cancelled mid-wait: the two
            # inner futures must not outlive the call (they used to
            # leak as destroyed-but-pending Queue.get tasks at loop
            # close)
            for p in (get, closed):
                if not p.done():
                    p.cancel()
        if get in done:
            item = get.result()
            if item == ("_close", None):
                self._closed.set()
                raise ConnectionError("connection closed by peer")
            return item
        raise ConnectionError("connection closed")

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._send_q.put_nowait(("_close", None))
            except asyncio.QueueFull:
                pass
            if self.peer is not None:
                self.peer._closed.set()

    @property
    def remote_addr(self) -> str:
        return self._remote_addr


class MemoryNetwork:
    """Shared fabric connecting MemoryTransports by address
    (reference: transport_memory.go MemoryNetwork)."""

    def __init__(self) -> None:
        self.transports: Dict[str, "MemoryTransport"] = {}

    def register(self, addr: str, transport: "MemoryTransport") -> None:
        self.transports[addr] = transport


class MemoryTransport(Transport):
    def __init__(self, network: MemoryNetwork, addr: str) -> None:
        self.network = network
        self.addr = addr
        self._accept_q: asyncio.Queue = asyncio.Queue()
        network.register(addr, self)

    async def listen(self, addr: str) -> None:
        pass  # registered at construction

    async def accept(self) -> Connection:
        return await self._accept_q.get()

    async def dial(self, host: str, port: int) -> Connection:
        if faults.net_armed():
            await consult_dial_plane(
                self.local_labels
                or (self.addr, self.addr.rsplit(":", 1)[0]),
                host,
                port,
            )
        target = self.network.transports.get(f"{host}:{port}")
        if target is None:
            raise ConnectionError(f"no memory transport at {host}:{port}")
        local, remote = _MemoryConnection.pair(
            self.addr, f"{host}:{port}"
        )
        await target._accept_q.put(remote)
        return local

    async def close(self) -> None:
        self.network.transports.pop(self.addr, None)


# ---------------------------------------------------------------------------
# TCP transport with SecretConnection (reference: transport_mconn.go)


class _TCPConnection(Connection):
    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._secret: Optional[SecretConnection] = None
        peer = writer.get_extra_info("peername") or ("?", 0)
        self._remote = f"{peer[0]}:{peer[1]}"

    async def handshake(self, node_info: NodeInfo, priv_key: PrivKey):
        self._secret = await SecretConnection.handshake(
            self._reader, self._writer, priv_key
        )
        await self._secret.write_frame(node_info.to_proto())
        peer_info = NodeInfo.from_proto(await self._secret.read_frame())
        peer_pub = self._secret.remote_pubkey
        # the node ID must be derived from the authenticated key
        if peer_info.node_id != node_id_from_pubkey(peer_pub):
            raise ConnectionError(
                "peer's node ID does not match its authenticated key"
            )
        return peer_info, peer_pub

    async def send(self, channel_id: int, payload: bytes) -> None:
        if len(payload) > MAX_MSG_SIZE:
            raise ValueError(f"message too large: {len(payload)}")
        self._check_open()
        frame = encode_varint(channel_id) + payload
        await self._secret.write_frame(frame)

    async def receive(self) -> Tuple[int, bytes]:
        self._check_open()
        try:
            frame = await self._secret.read_frame()
        except (asyncio.IncompleteReadError, ConnectionResetError) as e:
            raise ConnectionError(f"connection lost: {e}") from e
        channel_id, off = decode_varint(frame)
        return channel_id, frame[off:]

    def _check_open(self) -> None:
        if self._secret is None:
            raise ConnectionError("handshake not complete")

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass

    @property
    def remote_addr(self) -> str:
        return self._remote


class TCPTransport(Transport):
    def __init__(self) -> None:
        self.logger = get_logger("p2p.tcp")
        self._server: Optional[asyncio.AbstractServer] = None
        self._accept_q: asyncio.Queue = asyncio.Queue(maxsize=64)
        self.listen_port: int = 0

    async def listen(self, addr: str) -> None:
        from .types import parse_node_address

        _nid, host, port = parse_node_address(addr)  # defaults port 26656

        async def on_client(reader, writer):
            try:
                self._accept_q.put_nowait(_TCPConnection(reader, writer))
            except asyncio.QueueFull:
                writer.close()

        self._server = await asyncio.start_server(
            on_client, host, int(port)
        )
        self.listen_port = self._server.sockets[0].getsockname()[1]
        self.logger.info("p2p listening", addr=f"{host}:{self.listen_port}")

    async def accept(self) -> Connection:
        return await self._accept_q.get()

    async def dial(self, host: str, port: int) -> Connection:
        if faults.net_armed():
            await consult_dial_plane(self.local_labels, host, port)
        reader, writer = await asyncio.open_connection(host, port)
        return _TCPConnection(reader, writer)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                # 3.12: wait_closed blocks until every handler connection
                # closes; stragglers shouldn't wedge shutdown
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except Exception:
                pass
