"""Mempool — pending-transaction pool (reference: internal/mempool/)."""

from .cache import LRUTxCache, NopTxCache  # noqa: F401
from .mempool import TxMempool  # noqa: F401
from .types import Mempool, MempoolError, TxInfo, WrappedTx, tx_key  # noqa: F401
