"""Mempool metrics struct (reference: internal/mempool/metrics.go),
per-node when threaded from node assembly — see consensus/metrics.py
for the pattern.
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["MempoolMetrics"]


class MempoolMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.size = r.gauge(
            "mempool", "size", "Number of uncommitted transactions."
        )
        self.failed_txs = r.counter(
            "mempool",
            "failed_txs_total",
            "Transactions rejected by CheckTx.",
        )
        # the ingest-latency baseline the ROADMAP's sharded-CheckTx
        # follow-on will be judged against (mergeable sketch — see
        # docs/metrics.md "Latency sketches"); includes the mempool
        # lock wait, which is the contention signal under load
        self.checktx_seconds = r.sketch(
            "mempool",
            "checktx_seconds",
            "End-to-end CheckTx ingest latency (lock wait + app "
            "round-trip + pool insert).",
        )
        # the lock-wait half of that latency on its own (ISSUE 16
        # satellite): checktx_seconds folds the wait for consensus to
        # release the pool into the total, so a slow ingest p99 was
        # not attributable to contention vs validation without this
        # split. checktx p99 ≈ lock_wait p99 → contention-bound
        # (consensus holds the pool across Commit+Update); lock_wait
        # ≈ 0 → validation/insert-bound.
        self.lock_wait_seconds = r.sketch(
            "mempool",
            "lock_wait_seconds",
            "Time CheckTx spent waiting to acquire the mempool lock "
            "(the contention share of checktx_seconds).",
        )
        # the other half of the consensus hold: update() re-CheckTx's
        # every surviving pool tx under the lock, and that serial cost
        # scales with pool depth — without this sketch a slow commit
        # wasn't attributable to recheck vs app.commit (ISSUE 17
        # satellite; pairs with checktx_seconds/lock_wait_seconds)
        self.recheck_seconds = r.sketch(
            "mempool",
            "recheck_seconds",
            "Post-commit recheck duration per block (all pool txs "
            "re-validated under the consensus-held lock).",
        )
        # why txs leave without committing: TTL expiry vs full-pool
        # priority eviction — the two exits that silently eat offered
        # load before it ever reaches a proposal
        self.evicted_txs = r.counter(
            "mempool",
            "evicted_total",
            "Transactions evicted from the pool, by reason.",
            label_names=("reason",),
        )
