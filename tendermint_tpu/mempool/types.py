"""Mempool interface and transaction wrappers.

reference: internal/mempool/types.go:30-77 (Mempool iface),
internal/mempool/tx.go (WrappedTx, TxKey).
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

__all__ = ["tx_key", "TxInfo", "WrappedTx", "Mempool", "MempoolError"]


def tx_key(tx: bytes) -> bytes:
    """SHA-256 key identifying a tx (reference: types/tx.go Tx.Key)."""
    return hashlib.sha256(tx).digest()


class MempoolError(Exception):
    pass


class TxMempoolFullError(MempoolError):
    def __init__(self, num_txs: int, total_bytes: int) -> None:
        super().__init__(
            f"mempool is full: {num_txs} txs, {total_bytes} bytes"
        )


@dataclass(frozen=True)
class TxInfo:
    """Who sent us the tx (reference: internal/mempool/types.go:96-104)."""

    sender_id: int = 0
    sender_node_id: str = ""


_seq = itertools.count(1)


@dataclass
class WrappedTx:
    """A mempool-resident tx with its CheckTx verdict attached
    (reference: internal/mempool/tx.go:27-77)."""

    tx: bytes
    priority: int = 0
    sender: str = ""
    gas_wanted: int = 0
    height: int = 0  # height at which it entered the pool
    timestamp: float = 0.0
    peers: set = field(default_factory=set)  # sender ids that gossiped it
    seq: int = 0  # FIFO order for gossip / tie-breaking

    def __post_init__(self) -> None:
        if self.seq == 0:
            self.seq = next(_seq)

    @property
    def key(self) -> bytes:
        return tx_key(self.tx)

    def size(self) -> int:
        return len(self.tx)


class Mempool:
    """reference: internal/mempool/types.go:30-77."""

    async def check_tx(self, tx: bytes, tx_info: Optional[TxInfo] = None):
        raise NotImplementedError

    def remove_tx_by_key(self, key: bytes) -> None:
        raise NotImplementedError

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        raise NotImplementedError

    def reap_max_txs(self, max_txs: int) -> List[bytes]:
        raise NotImplementedError

    async def lock(self) -> None:
        raise NotImplementedError

    def unlock(self) -> None:
        raise NotImplementedError

    async def update(
        self,
        block_height: int,
        block_txs: Sequence[bytes],
        deliver_tx_responses: Sequence,
    ) -> None:
        raise NotImplementedError

    async def flush_app_conn(self) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError
