"""Seen-transaction cache.

reference: internal/mempool/cache.go — LRU keyed by tx hash, guarding
the app from re-CheckTx'ing recently seen txs (incl. committed ones).
"""

from __future__ import annotations

from collections import OrderedDict

from .types import tx_key

__all__ = ["LRUTxCache", "NopTxCache"]


class LRUTxCache:
    def __init__(self, size: int) -> None:
        self._size = max(1, size)
        self._map: OrderedDict[bytes, None] = OrderedDict()

    def reset(self) -> None:
        self._map.clear()

    def push(self, tx: bytes) -> bool:
        """Returns False if already present (moves it to most-recent)."""
        k = tx_key(tx)
        if k in self._map:
            self._map.move_to_end(k)
            return False
        self._map[k] = None
        if len(self._map) > self._size:
            self._map.popitem(last=False)
        return True

    def remove(self, tx: bytes) -> None:
        self._map.pop(tx_key(tx), None)

    def remove_by_key(self, key: bytes) -> None:
        self._map.pop(key, None)

    def has(self, tx: bytes) -> bool:
        return tx_key(tx) in self._map

    def __len__(self) -> int:
        return len(self._map)


class NopTxCache:
    """cache-size 0 ⇒ no caching (reference: cache.go NopTxCache)."""

    def reset(self) -> None: ...

    def push(self, tx: bytes) -> bool:
        return True

    def remove(self, tx: bytes) -> None: ...

    def remove_by_key(self, key: bytes) -> None: ...

    def has(self, tx: bytes) -> bool:
        return False

    def __len__(self) -> int:
        return 0
