"""No-op mempool for replay and non-validator contexts
(reference: internal/consensus/replay_stubs.go emptyMempool)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..abci import types as abci
from .types import Mempool, TxInfo

__all__ = ["NopMempool"]


class NopMempool(Mempool):
    async def check_tx(self, tx: bytes, tx_info: Optional[TxInfo] = None):
        return abci.ResponseCheckTx()

    def remove_tx_by_key(self, key: bytes) -> None: ...

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        return []

    def reap_max_txs(self, max_txs: int) -> List[bytes]:
        return []

    async def lock(self) -> None: ...

    def unlock(self) -> None: ...

    async def update(
        self,
        block_height: int,
        block_txs: Sequence[bytes],
        deliver_tx_responses: Sequence[abci.ResponseDeliverTx],
    ) -> None: ...

    async def flush_app_conn(self) -> None: ...

    def flush(self) -> None: ...

    def size(self) -> int:
        return 0

    def size_bytes(self) -> int:
        return 0
