"""Mempool reactor — tx gossip on channel 0x30.

reference: internal/mempool/reactor.go (channel id types.go:14,
descriptor :100-113, per-peer broadcast :150-230). Each peer gets a task
walking the mempool's FIFO gossip cursor; txs a peer sent us are never
echoed back to it.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..encoding.proto import FieldReader, ProtoWriter
from ..libs.log import get_logger
from ..libs.service import Service
from ..p2p.channel import Channel
from ..p2p.peermanager import PeerStatus
from ..p2p.types import ChannelDescriptor, Envelope
from .mempool import TxMempool
from .types import TxInfo

__all__ = ["MempoolReactor", "TxsMessage", "MEMPOOL_CHANNEL", "mempool_channel_descriptor"]

MEMPOOL_CHANNEL = 0x30


@dataclass
class TxsMessage:
    """proto/tendermint/mempool/types.pb.go Txs{txs=1}."""

    txs: Tuple[bytes, ...] = ()

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        for tx in self.txs:
            w.bytes(1, tx)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "TxsMessage":
        r = FieldReader(data)
        return cls(txs=tuple(r.get_all(1)))


def mempool_channel_descriptor(max_tx_bytes: int = 1 << 20):
    """reference: internal/mempool/reactor.go:100-113 (batch-sized)."""
    return ChannelDescriptor(
        channel_id=MEMPOOL_CHANNEL,
        message_type=TxsMessage,
        priority=5,
        send_queue_capacity=128,
        recv_message_capacity=max_tx_bytes * 10,
        recv_buffer_capacity=1024,
        name="mempool",
    )


class MempoolReactor(Service):
    def __init__(
        self,
        mempool: TxMempool,
        channel: Channel,
        peer_updates: asyncio.Queue,
        broadcast: bool = True,
    ) -> None:
        super().__init__(name="mempool.reactor", logger=get_logger("mempool.reactor"))
        self.mempool: TxMempool = mempool
        self.channel = channel
        self.peer_updates = peer_updates
        self.broadcast = broadcast
        self._peer_tasks: Dict[str, asyncio.Task] = {}

    async def on_start(self) -> None:
        self.spawn(self._peer_update_routine(), "peer-updates")
        self.spawn(self._recv_routine(), "recv")

    async def _peer_update_routine(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP and self.broadcast:
                if update.node_id not in self._peer_tasks:
                    self._peer_tasks[update.node_id] = self.spawn(
                        self._broadcast_to_peer(update.node_id),
                        f"tx-gossip-{update.node_id[:8]}",
                    )
            elif update.status == PeerStatus.DOWN:
                t = self._peer_tasks.pop(update.node_id, None)
                if t is not None and not t.done():
                    t.cancel()
                self._tasks = [x for x in self._tasks if not x.done()]

    async def _recv_routine(self) -> None:
        async for envelope in self.channel:
            msg = envelope.message
            info = TxInfo(sender_id=envelope.from_peer)
            # tmsafe: safe-unvalidated-use-ok — a tx is opaque app
            # bytes with no validate_basic of its own; CheckTx IS the
            # validation (size caps enforced by the channel
            # descriptor's max_tx_bytes upstream). One pipelined batch
            # per envelope: dup/full/invalid outcomes come back as
            # values (normal gossip noise, dropped).
            await self.mempool.check_tx_batch(list(msg.txs), info)

    async def _broadcast_to_peer(self, peer_id: str) -> None:
        """Walk the FIFO cursor, bundling a window of txs per envelope;
        skip txs the peer already knows (reference: reactor.go:150-230
        broadcastTxRoutine, which batches the same way)."""
        cursor = -1
        batch = max(1, int(getattr(self.mempool.cfg, "tx_batch_size", 1)))
        max_bytes = self.mempool.cfg.max_tx_bytes
        while True:
            await self.mempool.wait_for_tx(cursor)
            window = self.mempool.next_gossip_txs(cursor, batch, max_bytes)
            if not window:
                continue
            cursor = window[-1].seq
            txs = tuple(
                w.tx for w in window if peer_id not in w.peers
            )
            if not txs:
                continue  # peer sent all of them to us
            # blocking send: backpressure instead of silently skipping the
            # txs for this peer forever (reference blocks on SendEnvelope)
            await self.channel.send(
                Envelope(message=TxsMessage(txs=txs), to=peer_id)
            )
