"""TxMempool — the priority mempool, sharded for concurrent admission.

reference: internal/mempool/mempool.go (:28-56 design comment, CheckTx
:202, priority eviction :264-312, Update :380, recheck :471, TTL purge
:524). Transactions are validated through the ABCI mempool connection,
held with their priority/sender, reaped for proposals in priority order,
and gossiped in FIFO (arrival) order.

Admission is partitioned into N tx-key-hashed shards, each with its own
lock, seen-cache, and insertion-ordered tx map. CheckTx takes only its
shard's lock, so concurrent admissions overlap their ABCI round-trips
instead of convoying behind one pool-wide lock; consensus's lock() is an
epoch barrier that acquires every shard lock (ascending order, the same
order batch admission uses — no cycles), preserving the pre-shard
Commit+Update exclusion exactly. Reap, recheck, expiry, and eviction
operate on the global (-priority, seq) / seq orders, which are
shard-independent because `seq` is globally monotonic — semantics are
byte-identical to the unsharded pool (pinned by the oracle property
tests in tests/test_mempool_sharded.py).
"""

from __future__ import annotations

import asyncio
import time
from bisect import bisect_right
from typing import Callable, Dict, List, Optional, Sequence, Union

from ..abci import types as abci
from ..abci.client import ABCIClient
from ..config import MempoolConfig
from ..libs.log import get_logger
from .cache import LRUTxCache, NopTxCache
from .metrics import MempoolMetrics
from .types import (
    Mempool,
    MempoolError,
    TxInfo,
    TxMempoolFullError,
    WrappedTx,
    tx_key,
)

__all__ = ["TxMempool"]

# reference: internal/state/tx_filter.go pre-check is installed by the node;
# here the byte cap is enforced directly from config.

# Batch prevalidator plugin seam (the crypto.BatchVerifier shape applied
# to admission): takes the batch's raw txs, returns one truthy/falsy
# verdict per tx. CPU-bound implementations (e.g. stateless signature
# checks over ops.ed25519_kernel.batch_verify_host) run in the default
# executor so the event loop never blocks on them.
Prevalidator = Callable[[Sequence[bytes]], Sequence[object]]


class _Shard:
    """One admission partition: lock + seen-cache + insertion-ordered txs."""

    __slots__ = ("lock", "txs", "cache")

    def __init__(self, cache) -> None:
        self.lock = asyncio.Lock()
        # tmlive: bounded=cfg.size txs across all shards (is_full gates
        # every insert); cache bounded by its own LRU capacity
        self.txs: Dict[bytes, WrappedTx] = {}
        self.cache = cache


class _ShardedCache:
    """Seen-cache facade over the per-shard LRU caches.

    A tx always hashes to the same shard, so membership/dedup semantics
    match one global cache; only capacity-eviction order is per-shard.
    """

    def __init__(self, pool: "TxMempool") -> None:
        self._pool = pool

    def _cache_for(self, key: bytes):
        return self._pool._shard_for_key(key).cache

    def reset(self) -> None:
        for s in self._pool._shards:
            s.cache.reset()

    def push(self, tx: bytes) -> bool:
        return self._cache_for(tx_key(tx)).push(tx)

    def remove(self, tx: bytes) -> None:
        self._cache_for(tx_key(tx)).remove(tx)

    def remove_by_key(self, key: bytes) -> None:
        self._cache_for(key).remove_by_key(key)

    def has(self, tx: bytes) -> bool:
        return self._cache_for(tx_key(tx)).has(tx)

    def __len__(self) -> int:
        return sum(len(s.cache) for s in self._pool._shards)


class TxMempool(Mempool):
    def __init__(
        self,
        app_conn: ABCIClient,
        cfg: Optional[MempoolConfig] = None,
        height: int = 0,
        metrics: Optional[MempoolMetrics] = None,
        prevalidator: Optional[Prevalidator] = None,
    ) -> None:
        self.cfg = cfg or MempoolConfig()
        self.logger = get_logger("mempool")
        self._app = app_conn
        self._height = height
        n = max(1, int(getattr(self.cfg, "shards", 1)))
        if self.cfg.cache_size > 0:
            # split capacity across shards so the pool-wide budget is
            # unchanged; per-shard LRU order is the only divergence
            per = -(-self.cfg.cache_size // n)  # ceil
            caches = [LRUTxCache(per) for _ in range(n)]
        else:
            caches = [NopTxCache() for _ in range(n)]
        # tmlive: bounded=cfg.shards partitions, fixed at construction
        self._shards: List[_Shard] = [_Shard(c) for c in caches]
        self._senders: Dict[str, bytes] = {}  # sender → tx key (global)
        # FIFO index: the gossip cursor walk and the recheck iteration
        # need "live txs in seq order from a cursor" without an O(pool)
        # shard sweep per call (at a 5k-deep pool that sweep was the
        # top mempool bucket in the load profile). `_fifo_live` is
        # seq → wtx in insertion order (seq is assigned and inserted
        # synchronously, so insertion order IS seq order);
        # `_fifo_seqs` is the same seqs as a sorted list for bisect,
        # with lazy deletion — compacted when dead entries outnumber
        # live (tmlive: bounded=2×pool+64 by that compaction)
        self._fifo_live: Dict[int, WrappedTx] = {}
        self._fifo_seqs: List[int] = []
        # live count per distinct priority: lets a full-pool insert
        # answer "is there anything lower-priority to evict?" in
        # O(#distinct priorities) instead of materializing every
        # shard's txs just to reject (the uniform-priority flood case)
        self._prio_counts: Dict[int, int] = {}
        self._bytes = 0
        self._count = 0
        self.cache = _ShardedCache(self)
        self._tx_available = asyncio.Event()
        self.metrics = metrics if metrics is not None else MempoolMetrics()
        self._prevalidator = prevalidator

    # -- shard routing --

    def _shard_for_key(self, key: bytes) -> _Shard:
        return self._shards[
            int.from_bytes(key[:8], "big") % len(self._shards)
        ]

    @property
    def _txs(self) -> Dict[bytes, WrappedTx]:
        """Merged read-only view of every shard in global arrival (seq)
        order — the unsharded pool's insertion order. Introspection and
        tests only; hot paths use the FIFO index directly."""
        return {w.key: w for w in self._fifo_live.values()}

    def _all_wtxs(self) -> List[WrappedTx]:
        # seq-ascending by construction (see _fifo_live comment)
        return list(self._fifo_live.values())

    # -- sizes --

    def size(self) -> int:
        return self._count

    def size_bytes(self) -> int:
        return self._bytes

    def is_full(self, tx_size: int) -> bool:
        return (
            self._count >= self.cfg.size
            or self._bytes + tx_size > self.cfg.max_txs_bytes
        )

    # -- lifecycle with consensus --

    async def lock(self) -> None:
        """Epoch barrier: held by consensus across Commit+Update.
        Acquires every shard lock in ascending order (the same order
        check_tx_batch uses), excluding all admission exactly as the
        single pre-shard lock did."""
        for s in self._shards:
            await s.lock.acquire()

    def unlock(self) -> None:
        for s in reversed(self._shards):
            s.lock.release()

    async def flush_app_conn(self) -> None:
        await self._app.flush()

    def flush(self) -> None:
        """Drop everything (RPC unsafe_flush_mempool)."""
        for s in self._shards:
            s.txs.clear()
            s.cache.reset()
        self._senders.clear()
        self._fifo_live.clear()
        self._fifo_seqs.clear()
        self._prio_counts.clear()
        self._bytes = 0
        self._count = 0
        self.metrics.size.set(0)

    # -- ingestion --

    async def check_tx(
        self, tx: bytes, tx_info: Optional[TxInfo] = None
    ) -> abci.ResponseCheckTx:
        """Validate tx via the app and admit it to the pool
        (reference: internal/mempool/mempool.go:202-263). Takes the
        tx's shard lock, so ingestion is excluded while consensus holds
        the epoch barrier across Commit+Update — a tx can never be
        validated against pre-commit app state and inserted post-commit."""
        t0 = time.perf_counter()
        if len(tx) > self.cfg.max_tx_bytes:
            raise MempoolError(
                f"tx too large: {len(tx)} > {self.cfg.max_tx_bytes}"
            )
        key = tx_key(tx)
        shard = self._shard_for_key(key)
        try:
            async with shard.lock:
                # the contention share on its own: checktx_seconds
                # keeps folding the wait in (the total IS the ingest
                # latency), this split says how much of it was waiting
                # for consensus to release the pool
                self.metrics.lock_wait_seconds.observe(
                    time.perf_counter() - t0
                )
                res = self._precheck(shard, tx, key, tx_info)
                if isinstance(res, MempoolError):
                    raise res
                if self._prevalidator is not None:
                    verdicts = await asyncio.get_running_loop(
                    ).run_in_executor(None, self._prevalidator, [tx])
                    if not verdicts[0]:
                        return self._prevalidate_reject(tx)
                resp = await self._app.check_tx(abci.RequestCheckTx(tx=tx))
                out = self._admit(shard, tx, key, tx_info, resp)
                if isinstance(out, MempoolError):
                    raise out
                return out
        finally:
            # lock wait included on purpose: under load the wait for
            # consensus to release the pool IS the ingest latency
            self.metrics.checktx_seconds.observe(
                time.perf_counter() - t0
            )

    async def check_tx_batch(
        self, txs: Sequence[bytes], tx_info: Optional[TxInfo] = None
    ) -> List[Union[abci.ResponseCheckTx, MempoolError]]:
        """Admit a batch with one pipelined ABCI round: per-tx outcomes
        (a ResponseCheckTx, or the MempoolError check_tx would have
        raised) in input order. The involved shard locks are held in
        ascending order across the app call — the same exclusion
        serial check_tx gets from its single shard lock, amortized, and
        deadlock-free against the consensus barrier which acquires in
        the same order. The high-rate ingest paths (gossip receive, RPC
        broadcast batching) land here so the app lock and event-loop
        hops are paid once per batch instead of once per tx.
        """
        if not txs:
            return []
        t0 = time.perf_counter()
        keys = [tx_key(tx) for tx in txs]
        shard_ids = sorted(
            {
                int.from_bytes(k[:8], "big") % len(self._shards)
                for k in keys
            }
        )
        for sid in shard_ids:
            await self._shards[sid].lock.acquire()
        try:
            self.metrics.lock_wait_seconds.observe(
                time.perf_counter() - t0
            )
            out: List[Union[abci.ResponseCheckTx, MempoolError]] = [
                None
            ] * len(txs)
            pending: List[int] = []  # indices awaiting the app verdict
            for i, (tx, key) in enumerate(zip(txs, keys)):
                if len(tx) > self.cfg.max_tx_bytes:
                    out[i] = MempoolError(
                        f"tx too large: {len(tx)} > "
                        f"{self.cfg.max_tx_bytes}"
                    )
                    continue
                shard = self._shard_for_key(key)
                res = self._precheck(shard, tx, key, tx_info)
                if isinstance(res, MempoolError):
                    out[i] = res
                else:
                    pending.append(i)
            if pending and self._prevalidator is not None:
                # CPU-bound batch validation off-loop (BatchVerifier
                # plugin boundary): the loop keeps serving while the
                # executor grinds signatures
                verdicts = await asyncio.get_running_loop(
                ).run_in_executor(
                    None, self._prevalidator, [txs[i] for i in pending]
                )
                kept = []
                for i, ok in zip(pending, verdicts):
                    if ok:
                        kept.append(i)
                    else:
                        out[i] = self._prevalidate_reject(txs[i])
                pending = kept
            if pending:
                resps = await self._app.check_tx_batch(
                    [abci.RequestCheckTx(tx=txs[i]) for i in pending]
                )
                for i, resp in zip(pending, resps):
                    out[i] = self._admit(
                        self._shard_for_key(keys[i]),
                        txs[i],
                        keys[i],
                        tx_info,
                        resp,
                    )
            return out
        finally:
            for sid in reversed(shard_ids):
                self._shards[sid].lock.release()
            dur = time.perf_counter() - t0
            for _ in txs:
                self.metrics.checktx_seconds.observe(dur)

    def _precheck(
        self,
        shard: _Shard,
        tx: bytes,
        key: bytes,
        tx_info: Optional[TxInfo],
    ) -> Optional[MempoolError]:
        """Synchronous pre-app admission checks (dup/cache). Returns the
        error check_tx would raise, or None to proceed to the app."""
        tx_info = tx_info or TxInfo()
        if not shard.cache.push(tx):
            # seen before: note the gossiping peer for the existing entry
            wtx = shard.txs.get(key)
            if wtx is not None and tx_info.sender_id:
                wtx.peers.add(tx_info.sender_id)
            return MempoolError("tx already exists in cache")
        if key in shard.txs:
            # pool-resident but cache-evicted (shared LRU churn): don't
            # re-insert — that would double-count bytes and reset the
            # gossip seq (reference: mempool.go txStore.GetTxByHash guard)
            wtx = shard.txs[key]
            if tx_info.sender_id:
                wtx.peers.add(tx_info.sender_id)
            return MempoolError("tx already exists in the mempool")
        return None

    def _prevalidate_reject(self, tx: bytes) -> abci.ResponseCheckTx:
        self.metrics.failed_txs.inc()
        if not self.cfg.keep_invalid_txs_in_cache:
            self.cache.remove(tx)
        return abci.ResponseCheckTx(
            code=1, log="rejected by batch prevalidator"
        )

    def _admit(
        self,
        shard: _Shard,
        tx: bytes,
        key: bytes,
        tx_info: Optional[TxInfo],
        res: abci.ResponseCheckTx,
    ) -> Union[abci.ResponseCheckTx, MempoolError]:
        """Post-app admission: sender dedup + insert. Synchronous, so it
        is atomic with the app verdict from the event loop's view."""
        tx_info = tx_info or TxInfo()
        if not res.is_ok:
            self.metrics.failed_txs.inc()
            if not self.cfg.keep_invalid_txs_in_cache:
                shard.cache.remove(tx)
            return res

        if res.sender and res.sender in self._senders:
            shard.cache.remove(tx)
            return MempoolError(
                f"rejected tx with sender {res.sender!r}: already present"
            )

        wtx = WrappedTx(
            tx=tx,
            priority=res.priority,
            sender=res.sender,
            gas_wanted=res.gas_wanted,
            height=self._height,
            timestamp=time.monotonic(),
        )
        if tx_info.sender_id:
            wtx.peers.add(tx_info.sender_id)
        if not self._try_insert(shard, wtx):
            shard.cache.remove(tx)
            return TxMempoolFullError(self._count, self._bytes)
        return res

    def _try_insert(self, shard: _Shard, wtx: WrappedTx) -> bool:
        """Insert, evicting strictly-lower-priority txs when full
        (reference: internal/mempool/mempool.go:264-312). Victim choice
        spans every shard on the global (priority, -seq) order — the
        same candidates and order the unsharded pool picks."""
        if self.is_full(wtx.size()):
            # fast reject before the O(pool) victim scan: under a
            # uniform-priority flood every insert into a full pool
            # lands here, and the scan-to-find-nothing was the
            # profiler's top mempool stack at high offered rates
            if not any(p < wtx.priority for p in self._prio_counts):
                return False
            victims = sorted(
                (
                    w
                    for w in self._all_wtxs()
                    if w.priority < wtx.priority
                ),
                key=lambda w: (w.priority, -w.seq),
            )
            freed = 0
            chosen = []
            need_bytes = self._bytes + wtx.size() - self.cfg.max_txs_bytes
            need_count = self._count + 1 - self.cfg.size
            for v in victims:
                chosen.append(v)
                freed += v.size()
                if freed >= need_bytes and len(chosen) >= need_count:
                    break
            else:
                return False  # not enough low-priority mass to evict
            for v in chosen:
                self.logger.debug(
                    "evicting lower-priority tx", key=v.key.hex()[:16]
                )
                self._remove(v.key, remove_from_cache=True)
                self.metrics.evicted_txs.inc(reason="full")
        shard.txs[wtx.key] = wtx
        if wtx.sender:
            self._senders[wtx.sender] = wtx.key
        self._fifo_live[wtx.seq] = wtx
        self._fifo_seqs.append(wtx.seq)  # seq monotonic: stays sorted
        self._prio_counts[wtx.priority] = (
            self._prio_counts.get(wtx.priority, 0) + 1
        )
        self._bytes += wtx.size()
        self._count += 1
        self.metrics.size.set(self._count)
        self._tx_available.set()
        return True

    def _remove(self, key: bytes, remove_from_cache: bool = False) -> None:
        shard = self._shard_for_key(key)
        wtx = shard.txs.pop(key, None)
        if wtx is None:
            return
        if wtx.sender:
            self._senders.pop(wtx.sender, None)
        self._fifo_live.pop(wtx.seq, None)
        n = self._prio_counts.get(wtx.priority, 0) - 1
        if n > 0:
            self._prio_counts[wtx.priority] = n
        else:
            self._prio_counts.pop(wtx.priority, None)
        # lazy deletion in the bisect list: compact once dead entries
        # outnumber live ones (amortized O(1) per removal)
        if len(self._fifo_seqs) - len(self._fifo_live) > max(
            64, len(self._fifo_live)
        ):
            self._fifo_seqs = [
                s for s in self._fifo_seqs if s in self._fifo_live
            ]
        self._bytes -= wtx.size()
        self._count -= 1
        self.metrics.size.set(self._count)
        if remove_from_cache:
            shard.cache.remove_by_key(key)

    def remove_tx_by_key(self, key: bytes) -> None:
        self._remove(key, remove_from_cache=True)

    def get_tx(self, key: bytes) -> Optional[bytes]:
        wtx = self._shard_for_key(key).txs.get(key)
        return wtx.tx if wtx else None

    # -- reaping (proposal construction) --

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """Priority-descending reap under byte/gas budgets
        (reference: internal/mempool/mempool.go:328-366). The
        (-priority, seq) sort key is a total order (seq unique), so the
        result is shard-layout-independent."""
        out: List[bytes] = []
        total_bytes = 0
        total_gas = 0
        for wtx in sorted(
            self._all_wtxs(), key=lambda w: (-w.priority, w.seq)
        ):
            sz = wtx.size()
            if max_bytes > -1 and total_bytes + sz > max_bytes:
                continue
            if max_gas > -1 and total_gas + wtx.gas_wanted > max_gas:
                continue
            total_bytes += sz
            total_gas += wtx.gas_wanted
            out.append(wtx.tx)
        return out

    def reap_max_txs(self, max_txs: int) -> List[bytes]:
        n = self._count if max_txs < 0 else min(max_txs, self._count)
        ordered = sorted(
            self._all_wtxs(), key=lambda w: (-w.priority, w.seq)
        )
        return [w.tx for w in ordered[:n]]

    # -- post-commit update --

    async def update(
        self,
        block_height: int,
        block_txs: Sequence[bytes],
        deliver_tx_responses: Sequence[abci.ResponseDeliverTx],
    ) -> None:
        """Called by BlockExecutor.Commit with the epoch barrier held
        (reference: internal/mempool/mempool.go:380-445)."""
        self._height = block_height
        for tx, res in zip(block_txs, deliver_tx_responses):
            if res.is_ok:
                self.cache.push(tx)  # committed: never re-admit
            elif not self.cfg.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            self._remove(tx_key(tx))

        self._purge_expired(block_height)

        if self._count:
            if self.cfg.recheck:
                t0 = time.perf_counter()
                try:
                    await self._recheck()
                finally:
                    self.metrics.recheck_seconds.observe(
                        time.perf_counter() - t0
                    )
        if self._count:
            self._tx_available.set()

    async def _recheck(self) -> None:
        """Re-validate all pool txs against post-commit app state
        (reference: internal/mempool/mempool.go:471-523). Runs in
        arrival (seq) order — the unsharded pool's iteration order —
        pipelined through check_tx_batch in tx_batch_size chunks so the
        per-call client overhead is paid once per chunk; the app sees
        the identical request sequence."""
        wtxs = self._all_wtxs()  # already seq-ascending (FIFO index)
        chunk = max(1, int(getattr(self.cfg, "tx_batch_size", 64)))
        for lo in range(0, len(wtxs), chunk):
            batch = wtxs[lo : lo + chunk]
            resps = await self._app.check_tx_batch(
                [
                    abci.RequestCheckTx(
                        tx=w.tx, type=abci.CheckTxType.RECHECK
                    )
                    for w in batch
                ]
            )
            for wtx, res in zip(batch, resps):
                if not res.is_ok:
                    self._remove(
                        wtx.key,
                        remove_from_cache=(
                            not self.cfg.keep_invalid_txs_in_cache
                        ),
                    )
                else:
                    if res.priority != wtx.priority:
                        # keep the per-priority live counts exact: the
                        # full-pool fast reject consults them
                        n = self._prio_counts.get(wtx.priority, 0) - 1
                        if n > 0:
                            self._prio_counts[wtx.priority] = n
                        else:
                            self._prio_counts.pop(wtx.priority, None)
                        self._prio_counts[res.priority] = (
                            self._prio_counts.get(res.priority, 0) + 1
                        )
                    wtx.priority = res.priority
                    wtx.gas_wanted = res.gas_wanted

    def _purge_expired(self, block_height: int) -> None:
        """TTL eviction (reference: internal/mempool/mempool.go:524-570)."""
        if not self.cfg.ttl_duration and not self.cfg.ttl_num_blocks:
            return
        now = time.monotonic()
        for shard in self._shards:
            for key in list(shard.txs.keys()):
                wtx = shard.txs[key]
                if (
                    self.cfg.ttl_duration
                    and now - wtx.timestamp > self.cfg.ttl_duration
                ) or (
                    self.cfg.ttl_num_blocks
                    and block_height - wtx.height > self.cfg.ttl_num_blocks
                ):
                    self._remove(key, remove_from_cache=True)
                    self.metrics.evicted_txs.inc(reason="expired")

    # -- gossip support --

    def next_gossip_tx(self, after_seq: int) -> Optional[WrappedTx]:
        """First tx with seq > after_seq in FIFO order, or None —
        O(log pool) via the bisectable FIFO index (a per-peer cursor
        deep in a big pool would otherwise rescan the whole head on
        every wakeup)."""
        i = bisect_right(self._fifo_seqs, after_seq)
        while i < len(self._fifo_seqs):
            wtx = self._fifo_live.get(self._fifo_seqs[i])
            if wtx is not None:
                return wtx
            i += 1  # lazily-deleted entry
        return None

    def next_gossip_txs(
        self, after_seq: int, max_txs: int, max_bytes: int
    ) -> List[WrappedTx]:
        """Up to max_txs FIFO-successors of after_seq within a byte
        budget — one gossip envelope's worth (the windowed analog of
        next_gossip_tx; reference reactor batches txs the same way).
        O(log pool + window), same index as next_gossip_tx."""
        out: List[WrappedTx] = []
        total = 0
        i = bisect_right(self._fifo_seqs, after_seq)
        while i < len(self._fifo_seqs) and len(out) < max_txs:
            wtx = self._fifo_live.get(self._fifo_seqs[i])
            i += 1
            if wtx is None:
                continue  # lazily-deleted entry
            total += len(wtx.tx)
            if out and total > max_bytes:
                break
            out.append(wtx)
        return out

    async def wait_for_tx(self, after_seq: int) -> WrappedTx:
        """Block until a tx with seq > after_seq exists (gossip cursor,
        the clist-walk analog; reference: internal/mempool/reactor.go)."""
        while True:
            wtx = self.next_gossip_tx(after_seq)
            if wtx is not None:
                return wtx
            self._tx_available.clear()
            await self._tx_available.wait()

    def tx_available(self) -> asyncio.Event:
        return self._tx_available
