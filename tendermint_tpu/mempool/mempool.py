"""TxMempool — the priority mempool.

reference: internal/mempool/mempool.go (:28-56 design comment, CheckTx
:202, priority eviction :264-312, Update :380, recheck :471, TTL purge
:524). Transactions are validated through the ABCI mempool connection,
held with their priority/sender, reaped for proposals in priority order,
and gossiped in FIFO (arrival) order.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Sequence

from ..abci import types as abci
from ..abci.client import ABCIClient
from ..config import MempoolConfig
from ..libs.log import get_logger
from .cache import LRUTxCache, NopTxCache
from .metrics import MempoolMetrics
from .types import (
    Mempool,
    MempoolError,
    TxInfo,
    TxMempoolFullError,
    WrappedTx,
    tx_key,
)

__all__ = ["TxMempool"]

# reference: internal/state/tx_filter.go pre-check is installed by the node;
# here the byte cap is enforced directly from config.


class TxMempool(Mempool):
    def __init__(
        self,
        app_conn: ABCIClient,
        cfg: Optional[MempoolConfig] = None,
        height: int = 0,
        metrics: Optional[MempoolMetrics] = None,
    ) -> None:
        self.cfg = cfg or MempoolConfig()
        self.logger = get_logger("mempool")
        self._app = app_conn
        self._height = height
        self._txs: Dict[bytes, WrappedTx] = {}  # key → wtx, insertion order
        self._senders: Dict[str, bytes] = {}  # sender → tx key
        self._bytes = 0
        self.cache = (
            LRUTxCache(self.cfg.cache_size)
            if self.cfg.cache_size > 0
            else NopTxCache()
        )
        self._lock = asyncio.Lock()  # held by consensus across Commit+Update
        self._tx_available = asyncio.Event()
        self.metrics = metrics if metrics is not None else MempoolMetrics()

    # -- sizes --

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._bytes

    def is_full(self, tx_size: int) -> bool:
        return (
            len(self._txs) >= self.cfg.size
            or self._bytes + tx_size > self.cfg.max_txs_bytes
        )

    # -- lifecycle with consensus --

    async def lock(self) -> None:
        await self._lock.acquire()

    def unlock(self) -> None:
        self._lock.release()

    async def flush_app_conn(self) -> None:
        await self._app.flush()

    def flush(self) -> None:
        """Drop everything (RPC unsafe_flush_mempool)."""
        self._txs.clear()
        self._senders.clear()
        self._bytes = 0
        self.metrics.size.set(0)
        self.cache.reset()

    # -- ingestion --

    async def check_tx(
        self, tx: bytes, tx_info: Optional[TxInfo] = None
    ) -> abci.ResponseCheckTx:
        """Validate tx via the app and admit it to the pool
        (reference: internal/mempool/mempool.go:202-263). Takes the
        mempool lock, so ingestion is excluded while consensus holds it
        across Commit+Update — a tx can never be validated against
        pre-commit app state and inserted post-commit."""
        t0 = time.perf_counter()
        try:
            async with self._lock:
                # the contention share on its own: checktx_seconds
                # keeps folding the wait in (the total IS the ingest
                # latency), this split says how much of it was waiting
                # for consensus to release the pool
                self.metrics.lock_wait_seconds.observe(
                    time.perf_counter() - t0
                )
                return await self._check_tx_locked(tx, tx_info)
        finally:
            # lock wait included on purpose: under load the wait for
            # consensus to release the pool IS the ingest latency
            self.metrics.checktx_seconds.observe(
                time.perf_counter() - t0
            )

    async def _check_tx_locked(
        self, tx: bytes, tx_info: Optional[TxInfo]
    ) -> abci.ResponseCheckTx:
        tx_info = tx_info or TxInfo()
        if len(tx) > self.cfg.max_tx_bytes:
            raise MempoolError(
                f"tx too large: {len(tx)} > {self.cfg.max_tx_bytes}"
            )
        key = tx_key(tx)
        if not self.cache.push(tx):
            # seen before: note the gossiping peer for the existing entry
            wtx = self._txs.get(key)
            if wtx is not None and tx_info.sender_id:
                wtx.peers.add(tx_info.sender_id)
            raise MempoolError("tx already exists in cache")
        if key in self._txs:
            # pool-resident but cache-evicted (shared LRU churn): don't
            # re-insert — that would double-count bytes and reset the
            # gossip seq (reference: mempool.go txStore.GetTxByHash guard)
            wtx = self._txs[key]
            if tx_info.sender_id:
                wtx.peers.add(tx_info.sender_id)
            raise MempoolError("tx already exists in the mempool")

        res = await self._app.check_tx(abci.RequestCheckTx(tx=tx))
        if not res.is_ok:
            self.metrics.failed_txs.inc()
            if not self.cfg.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            return res

        if res.sender and res.sender in self._senders:
            self.cache.remove(tx)
            raise MempoolError(
                f"rejected tx with sender {res.sender!r}: already present"
            )

        wtx = WrappedTx(
            tx=tx,
            priority=res.priority,
            sender=res.sender,
            gas_wanted=res.gas_wanted,
            height=self._height,
            timestamp=time.monotonic(),
        )
        if tx_info.sender_id:
            wtx.peers.add(tx_info.sender_id)
        if not self._try_insert(wtx):
            self.cache.remove(tx)
            raise TxMempoolFullError(len(self._txs), self._bytes)
        return res

    def _try_insert(self, wtx: WrappedTx) -> bool:
        """Insert, evicting strictly-lower-priority txs when full
        (reference: internal/mempool/mempool.go:264-312)."""
        if self.is_full(wtx.size()):
            victims = sorted(
                (w for w in self._txs.values() if w.priority < wtx.priority),
                key=lambda w: (w.priority, -w.seq),
            )
            freed = 0
            chosen = []
            need_bytes = self._bytes + wtx.size() - self.cfg.max_txs_bytes
            need_count = len(self._txs) + 1 - self.cfg.size
            for v in victims:
                chosen.append(v)
                freed += v.size()
                if freed >= need_bytes and len(chosen) >= need_count:
                    break
            else:
                return False  # not enough low-priority mass to evict
            for v in chosen:
                self.logger.debug(
                    "evicting lower-priority tx", key=v.key.hex()[:16]
                )
                self._remove(v.key, remove_from_cache=True)
        self._txs[wtx.key] = wtx
        if wtx.sender:
            self._senders[wtx.sender] = wtx.key
        self._bytes += wtx.size()
        self.metrics.size.set(len(self._txs))
        self._tx_available.set()
        return True

    def _remove(self, key: bytes, remove_from_cache: bool = False) -> None:
        wtx = self._txs.pop(key, None)
        if wtx is None:
            return
        if wtx.sender:
            self._senders.pop(wtx.sender, None)
        self._bytes -= wtx.size()
        self.metrics.size.set(len(self._txs))
        if remove_from_cache:
            self.cache.remove_by_key(key)

    def remove_tx_by_key(self, key: bytes) -> None:
        self._remove(key, remove_from_cache=True)

    def get_tx(self, key: bytes) -> Optional[bytes]:
        wtx = self._txs.get(key)
        return wtx.tx if wtx else None

    # -- reaping (proposal construction) --

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """Priority-descending reap under byte/gas budgets
        (reference: internal/mempool/mempool.go:328-366)."""
        out: List[bytes] = []
        total_bytes = 0
        total_gas = 0
        for wtx in sorted(
            self._txs.values(), key=lambda w: (-w.priority, w.seq)
        ):
            sz = wtx.size()
            if max_bytes > -1 and total_bytes + sz > max_bytes:
                continue
            if max_gas > -1 and total_gas + wtx.gas_wanted > max_gas:
                continue
            total_bytes += sz
            total_gas += wtx.gas_wanted
            out.append(wtx.tx)
        return out

    def reap_max_txs(self, max_txs: int) -> List[bytes]:
        n = len(self._txs) if max_txs < 0 else min(max_txs, len(self._txs))
        ordered = sorted(self._txs.values(), key=lambda w: (-w.priority, w.seq))
        return [w.tx for w in ordered[:n]]

    # -- post-commit update --

    async def update(
        self,
        block_height: int,
        block_txs: Sequence[bytes],
        deliver_tx_responses: Sequence[abci.ResponseDeliverTx],
    ) -> None:
        """Called by BlockExecutor.Commit with the mempool lock held
        (reference: internal/mempool/mempool.go:380-445)."""
        self._height = block_height
        for tx, res in zip(block_txs, deliver_tx_responses):
            if res.is_ok:
                self.cache.push(tx)  # committed: never re-admit
            elif not self.cfg.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            self._remove(tx_key(tx))

        self._purge_expired(block_height)

        if self._txs:
            if self.cfg.recheck:
                await self._recheck()
        if self._txs:
            self._tx_available.set()

    async def _recheck(self) -> None:
        """Re-validate all pool txs against post-commit app state
        (reference: internal/mempool/mempool.go:471-523)."""
        for key in list(self._txs.keys()):
            wtx = self._txs.get(key)
            if wtx is None:
                continue
            res = await self._app.check_tx(
                abci.RequestCheckTx(tx=wtx.tx, type=abci.CheckTxType.RECHECK)
            )
            if not res.is_ok:
                self._remove(
                    key,
                    remove_from_cache=not self.cfg.keep_invalid_txs_in_cache,
                )
            else:
                wtx.priority = res.priority
                wtx.gas_wanted = res.gas_wanted

    def _purge_expired(self, block_height: int) -> None:
        """TTL eviction (reference: internal/mempool/mempool.go:524-570)."""
        if not self.cfg.ttl_duration and not self.cfg.ttl_num_blocks:
            return
        now = time.monotonic()
        for key in list(self._txs.keys()):
            wtx = self._txs[key]
            if (
                self.cfg.ttl_duration
                and now - wtx.timestamp > self.cfg.ttl_duration
            ) or (
                self.cfg.ttl_num_blocks
                and block_height - wtx.height > self.cfg.ttl_num_blocks
            ):
                self._remove(key, remove_from_cache=True)

    # -- gossip support --

    def next_gossip_tx(self, after_seq: int) -> Optional[WrappedTx]:
        """First tx with seq > after_seq in FIFO order, or None."""
        for wtx in self._txs.values():  # insertion-ordered
            if wtx.seq > after_seq:
                return wtx
        return None

    async def wait_for_tx(self, after_seq: int) -> WrappedTx:
        """Block until a tx with seq > after_seq exists (gossip cursor,
        the clist-walk analog; reference: internal/mempool/reactor.go)."""
        while True:
            wtx = self.next_gossip_tx(after_seq)
            if wtx is not None:
                return wtx
            self._tx_available.clear()
            await self._tx_available.wait()

    def tx_available(self) -> asyncio.Event:
        return self._tx_available
