"""tendermint-tpu: a TPU-native BFT state-machine-replication framework.

A from-scratch rebuild of the capabilities of Tendermint Core (the reference
at joeabbey/tendermint): BFT consensus over an arbitrary deterministic
application (ABCI), p2p gossip networking, block/state sync, light clients,
and remote signers — with the signature-verification and merkle-hashing hot
paths executed as batched JAX/XLA programs on TPU, gated behind the same
plugin boundary the reference uses (crypto.BatchVerifier,
reference: crypto/crypto.go:53-61).
"""

from .version import __version__  # noqa: F401

TM_CORE_SEMVER = "0.35.0"
P2P_PROTOCOL = 8
BLOCK_PROTOCOL = 11
