"""BlockExecutor — proposal creation, validation, and block application.

reference: internal/state/execution.go (CreateProposalBlock :102,
ValidateBlock :125, ApplyBlock :151, Commit :240, execBlockOnProxyApp
:290, validator-update application :378-424, updateState :426,
fireEvents :505) and internal/state/validation.go:14 (header wiring).

The LastCommit signature check inside ValidateBlock routes through
types.validation.verify_commit — the TPU batch-verify hot path: one
device program verifies the whole commit's signatures
(tendermint_tpu/ops/ed25519_kernel.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..abci import types as abci
from ..abci.client import ABCIClient
from ..crypto.keys import pubkey_from_type_and_bytes
from ..crypto.merkle import hash_from_byte_slices
from ..encoding.proto import ProtoWriter
from ..eventbus import EventBus
from ..libs import trace
from ..libs.log import get_logger
from ..mempool.types import Mempool
from ..types.block import Block
from ..types.block_id import BlockID
from ..types.commit import BLOCK_ID_FLAG_ABSENT, Commit
from ..types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    LightClientAttackEvidence,
)
from ..types import events as E
from ..types.tx import tx_hash
from ..types.validation import verify_commit
from ..types.validator import Validator, ValidatorSet
from .metrics import StateMetrics
from .store import ABCIResponses, StateStore
from .types import State, median_time

__all__ = [
    "BlockExecutor",
    "EmptyEvidencePool",
    "build_last_commit_info",
    "results_hash",
    "validate_block",
    "validator_updates_from_abci",
]


def build_last_commit_info(
    block: Block, last_vals: "ValidatorSet | None", initial_height: int
) -> abci.LastCommitInfo:
    """ABCI LastCommitInfo from a block's LastCommit and the validator set
    of the previous height; None last_vals (pruned history) yields votes=()
    (reference: internal/state/execution.go getBeginBlockValidatorInfo).
    Shared by BlockExecutor and the handshake replay path."""
    if block.header.height == initial_height:
        return abci.LastCommitInfo()
    if last_vals is None:
        return abci.LastCommitInfo(round=block.last_commit.round)
    votes = []
    for i, v in enumerate(last_vals.validators):
        sig = (
            block.last_commit.signatures[i]
            if i < len(block.last_commit.signatures)
            else None
        )
        signed = sig is not None and sig.block_id_flag != BLOCK_ID_FLAG_ABSENT
        votes.append(
            abci.VoteInfo(
                validator=abci.Validator(
                    address=v.address, power=v.voting_power
                ),
                signed_last_block=signed,
            )
        )
    return abci.LastCommitInfo(
        round=block.last_commit.round, votes=tuple(votes)
    )


def _deterministic_deliver_tx(r: abci.ResponseDeliverTx) -> bytes:
    """Deterministic subset of a DeliverTx result — only consensus-relevant
    fields (reference: abci/types/result.go deterministicResponseDeliverTx:
    code, data, gas_wanted, gas_used)."""
    w = ProtoWriter()
    w.uint(1, r.code)
    w.bytes(2, r.data)
    w.int(5, r.gas_wanted)
    w.int(6, r.gas_used)
    return w.finish()


def results_hash(responses: Sequence[abci.ResponseDeliverTx]) -> bytes:
    """Merkle root of deterministic DeliverTx results
    (reference: types/results.go ABCIResults.Hash)."""
    return hash_from_byte_slices(
        [_deterministic_deliver_tx(r) for r in responses]
    )


def validator_updates_from_abci(
    updates: Sequence[abci.ValidatorUpdate],
) -> List[Validator]:
    """ABCI pubkey/power pairs → domain validators
    (reference: types/protobuf.go PB2TM.ValidatorUpdates)."""
    out = []
    for vu in updates:
        pk = pubkey_from_type_and_bytes(vu.pub_key.key_type, vu.pub_key.data)
        out.append(Validator(address=pk.address(), pub_key=pk, voting_power=vu.power))
    return out


def validate_validator_updates(
    updates: Sequence[abci.ValidatorUpdate], params
) -> None:
    """reference: internal/state/execution.go:378-400."""
    for vu in updates:
        if vu.power < 0:
            raise ValueError(f"voting power can't be negative: {vu}")
        if vu.power == 0:
            continue
        if not params.validator.is_valid_pubkey_type(vu.pub_key.key_type):
            raise ValueError(
                f"validator {vu} is using pubkey {vu.pub_key.key_type}, "
                "which is unsupported for consensus"
            )


class EmptyEvidencePool:
    """No-op pool for nodes without the evidence subsystem wired
    (reference: internal/state/services.go EmptyEvidencePool)."""

    def pending_evidence(self, max_bytes: int) -> Tuple[List[Evidence], int]:
        return [], 0

    def add_evidence(self, ev: Evidence) -> None: ...

    def update(self, state: State, evidence: List[Evidence]) -> None: ...

    def check_evidence(self, evidence: List[Evidence]) -> None: ...


def validate_block(state: State, block: Block) -> None:
    """Header wiring vs state (reference: internal/state/validation.go:14).
    Signature checks (LastCommit) happen here too — the batch path."""
    from ..types.header import BLOCK_PROTOCOL

    block.validate_basic()
    h = block.header
    if h.version.block != BLOCK_PROTOCOL or h.version.app != state.app_version:
        raise ValueError(
            f"wrong Block.Header.Version: got {h.version}, "
            f"expected block={BLOCK_PROTOCOL} app={state.app_version}"
        )
    if h.chain_id != state.chain_id:
        raise ValueError(
            f"wrong Block.Header.ChainID: got {h.chain_id!r}, "
            f"expected {state.chain_id!r}"
        )
    if state.last_block_height == 0 and h.height != state.initial_height:
        raise ValueError(
            f"wrong Block.Header.Height: got {h.height}, expected initial "
            f"height {state.initial_height}"
        )
    if state.last_block_height > 0 and h.height != state.last_block_height + 1:
        raise ValueError(
            f"wrong Block.Header.Height: got {h.height}, "
            f"expected {state.last_block_height + 1}"
        )
    if h.last_block_id != state.last_block_id:
        raise ValueError(
            f"wrong Block.Header.LastBlockID: got {h.last_block_id}, "
            f"expected {state.last_block_id}"
        )
    if h.app_hash != state.app_hash:
        raise ValueError(
            f"wrong Block.Header.AppHash: got {h.app_hash.hex()}, "
            f"expected {state.app_hash.hex()}"
        )
    if h.consensus_hash != state.consensus_params.hash():
        raise ValueError("wrong Block.Header.ConsensusHash")
    if h.last_results_hash != state.last_results_hash:
        raise ValueError("wrong Block.Header.LastResultsHash")
    if h.validators_hash != state.validators.hash():
        raise ValueError("wrong Block.Header.ValidatorsHash")
    if h.next_validators_hash != state.next_validators.hash():
        raise ValueError("wrong Block.Header.NextValidatorsHash")

    # LastCommit
    if block.header.height == state.initial_height:
        if len(block.last_commit.signatures) != 0:
            raise ValueError("initial block can't have LastCommit signatures")
    else:
        # The whole previous commit in one batched device call.
        verify_commit(
            state.chain_id,
            state.last_validators,
            state.last_block_id,
            h.height - 1,
            block.last_commit,
        )

    if not state.validators.has_address(h.proposer_address):
        raise ValueError(
            f"block proposer {h.proposer_address.hex()} is not a validator"
        )

    # Evidence size cap (contents validated by the evidence pool)
    max_ev_bytes = state.consensus_params.evidence.max_bytes
    ev_bytes = sum(len(ev.bytes()) for ev in block.evidence)
    if ev_bytes > max_ev_bytes:
        raise ValueError(
            f"evidence bytes {ev_bytes} exceed max {max_ev_bytes}"
        )

    if h.height > state.initial_height:
        if h.time_ns != median_time(block.last_commit, state.last_validators):
            raise ValueError("invalid block time (not median of last commit)")
    elif h.time_ns != state.last_block_time_ns:
        raise ValueError("block time != genesis time for initial block")


class BlockExecutor:
    """reference: internal/state/execution.go:53-100."""

    def __init__(
        self,
        state_store: StateStore,
        app_conn: ABCIClient,
        mempool: Mempool,
        evidence_pool=None,
        block_store=None,
        event_bus: Optional[EventBus] = None,
        metrics: Optional[StateMetrics] = None,
    ) -> None:
        self.store = state_store
        self.app = app_conn
        self.mempool = mempool
        self.evpool = evidence_pool or EmptyEvidencePool()
        self.block_store = block_store
        self.event_bus = event_bus
        self.metrics = metrics if metrics is not None else StateMetrics()
        self.logger = get_logger("state.executor")

    # -- proposal --

    def create_proposal_block(
        self, height: int, state: State, commit: Commit, proposer_addr: bytes
    ):
        """Reap mempool + evidence into a new block
        (reference: internal/state/execution.go:102-123)."""
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence, ev_size = self.evpool.pending_evidence(
            state.consensus_params.evidence.max_bytes
        )
        from ..types.block import max_data_bytes

        data_cap = max_data_bytes(
            max_bytes, ev_size, len(state.validators)
        )
        txs = self.mempool.reap_max_bytes_max_gas(data_cap, max_gas)
        return state.make_block(height, txs, commit, evidence, proposer_addr)

    # -- validation --

    def validate_block(self, state: State, block: Block) -> None:
        validate_block(state, block)
        self.evpool.check_evidence(list(block.evidence))

    # -- application --

    async def apply_block(
        self, state: State, block_id: BlockID, block: Block
    ) -> State:
        """Validate, execute against the app, update state, commit
        (reference: internal/state/execution.go:151-237)."""
        with trace.span(
            "block_execute",
            hist=self.metrics.block_processing,
            height=block.header.height,
            txs=len(block.txs),
        ):
            return await self._apply_block_timed(state, block_id, block)

    async def _apply_block_timed(
        self, state: State, block_id: BlockID, block: Block
    ) -> State:
        self.validate_block(state, block)

        responses = await self._exec_block(state, block)

        self.store.save_abci_responses(block.header.height, responses)

        end_block = responses.end_block_obj
        validate_validator_updates(
            end_block.validator_updates, state.consensus_params
        )
        validator_updates = validator_updates_from_abci(
            end_block.validator_updates
        )
        if validator_updates:
            self.logger.info(
                "updates to validators",
                updates=",".join(
                    f"{v.address.hex()[:12]}:{v.voting_power}"
                    for v in validator_updates
                ),
            )

        new_state = update_state(
            state, block_id, block, responses, validator_updates
        )

        # Lock mempool, commit app state, update mempool
        app_hash, retain_height = await self._commit(new_state, block, responses)
        new_state.app_hash = app_hash

        self.evpool.update(new_state, list(block.evidence))

        self.store.save(new_state)

        if retain_height > 0 and self.block_store is not None:
            try:
                pruned = self.block_store.prune_blocks(retain_height)
                self.logger.info(
                    "pruned blocks", pruned=pruned, retain_height=retain_height
                )
            except Exception as e:
                self.logger.error("failed to prune blocks", err=str(e))

        self._fire_events(block, block_id, responses, validator_updates)
        return new_state

    async def _exec_block(self, state: State, block: Block) -> ABCIResponses:
        """BeginBlock → DeliverTx×N → EndBlock
        (reference: internal/state/execution.go:290-352)."""
        commit_info = self._begin_block_commit_info(state, block)
        byz = self._begin_block_evidence(state, block)
        begin = await self.app.begin_block(
            abci.RequestBeginBlock(
                hash=block.hash(),
                header_bytes=block.header.to_proto(),
                last_commit_info=commit_info,
                byzantine_validators=byz,
            )
        )
        deliver_txs: List[abci.ResponseDeliverTx] = []
        for txb in block.txs:
            r = await self.app.deliver_tx(abci.RequestDeliverTx(tx=txb))
            if not r.is_ok:
                self.logger.debug("invalid tx", code=r.code, log=r.log)
            deliver_txs.append(r)
        end = await self.app.end_block(
            abci.RequestEndBlock(height=block.header.height)
        )
        from ..abci.codec import _enc_resp_begin_block, _enc_resp_end_block

        resp = ABCIResponses(
            deliver_txs=[_full_deliver_tx_proto(r) for r in deliver_txs],
            end_block=_enc_resp_end_block(end),
            begin_block=_enc_resp_begin_block(begin),
        )
        # keep rich objects for eventing/state update in-memory
        resp.deliver_tx_objs = deliver_txs
        resp.end_block_obj = end
        resp.begin_block_obj = begin
        return resp

    def _begin_block_commit_info(
        self, state: State, block: Block
    ) -> abci.LastCommitInfo:
        """reference: internal/state/execution.go getBeginBlockValidatorInfo."""
        last_vals = self.store.load_validators(block.header.height - 1)
        if last_vals is None:
            last_vals = state.last_validators
        return build_last_commit_info(block, last_vals, state.initial_height)

    def _begin_block_evidence(
        self, state: State, block: Block
    ) -> tuple:
        out = []
        for ev in block.evidence:
            if isinstance(ev, DuplicateVoteEvidence):
                out.append(
                    abci.Misbehavior(
                        kind=abci.MISBEHAVIOR_DUPLICATE_VOTE,
                        validator=abci.Validator(
                            address=ev.vote_a.validator_address,
                            power=ev.validator_power,
                        ),
                        height=ev.height(),
                        time_ns=ev.timestamp_ns,
                        total_voting_power=ev.total_voting_power,
                    )
                )
            elif isinstance(ev, LightClientAttackEvidence):
                for v in ev.byzantine_validators:
                    out.append(
                        abci.Misbehavior(
                            kind=abci.MISBEHAVIOR_LIGHT_CLIENT_ATTACK,
                            validator=abci.Validator(
                                address=v.address, power=v.voting_power
                            ),
                            height=ev.height(),
                            time_ns=ev.timestamp_ns,
                            total_voting_power=ev.total_voting_power,
                        )
                    )
        return tuple(out)

    async def _commit(
        self, state: State, block: Block, responses: ABCIResponses
    ) -> Tuple[bytes, int]:
        """Mempool-locked ABCI Commit + mempool Update
        (reference: internal/state/execution.go:240-283)."""
        await self.mempool.lock()
        try:
            await self.mempool.flush_app_conn()
            res = await self.app.commit()
            self.logger.info(
                "committed state",
                height=block.header.height,
                num_txs=len(block.txs),
                app_hash=res.data.hex()[:16],
            )
            await self.mempool.update(
                block.header.height,
                list(block.txs),
                responses.deliver_tx_objs,
            )
            return res.data, res.retain_height
        finally:
            self.mempool.unlock()

    def _fire_events(
        self, block: Block, block_id: BlockID, responses: ABCIResponses,
        validator_updates: List[Validator],
    ) -> None:
        """reference: internal/state/execution.go:505-550."""
        if self.event_bus is None:
            return
        self.event_bus.publish_new_block(
            E.EventDataNewBlock(
                block=block,
                block_id=block_id,
                result_begin_block=responses.begin_block_obj,
                result_end_block=responses.end_block_obj,
            )
        )
        self.event_bus.publish_new_block_header(
            E.EventDataNewBlockHeader(
                header=block.header,
                num_txs=len(block.txs),
                result_begin_block=responses.begin_block_obj,
                result_end_block=responses.end_block_obj,
            )
        )
        for ev in block.evidence:
            self.event_bus.publish_new_evidence(
                E.EventDataNewEvidence(
                    evidence=ev, height=block.header.height
                )
            )
        for i, txb in enumerate(block.txs):
            self.event_bus.publish_tx(
                E.EventDataTx(
                    height=block.header.height,
                    tx=txb,
                    index=i,
                    result=responses.deliver_tx_objs[i],
                ),
                tx_hash=tx_hash(txb),
            )
        if validator_updates:
            self.event_bus.publish_validator_set_updates(
                E.EventDataValidatorSetUpdates(
                    validator_updates=tuple(validator_updates)
                )
            )


def update_state(
    state: State,
    block_id: BlockID,
    block: Block,
    responses: ABCIResponses,
    validator_updates: List[Validator],
) -> State:
    """The pure state-transition function
    (reference: internal/state/execution.go:426-500)."""
    h = block.header
    n_val_set = state.next_validators.copy()
    last_height_vals_changed = state.last_height_validators_changed
    if validator_updates:
        n_val_set.update_with_change_set(validator_updates)
        last_height_vals_changed = h.height + 1 + 1

    n_val_set.increment_proposer_priority(1)

    params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    app_version = state.app_version
    end_block = responses.end_block_obj
    if end_block.consensus_param_updates is not None:
        params = params.update(end_block.consensus_param_updates)
        params.validate()
        app_version = params.version.app_version
        last_height_params_changed = h.height + 1

    new_state = state.copy()
    new_state.last_block_height = h.height
    new_state.last_block_id = block_id
    new_state.last_block_time_ns = h.time_ns
    new_state.next_validators = n_val_set
    new_state.validators = state.next_validators.copy()
    new_state.last_validators = state.validators.copy()
    new_state.last_height_validators_changed = last_height_vals_changed
    new_state.consensus_params = params
    new_state.app_version = app_version
    new_state.last_height_consensus_params_changed = last_height_params_changed
    new_state.last_results_hash = results_hash(responses.deliver_tx_objs)
    new_state.app_hash = b""  # set after ABCI Commit
    return new_state


def _full_deliver_tx_proto(r: abci.ResponseDeliverTx) -> bytes:
    from ..abci.codec import _enc_resp_deliver_tx

    return _enc_resp_deliver_tx(r)
