"""Event indexer — block/tx event sinks feeding tx_search/block_search.

reference: internal/state/indexer/ (EventSink iface eventsink.go:26,
kv sink indexer/sink/kv, null sink, IndexerService
indexer_service.go:20-90). The KV sink indexes events whose attributes
were marked `index: true` by the app, plus the reserved tx.hash/tx.height
keys, and answers the same query language used by the event bus.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..abci import types as abci
from ..abci.codec import _dec_resp_deliver_tx, _enc_resp_deliver_tx
from ..encoding.proto import FieldReader, ProtoWriter
from ..eventbus import EventBus
from ..libs.service import Service
from ..pubsub import SubscriptionError
from ..pubsub.query import Query, compile_query
from ..store.kv import KVStore
from ..types import events as E
from ..types.tx import tx_hash

__all__ = ["TxResult", "EventSink", "KVSink", "NullSink", "IndexerService"]


@dataclass
class TxResult:
    """reference: proto/tendermint/abci/types.pb.go TxResult."""

    height: int
    index: int
    tx: bytes
    result: abci.ResponseDeliverTx

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.uint(2, self.index)
        w.bytes(3, self.tx)
        w.message(4, _enc_resp_deliver_tx(self.result))
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "TxResult":
        r = FieldReader(data)
        return cls(
            height=r.int64(1),
            index=r.uint(2),
            tx=r.bytes(3),
            result=_dec_resp_deliver_tx(r.bytes(4)),
        )


class EventSink:
    """reference: internal/state/indexer/eventsink.go:26-42."""

    def type(self) -> str:
        raise NotImplementedError

    def index_block_events(self, height: int, events: Sequence[abci.Event]) -> None:
        raise NotImplementedError

    def index_tx_events(self, results: Sequence[TxResult]) -> None:
        raise NotImplementedError

    def search_tx_events(self, query: "Query | str") -> List[TxResult]:
        raise NotImplementedError

    def search_block_events(self, query: "Query | str") -> List[int]:
        raise NotImplementedError

    def get_tx_by_hash(self, h: bytes) -> Optional[TxResult]:
        raise NotImplementedError

    def has_block(self, height: int) -> bool:
        raise NotImplementedError


class NullSink(EventSink):
    """reference: indexer/sink/null."""

    def type(self) -> str:
        return "null"

    def index_block_events(self, height, events) -> None: ...

    def index_tx_events(self, results) -> None: ...

    def search_tx_events(self, query) -> List[TxResult]:
        return []

    def search_block_events(self, query) -> List[int]:
        return []

    def get_tx_by_hash(self, h) -> Optional[TxResult]:
        return None

    def has_block(self, height: int) -> bool:
        return False


_TX_BY_HASH = b"th/"
_TX_INDEX = b"ti/"
_BLOCK_INDEX = b"bi/"
_SEP = b"\x00"


def _esc(s: str) -> bytes:
    """Escape tag/value bytes so the 0x00 key separator cannot appear
    inside them (0x00 → 0x01 0x01, 0x01 → 0x01 0x02)."""
    raw = s.encode(errors="replace")
    return raw.replace(b"\x01", b"\x01\x02").replace(b"\x00", b"\x01\x01")


def _unesc(raw: bytes) -> str:
    return (
        raw.replace(b"\x01\x01", b"\x00")
        .replace(b"\x01\x02", b"\x01")
        .decode(errors="replace")
    )


def _indexed_attrs(events: Sequence[abci.Event]) -> List[Tuple[str, str]]:
    out = []
    for ev in events or ():
        if not ev.type:
            continue
        for attr in ev.attributes:
            if attr.index:
                out.append(
                    (
                        f"{ev.type}.{attr.key.decode(errors='replace')}",
                        attr.value.decode(errors="replace"),
                    )
                )
    return out


class KVSink(EventSink):
    """Embedded-KV event sink (reference: indexer/sink/kv/kv.go)."""

    def __init__(self, db: KVStore) -> None:
        self._db = db

    def type(self) -> str:
        return "kv"

    # -- writes --

    def index_block_events(
        self, height: int, events: Sequence[abci.Event]
    ) -> None:
        hb = struct.pack(">q", height)
        self._db.set(_BLOCK_INDEX + b"height/" + hb, hb)
        for tag, value in _indexed_attrs(events):
            self._db.set(
                _BLOCK_INDEX + _esc(tag) + _SEP + _esc(value) + _SEP + hb,
                hb,
            )

    def index_tx_events(self, results: Sequence[TxResult]) -> None:
        for tr in results:
            h = tx_hash(tr.tx)
            self._db.set(_TX_BY_HASH + h, tr.to_proto())
            pos = struct.pack(">qI", tr.height, tr.index)
            pairs = _indexed_attrs(tr.result.events)
            pairs.append((E.TX_HEIGHT_KEY, str(tr.height)))
            pairs.append((E.TX_HASH_KEY, h.hex().upper()))
            for tag, value in pairs:
                self._db.set(
                    _TX_INDEX + _esc(tag) + _SEP + _esc(value) + _SEP + pos,
                    h,
                )

    # -- reads --

    def get_tx_by_hash(self, h: bytes) -> Optional[TxResult]:
        data = self._db.get(_TX_BY_HASH + h)
        return TxResult.from_proto(data) if data is not None else None

    def has_block(self, height: int) -> bool:
        return self._db.has(
            _BLOCK_INDEX + b"height/" + struct.pack(">q", height)
        )

    def _scan_condition(self, prefix: bytes, cond) -> Dict[bytes, bytes]:
        """tag-index scan → {position_key: payload} for entries whose value
        satisfies the condition."""
        base = prefix + _esc(cond.tag) + _SEP
        out: Dict[bytes, bytes] = {}
        if cond.op == "=" and isinstance(cond.arg, str):
            exact = base + _esc(cond.arg) + _SEP
            for k, v in self._db.iterate(exact, exact + b"\xff"):
                out[k[len(exact):]] = v
            return out
        for k, v in self._db.iterate(base, base + b"\xff"):
            rest = k[len(base):]
            value, _, pos = rest.partition(_SEP)
            if _cond_matches(cond, _unesc(value)):
                out[pos] = v
        return out

    def search_tx_events(self, query: "Query | str") -> List[TxResult]:
        q = compile_query(query) if isinstance(query, str) else query
        conds = q._conditions
        if not conds:
            return []
        sets = [self._scan_condition(_TX_INDEX, c) for c in conds]
        keys = set(sets[0])
        for s in sets[1:]:
            keys &= set(s)
        hashes = {sets[0][k] for k in keys}
        out = []
        for h in hashes:
            tr = self.get_tx_by_hash(h)
            if tr is not None:
                out.append(tr)
        out.sort(key=lambda t: (t.height, t.index))
        return out

    def search_block_events(self, query: "Query | str") -> List[int]:
        q = compile_query(query) if isinstance(query, str) else query
        conds = q._conditions
        if not conds:
            return []
        sets = []
        for c in conds:
            if c.tag == E.BLOCK_HEIGHT_KEY:
                # height is indexed positionally under bi/height/
                found = {}
                base = _BLOCK_INDEX + b"height/"
                for k, v in self._db.iterate(base, base + b"\xff"):
                    height = struct.unpack(">q", v)[0]
                    if _cond_matches(c, str(height)):
                        found[v] = v
                sets.append(found)
            else:
                sets.append(self._scan_condition(_BLOCK_INDEX, c))
        keys = set(sets[0])
        for s in sets[1:]:
            keys &= set(s)
        heights = sorted(
            struct.unpack(">q", sets[0][k])[0] for k in keys
        )
        return heights


def _cond_matches(cond, value: str) -> bool:
    return cond.matches([value])


class IndexerService(Service):
    """Subscribes to the event bus and feeds every sink
    (reference: internal/state/indexer/indexer_service.go:20-90)."""

    def __init__(self, sinks: List[EventSink], event_bus: EventBus) -> None:
        super().__init__(name="indexer")
        self.sinks = sinks
        self.bus = event_bus

    async def on_start(self) -> None:
        self._resubscribe("block")
        self._resubscribe("tx")
        self.spawn(self._index_blocks())
        self.spawn(self._index_txs())

    async def on_stop(self) -> None:
        try:
            self.bus.unsubscribe_all("indexer")
        except Exception:
            pass

    async def _index_blocks(self) -> None:
        await self._consume("block", lambda: self._block_sub, self._on_block)

    async def _index_txs(self) -> None:
        await self._consume("tx", lambda: self._tx_sub, self._on_tx)

    async def _consume(self, kind: str, get_sub, handler) -> None:
        """Drain a subscription forever. A sink error is logged, not fatal
        (one bad height must not kill indexing); a queue-overflow
        termination resubscribes loudly instead of silently stopping."""
        while self.is_running:
            try:
                msg = await get_sub().next()
            except SubscriptionError as e:
                if not self.is_running or str(e) in (
                    "unsubscribed", "server stopped"
                ):
                    return  # clean shutdown paths, not a lost subscription
                self.logger.error(
                    f"{kind} subscription lost; resubscribing "
                    "(events in the gap are not indexed)",
                    err=str(e),
                )
                self._resubscribe(kind)
                continue
            try:
                handler(msg.data)
            except Exception:
                self.logger.exception(f"failed to index {kind} events")

    def _resubscribe(self, kind: str) -> None:
        if kind == "block":
            self._block_sub = self.bus.subscribe(
                "indexer",
                f"{E.EVENT_TYPE_KEY} = '{E.EventValue.NEW_BLOCK}'",
                limit=1000,
            )
        else:
            self._tx_sub = self.bus.subscribe(
                "indexer",
                f"{E.EVENT_TYPE_KEY} = '{E.EventValue.TX}'",
                limit=10000,
            )

    def _on_block(self, data) -> None:
        events = []
        for src in (data.result_begin_block, data.result_end_block):
            events.extend(getattr(src, "events", ()) or ())
        for sink in self.sinks:
            sink.index_block_events(data.block.header.height, events)

    def _on_tx(self, data) -> None:
        tr = TxResult(
            height=data.height,
            index=data.index,
            tx=data.tx,
            result=data.result,
        )
        for sink in self.sinks:
            sink.index_tx_events([tr])
