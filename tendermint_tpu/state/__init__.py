"""Replicated state, execution, and indexing."""

from .execution import (  # noqa: F401
    BlockExecutor,
    EmptyEvidencePool,
    results_hash,
    update_state,
    validate_block,
)
from .indexer import IndexerService, KVSink, NullSink, TxResult  # noqa: F401
from .store import ABCIResponses, StateStore  # noqa: F401
from .types import State, median_time, state_from_genesis  # noqa: F401

__all__ = [
    "ABCIResponses",
    "BlockExecutor",
    "EmptyEvidencePool",
    "IndexerService",
    "KVSink",
    "NullSink",
    "State",
    "StateStore",
    "TxResult",
    "median_time",
    "results_hash",
    "state_from_genesis",
    "update_state",
    "validate_block",
]
