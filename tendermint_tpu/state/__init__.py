"""Replicated state and its persistence."""

from .store import ABCIResponses, StateStore  # noqa: F401
from .types import State, median_time, state_from_genesis  # noqa: F401

__all__ = [
    "ABCIResponses",
    "State",
    "StateStore",
    "median_time",
    "state_from_genesis",
]
