"""SQL event sink — the reference's psql sink, portable.

reference: internal/state/indexer/sink/psql/{psql.go,schema.sql}. The
schema is the reference's verbatim shape — blocks, tx_results, events,
attributes with composite keys — so operators can point the same
dashboards/joins at it. Two backends behind one DB-API surface:

  * sqlite3 (stdlib) — default; DSN ``sqlite:<path>`` or ``sqlite::memory:``
  * PostgreSQL via psycopg — DSN ``postgres://...`` (optional import;
    absent driver is a config error at construction, not at index time)

Where the reference sink is write-only (psql.go:238-256 returns "not
supported" for every read: operators query SQL directly), this one also
answers the EventSink read surface (search/get/has) over the same
schema, so `tx_search`/`block_search` keep working when the SQL sink is
the only sink configured.
"""

from __future__ import annotations

import datetime
import time
from typing import List, Optional, Sequence

from ..abci import types as abci
from ..pubsub.query import Query, compile_query
from ..types import events as E
from ..types.tx import tx_hash
from .indexer import EventSink, TxResult, _cond_matches

__all__ = ["SQLSink"]

_SCHEMA_SQLITE = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     INTEGER NOT NULL,
  chain_id   TEXT NOT NULL,
  created_at TEXT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain
  ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   INTEGER NOT NULL REFERENCES blocks(rowid),
  "index"    INTEGER NOT NULL,
  created_at TEXT NOT NULL,
  tx_hash    TEXT NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, "index")
);
CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id INTEGER NOT NULL REFERENCES blocks(rowid),
  tx_id    INTEGER NULL REFERENCES tx_results(rowid),
  type     TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
  event_id      INTEGER NOT NULL REFERENCES events(rowid),
  key           TEXT NOT NULL,
  composite_key TEXT NOT NULL,
  value         TEXT NULL
);
"""
# note: the reference schema declares UNIQUE (event_id, key) on
# attributes; dropped here deliberately — ABCI allows one event to
# repeat an attribute key with different values, the KV sink indexes
# every value, and the constraint would abort indexing on such (legal)
# events, killing the indexer task.

# reference schema.sql, lightly translated (BIGSERIAL/TIMESTAMPTZ/BYTEA)
_SCHEMA_PG = (
    _SCHEMA_SQLITE.replace(
        "INTEGER PRIMARY KEY AUTOINCREMENT", "BIGSERIAL PRIMARY KEY"
    )
    .replace("created_at TEXT", "created_at TIMESTAMPTZ")
    .replace("tx_result  BLOB", "tx_result  BYTEA")
)


class SQLSink(EventSink):
    """reference: indexer/sink/psql EventSink (schema-compatible)."""

    def __init__(self, dsn: str = "sqlite::memory:", chain_id: str = "") -> None:
        self.chain_id = chain_id
        if dsn.startswith("sqlite:"):
            import sqlite3

            path = dsn[len("sqlite:"):] or ":memory:"
            self._db = sqlite3.connect(path)
            self._ph = "?"
            self._pg = False
            self._db.executescript(_SCHEMA_SQLITE)
        elif dsn.startswith(("postgres://", "postgresql://")):
            try:
                import psycopg
            except ImportError as e:  # pragma: no cover - no pg in CI image
                raise ValueError(
                    "postgres DSN configured but psycopg is not "
                    "installed; use a sqlite: DSN or install psycopg"
                ) from e
            self._db = psycopg.connect(dsn)  # pragma: no cover
            self._ph = "%s"  # pragma: no cover
            self._pg = True  # pragma: no cover
            with self._db.cursor() as cur:  # pragma: no cover
                cur.execute(_SCHEMA_PG)
        else:
            raise ValueError(f"unsupported sink DSN {dsn!r}")

    def close(self) -> None:
        self._db.close()

    def type(self) -> str:
        return "psql"

    # -- helpers --

    def _exec(self, sql: str, params: tuple = ()):
        return self._db.execute(sql.replace("?", self._ph), params)

    def _insert_rowid(self, sql: str, params: tuple = ()) -> int:
        """INSERT returning the new rowid on both backends: sqlite
        exposes cursor.lastrowid; PostgreSQL needs RETURNING (psycopg
        cursors have no usable lastrowid)."""
        if self._pg:  # pragma: no cover - no pg in CI image
            cur = self._exec(sql + " RETURNING rowid", params)
            return cur.fetchone()[0]
        return self._exec(sql, params).lastrowid

    @staticmethod
    def _now() -> str:
        return datetime.datetime.now(datetime.timezone.utc).isoformat()

    def _block_rowid(self, height: int) -> Optional[int]:
        row = self._exec(
            "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?",
            (height, self.chain_id),
        ).fetchone()
        return row[0] if row else None

    def _ensure_block(self, height: int) -> int:
        """reference psql.go:154 insertBlock (idempotent per height)."""
        rowid = self._block_rowid(height)
        if rowid is not None:
            return rowid
        rowid = self._insert_rowid(
            "INSERT INTO blocks (height, chain_id, created_at) "
            "VALUES (?, ?, ?)",
            (height, self.chain_id, self._now()),
        )
        self._db.commit()
        return rowid

    def _insert_events(
        self,
        block_id: int,
        tx_id: Optional[int],
        events: Sequence[abci.Event],
        extra_attrs: Sequence[tuple] = (),
    ) -> None:
        """reference psql.go:95-143 insertEvents: only attributes the
        app marked index=true are recorded, plus the reserved keys."""
        if extra_attrs:
            event_id = self._insert_rowid(
                "INSERT INTO events (block_id, tx_id, type) VALUES (?, ?, ?)",
                (block_id, tx_id, ""),
            )
            for key, composite, value in extra_attrs:
                self._exec(
                    "INSERT INTO attributes "
                    "(event_id, key, composite_key, value) "
                    "VALUES (?, ?, ?, ?)",
                    (event_id, key, composite, value),
                )
        for ev in events or ():
            if not ev.type:
                continue
            event_id = self._insert_rowid(
                "INSERT INTO events (block_id, tx_id, type) VALUES (?, ?, ?)",
                (block_id, tx_id, ev.type),
            )
            for attr in ev.attributes:
                if not attr.index:
                    continue
                key = attr.key.decode(errors="replace")
                self._exec(
                    "INSERT INTO attributes "
                    "(event_id, key, composite_key, value) "
                    "VALUES (?, ?, ?, ?)",
                    (
                        event_id,
                        key,
                        f"{ev.type}.{key}",
                        attr.value.decode(errors="replace"),
                    ),
                )

    # -- EventSink writes --

    def index_block_events(
        self, height: int, events: Sequence[abci.Event]
    ) -> None:
        block_id = self._ensure_block(height)
        self._insert_events(block_id, None, events)
        self._db.commit()

    def index_tx_events(self, results: Sequence[TxResult]) -> None:
        """reference psql.go:182 IndexTxEvents, incl. the reserved
        tx.hash/tx.height attributes rows."""
        for tr in results:
            block_id = self._ensure_block(tr.height)
            h = tx_hash(tr.tx).hex().upper()
            cur = self._exec(
                "SELECT rowid FROM tx_results "
                'WHERE block_id = ? AND "index" = ?',
                (block_id, tr.index),
            )
            existing = cur.fetchone()
            if existing:
                continue  # already indexed (replay)
            tx_id = self._insert_rowid(
                "INSERT INTO tx_results "
                '(block_id, "index", created_at, tx_hash, tx_result) '
                "VALUES (?, ?, ?, ?, ?)",
                (block_id, tr.index, self._now(), h, tr.to_proto()),
            )
            self._insert_events(
                block_id,
                tx_id,
                tr.result.events,
                extra_attrs=[
                    ("hash", E.TX_HASH_KEY, h),
                    ("height", E.TX_HEIGHT_KEY, str(tr.height)),
                ],
            )
        self._db.commit()

    # -- EventSink reads (beyond the reference, which answers "not
    #    supported" for all of these and defers to raw SQL) --

    def get_tx_by_hash(self, h: bytes) -> Optional[TxResult]:
        # latest height wins, matching KVSink's last-write-wins when
        # the same tx bytes land at multiple heights
        row = self._exec(
            "SELECT t.tx_result FROM tx_results t "
            "JOIN blocks b ON b.rowid = t.block_id "
            "WHERE t.tx_hash = ? "
            'ORDER BY b.height DESC, t."index" DESC LIMIT 1',
            (h.hex().upper(),),
        ).fetchone()
        return TxResult.from_proto(row[0]) if row else None

    def has_block(self, height: int) -> bool:
        return self._block_rowid(height) is not None

    def _match_ids(self, cond, tx_scope: bool) -> set:
        """ids (tx rowids or block heights) whose attributes satisfy one
        query condition; value matching shares KVSink's semantics."""
        if tx_scope:
            sql = (
                "SELECT e.tx_id, a.value FROM events e "
                "JOIN attributes a ON a.event_id = e.rowid "
                "WHERE e.tx_id IS NOT NULL AND a.composite_key = ?"
            )
        else:
            sql = (
                "SELECT b.height, a.value FROM events e "
                "JOIN attributes a ON a.event_id = e.rowid "
                "JOIN blocks b ON b.rowid = e.block_id "
                "WHERE e.tx_id IS NULL AND a.composite_key = ?"
            )
        out = set()
        for ident, value in self._exec(sql, (cond.tag,)).fetchall():
            if _cond_matches(cond, value if value is not None else ""):
                out.add(ident)
        return out

    def search_tx_events(self, query: "Query | str") -> List[TxResult]:
        q = compile_query(query) if isinstance(query, str) else query
        conds = q._conditions
        if not conds:
            return []
        ids = self._match_ids(conds[0], tx_scope=True)
        for c in conds[1:]:
            ids &= self._match_ids(c, tx_scope=True)
        out: List[TxResult] = []
        for rowid in ids:
            row = self._exec(
                "SELECT tx_result FROM tx_results WHERE rowid = ?",
                (rowid,),
            ).fetchone()
            if row:
                out.append(TxResult.from_proto(row[0]))
        out.sort(key=lambda t: (t.height, t.index))
        return out

    def search_block_events(self, query: "Query | str") -> List[int]:
        q = compile_query(query) if isinstance(query, str) else query
        conds = q._conditions
        if not conds:
            return []
        sets = []
        for c in conds:
            if c.tag == E.BLOCK_HEIGHT_KEY:
                found = set()
                for (height,) in self._exec(
                    "SELECT height FROM blocks WHERE chain_id = ?",
                    (self.chain_id,),
                ).fetchall():
                    if _cond_matches(c, str(height)):
                        found.add(height)
                sets.append(found)
            else:
                sets.append(self._match_ids(c, tx_scope=False))
        ids = sets[0]
        for s in sets[1:]:
            ids &= s
        return sorted(ids)
