"""StateStore — persists State, validator sets, params, ABCI responses.

Reference: internal/state/store.go (Load/Save :70-270, validators with
sparse storage :300-420, consensus params :430-520, ABCI responses
:530-600) and internal/state/rollback.go:104.
"""

from __future__ import annotations

import struct
from typing import List, Optional

from ..encoding.proto import FieldReader, ProtoWriter, iter_fields
from ..types.params import ConsensusParams
from ..types.validator import ValidatorSet
from ..store.kv import Batch, KVStore
from .types import State

__all__ = ["StateStore", "ABCIResponses"]

_STATE = b"\x10"
_VALIDATORS = b"\x11"
_PARAMS = b"\x12"
_ABCI_RESPONSES = b"\x13"

# Validator sets are persisted every height; unchanged sets are stored as
# a pointer to the last height they changed (the reference's sparse
# storage, internal/state/store.go:330-360).
VALSET_CHECKPOINT_INTERVAL = 100000


def _vals_key(height: int) -> bytes:
    return _VALIDATORS + struct.pack(">q", height)


def _params_key(height: int) -> bytes:
    return _PARAMS + struct.pack(">q", height)


def _abci_key(height: int) -> bytes:
    return _ABCI_RESPONSES + struct.pack(">q", height)


class ABCIResponses:
    """DeliverTx/EndBlock results saved per height (reference:
    proto/tendermint/state/types.pb.go ABCIResponses). Stored as raw
    proto bytes of each DeliverTx response plus the EndBlock response."""

    def __init__(
        self,
        deliver_txs: Optional[List[bytes]] = None,
        end_block: bytes = b"",
        begin_block: bytes = b"",
    ) -> None:
        self.deliver_txs = deliver_txs or []
        self.end_block = end_block
        self.begin_block = begin_block

    @property
    def deliver_tx_objs(self):
        """Decoded DeliverTx responses (decoded lazily when loaded from
        disk; the executor sets the cache directly after execution)."""
        if not hasattr(self, "_deliver_tx_objs"):
            from ..abci.codec import _dec_resp_deliver_tx

            self._deliver_tx_objs = [
                _dec_resp_deliver_tx(d) for d in self.deliver_txs
            ]
        return self._deliver_tx_objs

    @deliver_tx_objs.setter
    def deliver_tx_objs(self, objs) -> None:
        self._deliver_tx_objs = objs

    @property
    def end_block_obj(self):
        if not hasattr(self, "_end_block_obj"):
            from ..abci.codec import _dec_resp_end_block

            self._end_block_obj = _dec_resp_end_block(self.end_block)
        return self._end_block_obj

    @end_block_obj.setter
    def end_block_obj(self, obj) -> None:
        self._end_block_obj = obj

    @property
    def begin_block_obj(self):
        if not hasattr(self, "_begin_block_obj"):
            from ..abci.codec import _dec_resp_begin_block

            self._begin_block_obj = _dec_resp_begin_block(self.begin_block)
        return self._begin_block_obj

    @begin_block_obj.setter
    def begin_block_obj(self, obj) -> None:
        self._begin_block_obj = obj

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        for dt in self.deliver_txs:
            w.message(1, dt)
        w.message(2, self.end_block)
        w.message(3, self.begin_block)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ABCIResponses":
        dts: List[bytes] = []
        eb = b""
        bb = b""
        for f, _wt, v in iter_fields(data):
            if f == 1:
                dts.append(v)
            elif f == 2:
                eb = v
            elif f == 3:
                bb = v
        return cls(deliver_txs=dts, end_block=eb, begin_block=bb)


class _ValInfo:
    """Validator-set record: either the set itself or a pointer to the
    last height it changed."""

    def __init__(
        self,
        val_set: Optional[ValidatorSet] = None,
        last_height_changed: int = 0,
    ) -> None:
        self.val_set = val_set
        self.last_height_changed = last_height_changed

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        if self.val_set is not None:
            w.message(1, self.val_set.to_proto())
        w.int(2, self.last_height_changed)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "_ValInfo":
        r = FieldReader(data)
        vs = r.get(1)
        return cls(
            val_set=(
                ValidatorSet.from_proto(vs) if vs is not None else None
            ),
            last_height_changed=r.int64(2),
        )


class StateStore:
    def __init__(self, db: KVStore) -> None:
        self._db = db

    # -- state --

    def load(self) -> Optional[State]:
        data = self._db.get(_STATE)
        return State.from_proto(data) if data is not None else None

    def save(self, state: State) -> None:
        """Persist state + the validator set & params it defines for
        future heights (reference: internal/state/store.go:150-220)."""
        next_height = state.last_block_height + 1
        if next_height == 1:
            next_height = state.initial_height
            # genesis bootstrap: persist validators for height 1 and 2
            self._save_validators(
                next_height, state.validators,
                state.last_height_validators_changed,
            )
        self._save_validators(
            next_height + 1, state.next_validators,
            state.last_height_validators_changed,
        )
        self._save_params(
            next_height, state.consensus_params,
            state.last_height_consensus_params_changed,
        )
        self._db.set(_STATE, state.to_proto())

    def bootstrap(self, state: State) -> None:
        """Used by state sync to install a trusted state
        (reference: internal/state/store.go Bootstrap)."""
        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height
        if state.last_validators is not None and height > 1:
            self._save_validators(
                height - 1, state.last_validators, height - 1
            )
        self._save_validators(height, state.validators, height)
        self._save_validators(height + 1, state.next_validators, height + 1)
        self._save_params(
            height, state.consensus_params,
            state.last_height_consensus_params_changed,
        )
        self._db.set(_STATE, state.to_proto())

    # -- validator sets per height --

    def save_validators(self, height: int, vals: ValidatorSet) -> None:
        """Store a historically-verified validator set (statesync
        backfill; reference: internal/state/store.go SaveValidatorSets)."""
        self._save_validators(height, vals, height)

    def _save_validators(
        self,
        height: int,
        vals: Optional[ValidatorSet],
        last_changed: int,
    ) -> None:
        if vals is None:
            return
        if (
            last_changed == height
            or height % VALSET_CHECKPOINT_INTERVAL == 0
        ):
            info = _ValInfo(val_set=vals, last_height_changed=last_changed)
        else:
            info = _ValInfo(val_set=None, last_height_changed=last_changed)
        self._db.set(_vals_key(height), info.to_proto())

    def load_validators(self, height: int) -> Optional[ValidatorSet]:
        """Sparse lookup: follow the pointer when the stored record has
        no set (reference: internal/state/store.go:300-360)."""
        data = self._db.get(_vals_key(height))
        if data is None:
            return None
        info = _ValInfo.from_proto(data)
        if info.val_set is not None:
            vs = info.val_set
        else:
            data2 = self._db.get(_vals_key(info.last_height_changed))
            if data2 is None:
                return None
            info2 = _ValInfo.from_proto(data2)
            if info2.val_set is None:
                return None
            vs = info2.val_set
            # advance priorities to this height, like the reference
            if height > info.last_height_changed:
                vs = vs.copy_increment_proposer_priority(
                    height - info.last_height_changed
                )
        return vs

    # -- consensus params per height --

    def _save_params(
        self, height: int, params: ConsensusParams, last_changed: int
    ) -> None:
        w = ProtoWriter()
        if last_changed == height:
            w.message(1, params.to_proto())
        w.int(2, last_changed)
        self._db.set(_params_key(height), w.finish())

    def load_params(self, height: int) -> Optional[ConsensusParams]:
        data = self._db.get(_params_key(height))
        if data is None:
            return None
        r = FieldReader(data)
        p = r.get(1)
        if p is not None:
            return ConsensusParams.from_proto(p)
        data2 = self._db.get(_params_key(r.int64(2)))
        if data2 is None:
            return None
        r2 = FieldReader(data2)
        p2 = r2.get(1)
        return ConsensusParams.from_proto(p2) if p2 is not None else None

    # -- ABCI responses --

    def save_abci_responses(
        self, height: int, responses: ABCIResponses
    ) -> None:
        self._db.set(_abci_key(height), responses.to_proto())

    def load_abci_responses(self, height: int) -> Optional[ABCIResponses]:
        data = self._db.get(_abci_key(height))
        return (
            ABCIResponses.from_proto(data) if data is not None else None
        )

    # -- pruning & rollback --

    def prune(self, retain_height: int) -> None:
        """Delete historical validator/params/ABCI records below
        retain_height (reference: internal/state/store.go PruneStates
        :220-330). Sparse pointer records reference the last height
        their data changed, so that one depended-on record below
        retain_height is kept (the reference's skip-over behavior)."""
        batch = Batch()

        # validators: keep the record the retain_height pointer targets
        data = self._db.get(_vals_key(retain_height))
        if data is not None:
            info = _ValInfo.from_proto(data)
            keep = (
                info.last_height_changed
                if info.val_set is None
                else retain_height
            )
            for k, _v in self._db.iterate(
                _vals_key(0), _vals_key(retain_height)
            ):
                if k != _vals_key(keep):
                    batch.delete(k)

        # params: same skip-over
        data = self._db.get(_params_key(retain_height))
        if data is not None:
            r = FieldReader(data)
            keep = retain_height if r.get(1) is not None else r.int64(2)
            for k, _v in self._db.iterate(
                _params_key(0), _params_key(retain_height)
            ):
                if k != _params_key(keep):
                    batch.delete(k)

        for k, _v in self._db.iterate(
            _abci_key(0), _abci_key(retain_height)
        ):
            batch.delete(k)
        self._db.write_batch(batch)

    def rollback(self, block_store) -> State:
        """Rewind state one height (reference:
        internal/state/rollback.go:13-104)."""
        state = self.load()
        if state is None or state.is_empty():
            raise ValueError("no state found")
        bs_height = block_store.height()
        # blockstore may legitimately be one ahead (non-atomic saves
        # around a crash): nothing to roll back.
        if bs_height == state.last_block_height + 1:
            return state
        if bs_height != state.last_block_height:
            raise ValueError(
                f"statestore height ({state.last_block_height}) is not "
                f"one below or equal to blockstore height ({bs_height})"
            )
        rollback_height = state.last_block_height - 1
        meta = block_store.load_block_meta(rollback_height)
        if meta is None:
            raise ValueError(
                f"block at height {rollback_height} not found"
            )
        prev_last_vals = self.load_validators(rollback_height)
        if prev_last_vals is None:
            raise ValueError(f"no validators at height {rollback_height}")
        params = self.load_params(rollback_height + 1)
        if params is None:
            raise ValueError(f"no params at height {rollback_height + 1}")
        val_change = state.last_height_validators_changed
        if val_change > rollback_height:
            val_change = rollback_height + 1
        params_change = state.last_height_consensus_params_changed
        if params_change > rollback_height:
            params_change = rollback_height + 1
        new_state = state.copy()
        new_state.last_block_height = meta.header.height
        new_state.last_block_id = meta.block_id
        new_state.last_block_time_ns = meta.header.time_ns
        new_state.next_validators = state.validators
        new_state.validators = state.last_validators
        new_state.last_validators = prev_last_vals
        new_state.last_height_validators_changed = val_change
        new_state.consensus_params = params
        new_state.app_version = params.version.app_version
        new_state.last_height_consensus_params_changed = params_change
        new_state.app_hash = meta.header.app_hash
        new_state.last_results_hash = meta.header.last_results_hash
        self._db.set(_STATE, new_state.to_proto())
        return new_state
