"""State (block execution) metrics struct
(reference: internal/state/metrics.go), per-node when threaded from
node assembly — see consensus/metrics.py for the pattern.
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["StateMetrics"]


class StateMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.block_processing = r.histogram(
            "state",
            "block_processing_seconds",
            "Time spent processing a block (validate + execute + commit).",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
        )
