"""State — the replicated-consensus state snapshot.

Reference: internal/state/state.go (State struct :66-101, Copy :104,
MakeBlock :255, MedianTime :295, genesis construction :320-400).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..types.block import Block, make_block
from ..types.block_id import BlockID
from ..types.block_meta import BlockMeta
from ..types.commit import Commit
from ..types.evidence import Evidence
from ..types.genesis import GenesisDoc
from ..types.header import Consensus
from ..types.params import ConsensusParams
from ..types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
from ..types.validator import Validator, ValidatorSet

__all__ = ["State", "median_time", "state_from_genesis"]


def median_time(commit: Commit, validators: ValidatorSet) -> int:
    """Voting-power-weighted median of commit timestamps — bounded by
    honest votes (reference: internal/state/state.go:291-312)."""
    weighted: List[tuple[int, int]] = []  # (time_ns, power)
    total = 0
    for cs in commit.signatures:
        if cs.is_absent():
            continue
        _, val = validators.get_by_address(cs.validator_address)
        if val is not None:
            total += val.voting_power
            weighted.append((cs.timestamp_ns, val.voting_power))
    weighted.sort()
    median = total // 2
    acc = 0
    for t, power in weighted:
        acc += power
        if acc > median:
            return t
    raise ValueError("median time: no votes")


@dataclass
class State:
    chain_id: str = ""
    initial_height: int = 1
    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time_ns: int = 0
    next_validators: Optional[ValidatorSet] = None
    validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0
    consensus_params: ConsensusParams = field(
        default_factory=ConsensusParams
    )
    last_height_consensus_params_changed: int = 0
    last_results_hash: bytes = b""
    app_hash: bytes = b""
    app_version: int = 0

    def copy(self) -> "State":
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time_ns=self.last_block_time_ns,
            next_validators=(
                self.next_validators.copy()
                if self.next_validators
                else None
            ),
            validators=(
                self.validators.copy() if self.validators else None
            ),
            last_validators=(
                self.last_validators.copy()
                if self.last_validators
                else None
            ),
            last_height_validators_changed=(
                self.last_height_validators_changed
            ),
            consensus_params=replace(self.consensus_params),
            last_height_consensus_params_changed=(
                self.last_height_consensus_params_changed
            ),
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
            app_version=self.app_version,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    def make_block(
        self,
        height: int,
        txs: List[bytes],
        commit: Commit,
        evidence: List[Evidence],
        proposer_address: bytes,
    ) -> tuple[Block, PartSet]:
        """reference: internal/state/state.go:255-289."""
        block = make_block(height, txs, commit, evidence)
        if height == self.initial_height:
            timestamp = self.last_block_time_ns  # genesis time
        else:
            timestamp = median_time(commit, self.last_validators)
        h = block.header
        h.version = Consensus(app=self.app_version)
        h.chain_id = self.chain_id
        h.time_ns = timestamp
        h.last_block_id = self.last_block_id
        h.validators_hash = self.validators.hash()
        h.next_validators_hash = self.next_validators.hash()
        h.consensus_hash = self.consensus_params.hash()
        h.app_hash = self.app_hash
        h.last_results_hash = self.last_results_hash
        h.proposer_address = proposer_address
        bps = block.make_part_set(BLOCK_PART_SIZE_BYTES)
        return block, bps

    # -- persistence form: reuse proto-encoded sections --

    def to_proto(self) -> bytes:
        from ..encoding.proto import ProtoWriter
        from ..types.timestamp import encode_timestamp

        w = ProtoWriter()
        w.string(2, self.chain_id)
        w.int(3, self.initial_height)
        w.int(4, self.last_block_height)
        w.message(5, self.last_block_id.to_proto())
        w.message(6, encode_timestamp(self.last_block_time_ns))
        if self.next_validators is not None:
            w.message(7, self.next_validators.to_proto())
        if self.validators is not None:
            w.message(8, self.validators.to_proto())
        if self.last_validators is not None:
            w.message(9, self.last_validators.to_proto())
        w.int(10, self.last_height_validators_changed)
        w.message(11, self.consensus_params.to_proto())
        w.int(12, self.last_height_consensus_params_changed)
        w.bytes(13, self.last_results_hash)
        w.bytes(14, self.app_hash)
        w.int(15, self.app_version)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "State":
        from ..encoding.proto import FieldReader
        from ..types.timestamp import decode_timestamp

        r = FieldReader(data)
        bid = r.get(5)
        ts = r.get(6)
        nv, v, lv = r.get(7), r.get(8), r.get(9)
        cp = r.get(11)
        return cls(
            chain_id=r.string(2),
            initial_height=r.int64(3),
            last_block_height=r.int64(4),
            last_block_id=(
                BlockID.from_proto(bid) if bid is not None else BlockID()
            ),
            last_block_time_ns=(
                decode_timestamp(ts) if ts is not None else 0
            ),
            next_validators=(
                ValidatorSet.from_proto(nv) if nv is not None else None
            ),
            validators=(
                ValidatorSet.from_proto(v) if v is not None else None
            ),
            last_validators=(
                ValidatorSet.from_proto(lv) if lv is not None else None
            ),
            last_height_validators_changed=r.int64(10),
            consensus_params=(
                ConsensusParams.from_proto(cp)
                if cp is not None
                else ConsensusParams()
            ),
            last_height_consensus_params_changed=r.int64(12),
            last_results_hash=r.bytes(13),
            app_hash=r.bytes(14),
            app_version=r.int64(15),
        )


def state_from_genesis(genesis: GenesisDoc) -> State:
    """reference: internal/state/state.go MakeGenesisState (:340-400)."""
    genesis.validate_and_complete()
    if genesis.validators:
        val_set = genesis.validator_set()
        next_vals = val_set.copy_increment_proposer_priority(1)
    else:
        val_set = None  # awaiting InitChain validators from the app
        next_vals = None
    return State(
        chain_id=genesis.chain_id,
        initial_height=genesis.initial_height,
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time_ns=genesis.genesis_time_ns,
        next_validators=next_vals,
        validators=val_set,
        last_validators=None,
        last_height_validators_changed=genesis.initial_height,
        consensus_params=genesis.consensus_params,
        last_height_consensus_params_changed=genesis.initial_height,
        last_results_hash=b"",
        app_hash=genesis.app_hash,
        app_version=genesis.consensus_params.version.app_version,
    )
