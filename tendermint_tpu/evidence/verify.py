"""Evidence verification.

reference: internal/evidence/verify.go (Verify :24, VerifyLightClientAttack
:159, VerifyDuplicateVote :202). Both paths are signature-heavy — the
duplicate-vote check verifies two signatures, the light-attack check
re-verifies a whole commit through the batched device path
(types.validation.verify_commit_light_trusting).
"""

from __future__ import annotations

from typing import Optional

from ..state.types import State
from ..types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    LightClientAttackEvidence,
)
from ..types.validation import Fraction, verify_commit_light_trusting
from ..types.validator import ValidatorSet

__all__ = ["verify_evidence", "verify_duplicate_vote", "verify_light_client_attack"]


def verify_evidence(
    ev: Evidence,
    state: State,
    state_store,
    block_store,
) -> None:
    """Full contextual verification (reference: verify.go:24-107).
    Raises ValueError on invalid evidence."""
    height = ev.height()
    header = _header_at(block_store, height)
    if header is None:
        raise ValueError(
            f"don't have header at height {height} for evidence verification"
        )
    ev_time = header.time_ns

    # expiry check against consensus params
    params = state.consensus_params.evidence
    age_num_blocks = state.last_block_height - height
    age_duration_ns = state.last_block_time_ns - ev_time
    if (
        age_duration_ns > params.max_age_duration_ns
        and age_num_blocks > params.max_age_num_blocks
    ):
        raise ValueError(
            f"evidence from height {height} is too old; "
            f"min height is {state.last_block_height - params.max_age_num_blocks}"
        )

    if isinstance(ev, DuplicateVoteEvidence):
        vals = state_store.load_validators(height)
        if vals is None:
            raise ValueError(f"no validator set at height {height}")
        verify_duplicate_vote(ev, state.chain_id, vals)
        if ev.timestamp_ns != ev_time:
            raise ValueError(
                "evidence has a different time to the block it is associated "
                f"with ({ev.timestamp_ns} != {ev_time})"
            )
    elif isinstance(ev, LightClientAttackEvidence):
        common_vals = state_store.load_validators(ev.common_height)
        if common_vals is None:
            raise ValueError(
                f"no validator set at common height {ev.common_height}"
            )
        verify_light_client_attack(ev, state.chain_id, common_vals, header)
    else:
        raise ValueError(f"unrecognized evidence type {type(ev).__name__}")


def verify_duplicate_vote(
    ev: DuplicateVoteEvidence, chain_id: str, val_set: ValidatorSet
) -> None:
    """reference: verify.go:202-263."""
    a, b = ev.vote_a, ev.vote_b
    if a.height != b.height or a.round != b.round or a.type != b.type:
        raise ValueError(
            f"h/r/s does not match: {a.height}/{a.round}/{a.type} vs "
            f"{b.height}/{b.round}/{b.type}"
        )
    if a.validator_address != b.validator_address:
        raise ValueError("validator addresses do not match")
    if a.block_id == b.block_id:
        raise ValueError(
            "block IDs are the same; duplicate evidence requires votes for "
            "different blocks"
        )
    _idx, val = val_set.get_by_address(a.validator_address)
    if val is None:
        raise ValueError(
            f"address {a.validator_address.hex()} was not a validator at "
            f"height {a.height}"
        )
    if val.voting_power != ev.validator_power:
        raise ValueError(
            f"validator power from evidence {ev.validator_power} != "
            f"{val.voting_power}"
        )
    if ev.total_voting_power != val_set.total_voting_power():
        raise ValueError(
            f"total voting power from evidence {ev.total_voting_power} != "
            f"{val_set.total_voting_power()}"
        )
    a.verify(chain_id, val.pub_key)
    b.verify(chain_id, val.pub_key)


def verify_light_client_attack(
    ev: LightClientAttackEvidence,
    chain_id: str,
    common_vals: ValidatorSet,
    trusted_header,
) -> None:
    """reference: verify.go:159-200. The conflicting block's commit must
    carry 1/3 of the validator set trusted at the common height (the
    batched device verify path), and the header must genuinely conflict."""
    cb = ev.conflicting_block
    if (
        cb is None
        or cb.signed_header is None
        or cb.signed_header.header is None
        or cb.signed_header.commit is None
    ):
        raise ValueError("conflicting block is incomplete")
    verify_commit_light_trusting(
        chain_id, common_vals, cb.signed_header.commit, Fraction(1, 3)
    )
    if trusted_header is not None:
        if trusted_header.hash() == cb.signed_header.header.hash():
            raise ValueError(
                "conflicting block is the same as the trusted block; "
                "not an attack"
            )
    if ev.total_voting_power != common_vals.total_voting_power():
        raise ValueError(
            f"total voting power from evidence {ev.total_voting_power} != "
            f"{common_vals.total_voting_power()}"
        )


def _header_at(block_store, height: int):
    meta = block_store.load_block_meta(height)
    return meta.header if meta is not None else None
