"""Evidence subsystem — pool, verification, gossip reactor.

reference: internal/evidence/.
"""

from .metrics import EvidenceMetrics
from .pool import EvidenceError, EvidencePool
from .reactor import (
    EVIDENCE_CHANNEL,
    EvidenceListMessage,
    EvidenceReactor,
    evidence_channel_descriptor,
)
from .verify import (
    verify_duplicate_vote,
    verify_evidence,
    verify_light_client_attack,
)

__all__ = [
    "EVIDENCE_CHANNEL",
    "EvidenceError",
    "EvidenceListMessage",
    "EvidenceMetrics",
    "EvidencePool",
    "EvidenceReactor",
    "evidence_channel_descriptor",
    "verify_duplicate_vote",
    "verify_evidence",
    "verify_light_client_attack",
]
