"""Evidence pool metrics struct
(reference: internal/evidence metrics), per-node when threaded from
node assembly — see consensus/metrics.py for the pattern. The pool
mutators (pool.py _add_pending/_mark_committed/_prune_expired) keep
`pool_size` exact, so loadgen/scrape.py can fold the byzantine
campaign's evidence flow into per-window deltas.
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["EvidenceMetrics"]


class EvidenceMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.pool_size = r.gauge(
            "evidence",
            "pool_size",
            "Verified evidence pending inclusion in a block.",
        )
        self.committed_total = r.counter(
            "evidence",
            "committed_total",
            "Evidence items committed in blocks (marked by Update).",
        )
        self.expired_total = r.counter(
            "evidence",
            "expired_total",
            "Pending evidence pruned after aging past both expiry bounds.",
        )
