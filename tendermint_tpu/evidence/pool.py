"""Evidence pool — verified-but-uncommitted evidence awaiting a block.

reference: internal/evidence/pool.go (:56-324). DB-backed pending list
with expiry by age/height, committed-evidence marking, and the
consensus-reported double-sign intake (ReportConflictingVotes :188).
"""

from __future__ import annotations

import struct
import time
from typing import List, Optional, Tuple

from ..libs.log import get_logger
from ..state.types import State
from ..types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    evidence_from_proto,
    evidence_to_proto,
)
from ..types.vote import Vote
from .metrics import EvidenceMetrics
from .verify import verify_evidence

__all__ = ["EvidencePool", "EvidenceError"]

_PENDING_PREFIX = b"evp/"  # pending evidence
_COMMITTED_PREFIX = b"evc/"  # committed evidence markers


class EvidenceError(Exception):
    pass


def _key(prefix: bytes, ev: Evidence) -> bytes:
    return prefix + struct.pack(">q", ev.height()) + ev.hash()


class EvidencePool:
    def __init__(self, db, state_store, block_store, metrics=None) -> None:
        self.db = db
        self.state_store = state_store
        self.block_store = block_store
        self.logger = get_logger("evidence.pool")
        # per-node registry when node assembly provides one; bare
        # constructions share DEFAULT_REGISTRY (idempotent register)
        self.metrics = (
            metrics if metrics is not None else EvidenceMetrics()
        )
        self._pending: List[Evidence] = []
        self._pending_keys: set = set()
        # consensus-reported double signs buffered until the next Update
        # (they may be for the current height, whose block isn't stored yet;
        # reference: pool.go:188-204 + consensus buffer handling)
        self._consensus_buffer: List[Tuple[Vote, Vote]] = []
        self._load_pending()
        self.metrics.pool_size.set(len(self._pending))

    # -- queries --

    def pending_evidence(self, max_bytes: int) -> Tuple[List[Evidence], int]:
        """reference: pool.go:88-110 PendingEvidence."""
        out: List[Evidence] = []
        size = 0
        for ev in self._pending:
            ev_size = len(ev.bytes())
            if size + ev_size > max_bytes:
                break
            out.append(ev)
            size += ev_size
        return out, size

    def is_committed(self, ev: Evidence) -> bool:
        return self.db.get(_key(_COMMITTED_PREFIX, ev)) is not None

    def is_pending(self, ev: Evidence) -> bool:
        return _key(_PENDING_PREFIX, ev) in self._pending_keys

    # -- intake --

    def add_evidence(self, ev: Evidence) -> None:
        """Verify and admit gossiped/submitted evidence
        (reference: pool.go:112-160). Raises EvidenceError if invalid."""
        if self.is_pending(ev) or self.is_committed(ev):
            return  # already known
        state = self.state_store.load()
        try:
            verify_evidence(ev, state, self.state_store, self.block_store)
        except ValueError as e:
            raise EvidenceError(f"invalid evidence: {e}") from e
        self._add_pending(ev)
        self.logger.info("verified new evidence", evidence=ev.hash().hex()[:16])

    def report_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        """From consensus: buffer the pair; evidence is formed at the next
        Update when the validator set for that height is known
        (reference: pool.go:188-204)."""
        self._consensus_buffer.append((vote_a, vote_b))

    def check_evidence(self, evidence: List[Evidence]) -> None:
        """Block-validation path: every item must verify and not be
        committed; duplicates in one block are invalid
        (reference: pool.go:206-260)."""
        if not evidence:
            # the overwhelmingly common case — don't pay a full state
            # decode (ValidatorSet included) per evidence-free block
            return
        state = self.state_store.load()
        seen = set()
        for ev in evidence:
            if not self.is_pending(ev):
                try:
                    verify_evidence(
                        ev, state, self.state_store, self.block_store
                    )
                except ValueError as e:
                    raise EvidenceError(f"invalid evidence: {e}") from e
            if self.is_committed(ev):
                raise EvidenceError("evidence was already committed")
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)

    # -- post-commit update --

    def update(self, state: State, evidence: List[Evidence]) -> None:
        """Mark committed, prune expired, and materialize buffered
        double-signs (reference: pool.go:162-186)."""
        for ev in evidence:
            self._mark_committed(state.last_block_height, ev)
        self._process_consensus_buffer(state)
        self._prune_expired(state)

    def _process_consensus_buffer(self, state: State) -> None:
        buffered, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buffered:
            vals = self.state_store.load_validators(vote_a.height)
            if vals is None:
                self.logger.error(
                    "failed to form duplicate-vote evidence; no validator "
                    "set", height=vote_a.height,
                )
                continue
            _idx, val = vals.get_by_address(vote_a.validator_address)
            if val is None:
                continue
            ev = DuplicateVoteEvidence.from_votes(
                vote_a,
                vote_b,
                block_time_ns=self._block_time(vote_a.height),
                val_set=vals,
            )
            if not (self.is_pending(ev) or self.is_committed(ev)):
                self._add_pending(ev)
                self.logger.info(
                    "generated double-sign evidence",
                    height=ev.height(),
                    validator=vote_a.validator_address.hex()[:12],
                )

    def _block_time(self, height: int) -> int:
        meta = self.block_store.load_block_meta(height)
        return meta.header.time_ns if meta is not None else time.time_ns()

    def _prune_expired(self, state: State) -> None:
        params = state.consensus_params.evidence
        keep: List[Evidence] = []
        for ev in self._pending:
            age_blocks = state.last_block_height - ev.height()
            ev_time = self._block_time(ev.height())
            age_ns = state.last_block_time_ns - ev_time
            if (
                age_blocks > params.max_age_num_blocks
                and age_ns > params.max_age_duration_ns
            ):
                self.db.delete(_key(_PENDING_PREFIX, ev))
                self._pending_keys.discard(_key(_PENDING_PREFIX, ev))
                self.metrics.expired_total.inc()
                self.logger.info(
                    "pruned expired evidence", height=ev.height()
                )
            else:
                keep.append(ev)
        self._pending = keep
        self.metrics.pool_size.set(len(self._pending))

    # -- storage --

    def _add_pending(self, ev: Evidence) -> None:
        key = _key(_PENDING_PREFIX, ev)
        self.db.set(key, evidence_to_proto(ev))
        self._pending.append(ev)
        self._pending_keys.add(key)
        self.metrics.pool_size.set(len(self._pending))

    def _mark_committed(self, commit_height: int, ev: Evidence) -> None:
        self.db.set(
            _key(_COMMITTED_PREFIX, ev), struct.pack(">q", commit_height)
        )
        self.metrics.committed_total.inc()
        key = _key(_PENDING_PREFIX, ev)
        if key in self._pending_keys:
            self.db.delete(key)
            self._pending_keys.discard(key)
            self._pending = [
                p for p in self._pending if p.hash() != ev.hash()
            ]
            self.metrics.pool_size.set(len(self._pending))

    def _load_pending(self) -> None:
        end = _PENDING_PREFIX[:-1] + bytes([_PENDING_PREFIX[-1] + 1])
        for key, value in self.db.iterate(start=_PENDING_PREFIX, end=end):
            ev = evidence_from_proto(value)
            self._pending.append(ev)
            self._pending_keys.add(key)

    def size(self) -> int:
        return len(self._pending)
