"""Evidence reactor — gossips pending evidence on channel 0x38.

reference: internal/evidence/reactor.go (channel :22, broadcast
:112-190). Each peer gets a task streaming the pool's pending list;
received evidence is verified by the pool before admission.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Dict, Tuple

from ..encoding.proto import FieldReader, ProtoWriter
from ..libs.log import get_logger
from ..libs.service import Service
from ..p2p.channel import Channel
from ..p2p.peermanager import PeerStatus
from ..p2p.types import ChannelDescriptor, Envelope, PeerError
from ..types.evidence import Evidence, evidence_from_proto, evidence_to_proto
from .pool import EvidenceError, EvidencePool

__all__ = [
    "EvidenceReactor",
    "EvidenceListMessage",
    "EVIDENCE_CHANNEL",
    "evidence_channel_descriptor",
]

EVIDENCE_CHANNEL = 0x38
_BROADCAST_INTERVAL = 1.0  # reapply pending list to peers at this cadence

# per-message evidence bound, enforced on BOTH sides: the receiver
# verifies at most this many items per message (each new item costs a
# 1/3-committee signature check — the per-message work must be bounded
# by config, not by the peer), and our broadcast loop sends at most
# this many per tick so the recv clamp never drops an honest sender's
# tail (a bigger backlog simply drains across ticks).
MAX_MSG_EVIDENCE = 64


@dataclass
class EvidenceListMessage:
    """proto/tendermint/evidence EvidenceList{evidence=1}."""

    evidence: Tuple[Evidence, ...] = ()

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        for ev in self.evidence:
            w.bytes(1, evidence_to_proto(ev))
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "EvidenceListMessage":
        r = FieldReader(data)
        return cls(
            evidence=tuple(evidence_from_proto(b) for b in r.get_all(1))
        )


def evidence_channel_descriptor():
    return ChannelDescriptor(
        channel_id=EVIDENCE_CHANNEL,
        message_type=EvidenceListMessage,
        priority=6,
        send_queue_capacity=16,
        recv_buffer_capacity=32,
        name="evidence",
    )


class EvidenceReactor(Service):
    def __init__(
        self,
        pool: EvidencePool,
        channel: Channel,
        peer_updates: asyncio.Queue,
    ) -> None:
        super().__init__(name="evidence.reactor", logger=get_logger("evidence.reactor"))
        self.pool: EvidencePool = pool
        self.channel = channel
        self.peer_updates = peer_updates
        self._peer_tasks: Dict[str, asyncio.Task] = {}

    async def on_start(self) -> None:
        self.spawn(self._peer_update_routine(), "peer-updates")
        self.spawn(self._recv_routine(), "recv")

    async def _peer_update_routine(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP:
                if update.node_id not in self._peer_tasks:
                    self._peer_tasks[update.node_id] = self.spawn(
                        self._broadcast_to_peer(update.node_id),
                        f"ev-gossip-{update.node_id[:8]}",
                    )
            elif update.status == PeerStatus.DOWN:
                t = self._peer_tasks.pop(update.node_id, None)
                if t is not None and not t.done():
                    t.cancel()
                self._tasks = [x for x in self._tasks if not x.done()]

    async def _recv_routine(self) -> None:
        async for envelope in self.channel:
            # per-message verification work is clamped: each NEW
            # evidence item costs a committee-sized signature check
            # (verify_commit_light_trusting; dup items short-circuit on
            # the pool's is_pending/is_committed probe first), so an
            # unclamped list let one message buy n_evidence × vset
            # work (tmcost cost-superlinear, first-run finding). Our
            # own sender paces to the same bound — one
            # MAX_MSG_EVIDENCE chunk per broadcast tick — so an honest
            # peer's items are never clamp-dropped here.
            for ev in envelope.message.evidence[:MAX_MSG_EVIDENCE]:
                try:
                    # validate-before-use (tmsafe safe-unvalidated-use):
                    # shape checks run before the pool touches state or
                    # store, same discipline as the consensus handlers
                    ev.validate_basic()
                    self.pool.add_evidence(ev)
                except ValueError as e:
                    self.logger.info(
                        "peer sent malformed evidence",
                        peer=envelope.from_peer[:12],
                        err=str(e),
                    )
                    await self.channel.send_error(
                        PeerError(node_id=envelope.from_peer, err=str(e))
                    )
                    break
                except EvidenceError as e:
                    # A lagging node can't verify future-height evidence:
                    # that is not peer misbehavior (reference gates sends
                    # on peer height; we tolerate on receive instead)
                    if "don't have header" in str(e) or "too old" in str(e):
                        self.logger.debug(
                            "cannot verify gossiped evidence yet",
                            err=str(e),
                        )
                        continue
                    self.logger.info(
                        "peer sent invalid evidence",
                        peer=envelope.from_peer[:12],
                        err=str(e),
                    )
                    await self.channel.send_error(
                        PeerError(node_id=envelope.from_peer, err=str(e))
                    )
                    break

    async def _broadcast_to_peer(self, peer_id: str) -> None:
        """Periodically (re)send pending evidence the peer may lack
        (reference: reactor.go:112-190 broadcastEvidenceLoop)."""
        sent: set = set()
        ticks = 0
        while True:
            pending, _ = self.pool.pending_evidence(1 << 20)
            fresh = [ev for ev in pending if ev.hash() not in sent]
            # pace sends to the receiver's per-message verification
            # clamp: one MAX_MSG_EVIDENCE chunk per tick. An oversized
            # single message would have its tail clamp-dropped on the
            # far side and re-offers would resend the SAME prefix —
            # chunked pacing is what makes the recv clamp lossless for
            # honest senders (items beyond the chunk go next tick)
            fresh = fresh[:MAX_MSG_EVIDENCE]
            if fresh:
                if self.channel.try_send(
                    Envelope(
                        message=EvidenceListMessage(evidence=tuple(fresh)),
                        to=peer_id,
                    )
                ):
                    sent.update(ev.hash() for ev in fresh)
            await asyncio.sleep(_BROADCAST_INTERVAL)
            ticks += 1
            if ticks % 10 == 0:
                # periodic re-offer: a peer that was too far behind to
                # verify the first send gets another chance once caught up
                sent.clear()
