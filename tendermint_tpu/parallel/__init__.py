"""Device-mesh parallelism for the TPU crypto path."""

from .sharding import (  # noqa: F401
    SIG_AXIS,
    ShardedEd25519Verifier,
    ShardedSr25519Verifier,
    make_mesh,
    sharded_batch_verify,
)

__all__ = [
    "SIG_AXIS",
    "ShardedEd25519Verifier",
    "ShardedSr25519Verifier",
    "make_mesh",
    "sharded_batch_verify",
]
