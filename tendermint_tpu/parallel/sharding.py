"""Device-mesh sharding for the batched crypto kernels.

The reference scales signature verification with CPU goroutines behind
crypto.BatchVerifier (reference: crypto/ed25519/ed25519.go:202-237); the
TPU-native framework scales it across a `jax.sharding.Mesh`. The batch
dimension of (pubkey, R, S, k) arrays is embarrassingly parallel, so the
layout is 1-D data-parallel over a single `sig` axis: XLA partitions the
whole verification program with zero cross-device traffic until the final
validity-bitmap gather, which rides ICI.

This module is also what the multi-chip dry-run exercises on a virtual CPU
mesh (`__graft_entry__.dryrun_multichip`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import ed25519_kernel as K
from ..ops import sr25519_kernel as SR

__all__ = [
    "make_mesh",
    "ShardedEd25519Verifier",
    "ShardedSr25519Verifier",
    "sharded_batch_verify",
]

SIG_AXIS = "sig"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name `sig`.

    Signature verification has no tensor/pipeline dimension worth sharding —
    each (pk, msg, sig) triple is independent — so the whole fleet is one
    data-parallel axis, the analog of the reference fanning votes across
    goroutines (internal/consensus/reactor.go:752).
    """
    devs = list(devices) if devices is not None else jax.devices()
    # tmlint: disable=dev-host-sync — devs is a host-side list of
    # Device handles (mesh topology), not a device array
    return Mesh(np.array(devs), (SIG_AXIS,))


class _MeshSharded:
    """Mixin partitioning a bucketed verifier's device program over a
    mesh. Buckets round up to a multiple of the mesh size so every
    device gets an equal shard; host-side packing is identical to the
    single-chip path — only placement changes. Subclasses name their
    kernel via _TILE_FN / _DEFAULT_SIZES; everything else (bucket
    rounding incl. oversized batches, the sharded jit) is shared so the
    two curves' device layouts cannot drift apart."""

    _TILE_FN = None  # staticmethod: the tile body to jit
    _DEFAULT_SIZES: Sequence[int] = ()

    def __init__(
        self,
        mesh: Mesh,
        bucket_sizes: Optional[Sequence[int]] = None,
    ) -> None:
        self.mesh = mesh
        n = mesh.devices.size
        sizes = bucket_sizes or self._DEFAULT_SIZES
        super().__init__(sorted({-(-s // n) * n for s in sizes}))

    def _bucket(self, n: int) -> int:
        b = super()._bucket(n)
        devs = self.mesh.devices.size
        return -(-b // devs) * devs  # oversized batches still pad to a multiple

    def _program(self, size: int):
        fn = self._compiled.get(size)
        if fn is None:
            # batch axis is MINOR (see field25519 layout note): the
            # program takes (32, N) pk bytes, (64, N) sig bytes, and a
            # (64|32, N) digest/challenge matrix, returns the (N,) bitmap
            vec = NamedSharding(self.mesh, P(SIG_AXIS))
            mat = NamedSharding(self.mesh, P(None, SIG_AXIS))
            fn = jax.jit(
                type(self)._TILE_FN,
                in_shardings=(mat, mat, mat),
                out_shardings=vec,
            )
            self._compiled[size] = fn
        return fn


class ShardedEd25519Verifier(_MeshSharded, K.Ed25519Verifier):
    """Ed25519Verifier whose device program is partitioned over a mesh."""

    _TILE_FN = staticmethod(K._verify_tile)
    _DEFAULT_SIZES = K.DEFAULT_BUCKET_SIZES


class ShardedSr25519Verifier(_MeshSharded, SR.Sr25519Verifier):
    """Sr25519Verifier partitioned over a mesh — same layout as the
    ed25519 variant: 1-D data-parallel over `sig`, host packing
    (merlin challenges + byte joins) unchanged. Reference analog:
    crypto/sr25519/batch.go behind the crypto.BatchVerifier seam."""

    _TILE_FN = staticmethod(SR._verify_tile_sr)
    _DEFAULT_SIZES = SR.DEFAULT_BUCKET_SIZES


def sharded_batch_verify(mesh, pubkeys, msgs, sigs) -> np.ndarray:
    """One-shot convenience: verify a batch across `mesh`."""
    return ShardedEd25519Verifier(mesh).verify(pubkeys, msgs, sigs)
