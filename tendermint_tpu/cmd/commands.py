"""Operator CLI: `python -m tendermint_tpu.cmd <command>`.

reference: cmd/tendermint/commands/ (init, run_node/start, light,
rollback, testnet, gen_validator, gen_node_key, show_validator,
show_node_id, reset, inspect, replay, version). argparse instead of
cobra; every command operates on a --home directory laid out exactly
like make_node expects (config/config.toml, config/genesis.json,
config/node_key.json, config/priv_validator_key.json, data/).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import sys
import time
from typing import List, Optional

from .. import version as _version
from ..config import Config, load_config, write_config
from ..crypto.ed25519 import PrivKeyEd25519


def _config_path(home: str) -> str:
    return os.path.join(os.path.expanduser(home), "config", "config.toml")


def _load_home(home: str) -> Config:
    path = _config_path(home)
    if os.path.exists(path):
        cfg = load_config(path)
    else:
        cfg = Config()
    cfg.base.home = home
    return cfg


# -- init (reference: commands/init.go) -------------------------------------


def cmd_init(args) -> int:
    from ..node.key import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    cfg = Config()
    cfg.base.home = args.home
    cfg.base.mode = args.mode
    cfg.base.moniker = args.moniker
    cfg.ensure_dirs()

    genesis_path = cfg.base.path(cfg.base.genesis_file)
    pv = None
    if args.mode == "validator":
        pv = FilePV.load_or_generate(
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
            key_type=getattr(args, "key", None) or "ed25519",
        )
    if os.path.exists(genesis_path):
        print(f"found genesis file {genesis_path}")
        genesis = GenesisDoc.from_file(genesis_path)
    else:
        chain_id = args.chain_id or f"test-chain-{os.urandom(3).hex()}"
        validators = []
        if pv is not None:
            validators.append(
                GenesisValidator(pub_key=pv.key.pub_key, power=10)
            )
        genesis = GenesisDoc(
            chain_id=chain_id,
            genesis_time_ns=time.time_ns(),
            validators=validators,
        )
        genesis.save_as(genesis_path)
        print(f"generated genesis file {genesis_path}")
    cfg.base.chain_id = genesis.chain_id
    NodeKey.load_or_generate(cfg.base.path(cfg.base.node_key_file))
    write_config(cfg, _config_path(args.home))
    print(f"initialized {args.mode} node in {cfg.base.root()}")
    return 0


# -- start (reference: commands/run_node.go) --------------------------------


def cmd_signer(args) -> int:
    """Run the external signing process against a node's
    [priv_validator] listen_addr: loads this home's FilePV (key +
    last-sign double-sign protection state) and serves signing
    requests over SecretConnection — or gRPC with --grpc (reference:
    the tmkms/SignerServer deployment shape; privval/signer.py
    SignerServer, signer_server.go)."""
    from ..libs.log import configure
    from ..privval import FilePV

    cfg = _load_home(args.home)
    configure(
        level=cfg.base.log_level,
        json_format=cfg.base.log_format == "json",
    )
    pv = FilePV.load(
        cfg.base.path(cfg.priv_validator.key_file),
        cfg.base.path(cfg.priv_validator.state_file),
    )
    print(
        f"signer for validator {pv.key.address.hex()} -> {args.addr}",
        flush=True,
    )

    async def run() -> None:
        if args.grpc:
            if args.node_id:
                print(
                    "--node-id applies to the socket transport only "
                    "(no identity check exists on grpc); refusing to "
                    "silently ignore it",
                    file=sys.stderr,
                )
                raise SystemExit(2)
            from ..privval.grpc import GRPCSignerServer

            srv = GRPCSignerServer(args.addr, cfg.base.chain_id, pv)
        else:
            from ..privval.signer import SignerServer

            srv = SignerServer(
                args.addr,
                pv,
                expected_node_id=args.node_id,
                chain_id=cfg.base.chain_id,
            )
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await srv.start()
        try:
            await stop.wait()
        finally:
            await srv.stop()

    asyncio.run(run())
    return 0


def cmd_start(args) -> int:
    from ..libs.log import configure
    from ..node import make_node

    cfg = _load_home(args.home)
    if args.moniker:
        cfg.base.moniker = args.moniker
    # without this, a started node emits nothing below WARNING —
    # unusable for operators and for e2e post-mortems
    configure(
        level=cfg.base.log_level,
        json_format=cfg.base.log_format == "json",
    )

    async def run() -> None:
        node = make_node(cfg)
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        # a failed start tears itself down (Node.on_start wraps
        # _start_impl in its own teardown), so only a SUCCESSFUL start
        # owes a stop() here
        await node.start()
        try:
            await stop.wait()
        finally:
            await node.stop()

    asyncio.run(run())
    return 0


# -- key / identity commands ------------------------------------------------


def cmd_gen_validator(args) -> int:
    """reference: commands/gen_validator.go — prints a fresh key
    (--key ed25519|secp256k1, matching GenFilePV's switch)."""
    from ..crypto.keys import generate_priv_key

    key_type = getattr(args, "key", None) or "ed25519"
    try:
        priv = generate_priv_key(key_type)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    out = {
        "address": priv.pub_key().address().hex().upper(),
        "pub_key": {"type": key_type, "value": priv.pub_key().bytes().hex()},
        "priv_key": {"type": key_type, "value": priv.bytes().hex()},
    }
    # tmct: ct-ok — gen_validator's documented contract IS emitting the
    # fresh private key JSON on stdout for the operator to install
    # (reference: commands/gen_validator.go prints priv_validator JSON)
    print(json.dumps(out, indent=2))
    return 0


def cmd_gen_node_key(args) -> int:
    """Write a fresh node key into --home and print its ID; refuses to
    overwrite (reference: commands/gen_node_key.go)."""
    from ..node.key import NodeKey

    cfg = _load_home(args.home)
    cfg.ensure_dirs()
    path = cfg.base.path(cfg.base.node_key_file)
    if os.path.exists(path):
        print(f"node key file already exists at {path}", file=sys.stderr)
        return 1
    nk = NodeKey(priv_key=PrivKeyEd25519.generate())
    nk.save_as(path)
    print(nk.node_id)
    return 0


def cmd_show_node_id(args) -> int:
    from ..node.key import NodeKey

    cfg = _load_home(args.home)
    nk = NodeKey.load_or_generate(cfg.base.path(cfg.base.node_key_file))
    print(nk.node_id)
    return 0


def cmd_show_validator(args) -> int:
    from ..privval import FilePV

    cfg = _load_home(args.home)
    pv = FilePV.load_or_generate(
        cfg.base.path(cfg.priv_validator.key_file),
        cfg.base.path(cfg.priv_validator.state_file),
    )
    print(
        json.dumps(
            {
                "type": pv.key.pub_key.type(),
                "value": pv.key.pub_key.bytes().hex(),
            }
        )
    )
    return 0


# -- rollback / reset (reference: commands/rollback.go, reset.go) ----------


def cmd_rollback(args) -> int:
    from ..state import StateStore
    from ..store.block_store import BlockStore
    from ..store.kv import open_db

    cfg = _load_home(args.home)
    try:
        with _ensure_node_stopped(cfg):
            db_dir = cfg.base.path(cfg.base.db_dir)
            state_db = open_db("state", cfg.base.db_backend, db_dir)
            block_db = open_db("blockstore", cfg.base.db_backend, db_dir)
            try:
                state_store = StateStore(state_db)
                block_store = BlockStore(block_db)
                new_state = state_store.rollback(block_store)
                print(
                    "rolled back state to height "
                    f"{new_state.last_block_height} "
                    f"app_hash {new_state.app_hash.hex()}"
                )
            finally:
                state_db.close()
                block_db.close()
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    return 0


def cmd_reset_unsafe(args) -> int:
    """Remove all data, keep config + keys; reset privval state
    (reference: commands/reset.go UnsafeResetAll)."""
    cfg = _load_home(args.home)
    try:
        with _ensure_node_stopped(cfg):
            data = cfg.base.path("data")
            if os.path.isdir(data):
                shutil.rmtree(data)
            os.makedirs(data, exist_ok=True)
            os.makedirs(
                os.path.dirname(cfg.base.path(cfg.consensus.wal_file)),
                exist_ok=True,
            )
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    print(f"removed all data in {data} (config and keys kept)")
    return 0


# -- testnet (reference: commands/testnet.go) -------------------------------


def cmd_testnet(args) -> int:
    from ..node.key import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    n = args.validators
    out = os.path.expanduser(args.output_dir)
    privs = [PrivKeyEd25519.generate() for _ in range(n)]
    genesis = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    )
    cfgs: List[Config] = []
    node_ids: List[str] = []
    for i in range(n):
        cfg = Config()
        cfg.base.home = os.path.join(out, f"node{i}")
        cfg.base.chain_id = genesis.chain_id
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i + 1}"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            privs[i],
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        nk = NodeKey.load_or_generate(cfg.base.path(cfg.base.node_key_file))
        node_ids.append(nk.node_id)
        cfgs.append(cfg)
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = ",".join(
            f"{node_ids[j]}@127.0.0.1:{args.starting_port + 2 * j}"
            for j in range(n)
            if j != i
        )
        write_config(cfg, _config_path(cfg.base.home))
    print(
        f"wrote {n}-validator testnet for chain {genesis.chain_id} "
        f"under {out}"
    )
    return 0


# -- light (reference: commands/light.go — verifying proxy) -----------------


def cmd_light(args) -> int:
    from ..light import Client, LightStore, TrustOptions
    from ..light.provider import HTTPProvider
    from ..rpc.jsonrpc import (
        INVALID_PARAMS,
        JSONRPCServer,
        RPCError,
    )
    from ..store.kv import open_db

    home = os.path.expanduser(args.home)
    os.makedirs(os.path.join(home, "light"), exist_ok=True)
    db = open_db("light", "sqlite", os.path.join(home, "light"))

    async def run() -> None:
        primary = HTTPProvider(args.primary)
        witnesses = [HTTPProvider(w) for w in args.witness or []]
        client = Client(
            args.chain_id,
            TrustOptions(
                period_ns=int(args.trust_period * 1e9),
                height=args.trust_height,
                hash=bytes.fromhex(args.trust_hash),
            ),
            primary,
            witnesses,
            LightStore(db),
            sequential=args.sequential,
        )

        from ..rpc.core import encode

        async def _verified(height: int):
            return await client.verify_light_block_at_height(
                height, time.time_ns()
            )

        async def route_header(req):
            h = int(req.params.get("height", 0))
            if h <= 0:
                raise RPCError(INVALID_PARAMS, "height required")
            lb = await _verified(h)
            return {"header": encode(lb.signed_header.header)}

        async def route_commit(req):
            h = int(req.params.get("height", 0))
            if h <= 0:
                raise RPCError(INVALID_PARAMS, "height required")
            lb = await _verified(h)
            return {
                "signed_header": encode(lb.signed_header),
                "canonical": True,
            }

        async def route_light_block(req):
            h = int(req.params.get("height", 0))
            if h <= 0:
                raise RPCError(INVALID_PARAMS, "height required")
            lb = await _verified(h)
            return {"height": h, "light_block": lb.to_proto().hex()}

        async def route_status(req):
            lb = client.store.latest_light_block()
            latest = lb.height if lb is not None else 0
            return {
                "chain_id": args.chain_id,
                "trusted_height": latest,
                "primary": args.primary,
                "witnesses": [w.id() for w in witnesses],
            }

        server = JSONRPCServer(
            {
                "header": route_header,
                "commit": route_commit,
                "light_block": route_light_block,
                "status": route_status,
            }
        )
        host, _, port = args.laddr.replace("tcp://", "").rpartition(":")
        await server.start(host or "127.0.0.1", int(port))
        print(
            f"light client proxy for {args.chain_id} on "
            f"{host}:{server.bound_port} (primary {args.primary})",
            flush=True,
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    try:
        asyncio.run(run())
    finally:
        db.close()
    return 0


# -- inspect (reference: internal/inspect) ----------------------------------


def cmd_inspect(args) -> int:
    """Read-only RPC over a STOPPED node's data directories."""
    cfg = _load_home(args.home)
    # hold the advisory lock for inspect's whole lifetime: a node
    # (or reset/rollback) starting mid-serve must fail fast, not
    # mutate the stores underneath us. Only the lock acquisition maps
    # to the one-line refusal; serve-time errors propagate with their
    # tracebacks.
    guard = _ensure_node_stopped(cfg)
    try:
        guard.__enter__()
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    try:
        return _inspect_serve(cfg, args)
    finally:
        guard.__exit__(None, None, None)


def _inspect_serve(cfg: Config, args) -> int:
    from ..rpc.core import Environment
    from ..rpc.jsonrpc import JSONRPCServer
    from ..state import StateStore
    from ..state.indexer import KVSink
    from ..store.block_store import BlockStore
    from ..store.kv import open_db
    from ..types.genesis import GenesisDoc

    db_dir = cfg.base.path(cfg.base.db_dir)
    dbs = [open_db(n, cfg.base.db_backend, db_dir)
           for n in ("blockstore", "state", "tx_index")]
    genesis = None
    gpath = cfg.base.path(cfg.base.genesis_file)
    if os.path.exists(gpath):
        genesis = GenesisDoc.from_file(gpath)
    env = Environment(
        chain_id=genesis.chain_id if genesis else "",
        block_store=BlockStore(dbs[0]),
        state_store=StateStore(dbs[1]),
        genesis=genesis,
        event_sinks=[KVSink(dbs[2])],
        cfg=cfg,
    )
    read_only = {
        k: v
        for k, v in env.routes().items()
        if k
        in (
            "health", "status", "genesis", "genesis_chunked", "blockchain",
            "header", "header_by_hash", "block", "block_by_hash",
            "block_results", "commit", "validators", "consensus_params",
            "tx", "tx_search", "block_search", "light_block",
        )
    }

    async def run() -> None:
        server = JSONRPCServer(read_only)
        host, _, port = (
            args.laddr.replace("tcp://", "").rpartition(":")
        )
        await server.start(host or "127.0.0.1", int(port))
        print(
            f"inspect server on {host}:{server.bound_port} "
            f"(read-only routes: {', '.join(sorted(read_only))})",
            flush=True,
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    try:
        asyncio.run(run())
    finally:
        for db in dbs:
            db.close()
    return 0


# -- replay (reference: commands/replay.go) ---------------------------------


def cmd_replay(args) -> int:
    """Re-execute stored blocks through a fresh builtin app (sanity /
    debugging tool; reference: consensus/replay_file.go). With
    --console, drop into the interactive WAL playback console after
    block replay (reference: replay_file.go:54,188-193)."""
    from ..abci.client import local_creator
    from ..abci.kvstore import KVStoreApplication
    from ..abci.proxy import AppConns
    from ..consensus.replay import Handshaker
    from ..state import StateStore, state_from_genesis
    from ..store.block_store import BlockStore
    from ..store.kv import open_db
    from ..types.genesis import GenesisDoc

    cfg = _load_home(args.home)
    db_dir = cfg.base.path(cfg.base.db_dir)
    block_db = open_db("blockstore", cfg.base.db_backend, db_dir)
    state_db = open_db("state", cfg.base.db_backend, db_dir)
    genesis = GenesisDoc.from_file(cfg.base.path(cfg.base.genesis_file))

    async def run() -> None:
        block_store = BlockStore(block_db)
        # the node's REAL state store (the reference's
        # newConsensusStateForReplay does the same, replay_file.go:295):
        # the handshake decision table assumes state tracks the store,
        # and replays every stored block into the fresh app
        state_store = StateStore(state_db)
        state = state_store.load()
        if state is None:
            state = state_from_genesis(genesis)
            state_store.save(state)
        proxy = AppConns(local_creator(KVStoreApplication()))
        await proxy.start()
        try:
            handshaker = Handshaker(
                state_store, state, block_store, genesis
            )
            await handshaker.handshake(proxy.consensus)
            final = state_store.load()
            print(
                f"replayed {block_store.height()} blocks; final height "
                f"{final.last_block_height} app_hash "
                f"{final.app_hash.hex()}"
            )
            if getattr(args, "console", False):
                await _replay_console(cfg, final, proxy, block_store)
        finally:
            await proxy.stop()

    try:
        asyncio.run(run())
    finally:
        block_db.close()
        state_db.close()
    return 0


async def _build_replay_cs(cfg, state, proxy, block_store):
    """A ConsensusState in replay mode over the handshaken state — no
    privval, no live WAL, ticker started so scheduled timeouts are
    tracked (their firings go nowhere: the receive loop never runs;
    the console feeds recorded TimeoutInfo records instead)."""
    from ..config import MempoolConfig
    from ..consensus import ConsensusState
    from ..mempool import TxMempool
    from ..state import StateStore
    from ..state.execution import BlockExecutor
    from ..store.kv import MemKV

    state_store = StateStore(MemKV())
    state_store.save(state)
    mempool = TxMempool(proxy.mempool, MempoolConfig())
    block_exec = BlockExecutor(
        state_store, proxy.consensus, mempool, block_store=block_store
    )
    cs = ConsensusState(
        cfg.consensus, state, block_exec, block_store, privval=None,
        replay_mode=True,
    )
    await cs.ticker.start()
    return cs


def _stdin_reader_queue(loop, prompt: str = "") -> "asyncio.Queue":
    """Feed stdin lines into an asyncio.Queue from a daemon thread —
    the one sanctioned way a console coroutine reads the operator.
    Reading inline would park the event loop it shares with the
    proxy/ABCI clients (tmlive: live-block-in-main-loop); a
    default-executor hop would make asyncio.run's teardown join a
    thread still parked in input(), hanging Ctrl-C until the operator
    pressed Enter. A daemon thread is joined by nobody. EOF (or a
    loop that closed while the thread was parked) ends the stream
    with a None sentinel."""
    import threading

    lines: asyncio.Queue = asyncio.Queue()

    def _post(item) -> None:
        try:
            loop.call_soon_threadsafe(lines.put_nowait, item)
        except RuntimeError:
            pass  # loop already closed; the console is gone

    def _reader() -> None:
        while True:
            try:
                # tmlive: block-ok — dedicated stdin reader: waiting
                # for the operator is this daemon thread's whole job;
                # parking HERE is what keeps the event loop free
                raw = input(prompt)
            except Exception:  # EOFError / closed or broken stdin
                _post(None)
                return
            _post(raw)

    threading.Thread(target=_reader, daemon=True).start()
    return lines


def _console_rs(cs, field: str) -> str:
    """One rs-console view (reference: replay_file.go:259-287)."""
    rs = cs.rs
    if field == "short" or field == "":
        return f"{rs.height}/{rs.round}/{rs.step}"
    if field == "locked_round":
        return str(rs.locked_round)
    if field == "locked_block":
        return (
            rs.locked_block.hash().hex()
            if rs.locked_block is not None
            else "nil"
        )
    if field == "proposal":
        return repr(rs.proposal)
    if field == "validators":
        return "\n".join(
            f"{v.address.hex()} power={v.voting_power}"
            for v in rs.validators.validators
        )
    if field == "votes":
        out = []
        for r in range(rs.round + 1):
            pv = rs.votes.prevotes(r)
            pc = rs.votes.precommits(r)
            out.append(
                f"round {r}: prevotes={pv.bit_array() if pv else None} "
                f"precommits={pc.bit_array() if pc else None}"
            )
        return "\n".join(out)
    return f"unknown rs field {field!r}"


async def _replay_console(cfg, state, proxy, block_store) -> None:
    """Interactive WAL playback (reference: replay_file.go console:
    next [N], back [N], rs [field], n, quit). Steps the current
    height's recorded inputs one message at a time through a
    replay-mode ConsensusState; `back` rebuilds the state machine and
    replays up to count-N (the reference does the same — the state
    machine cannot run backwards)."""
    from ..consensus.wal import WAL

    wal = WAL(cfg.base.path(cfg.consensus.wal_file))
    end_height = state.last_block_height
    msgs = wal.search_for_end_height(end_height)
    if msgs is None:
        # distinct from an empty tail: the marker is absent (missing,
        # truncated, or corrupt WAL — search refuses gapped histories)
        print(
            f"cannot replay: WAL has no EndHeight({end_height}) marker "
            "(missing or corrupt WAL)"
        )
        return
    print(
        f"console: {len(msgs)} WAL records after EndHeight({end_height}); "
        "commands: next [N] | back [N] | rs [short|locked_round|"
        "locked_block|proposal|validators|votes] | n | quit"
    )
    cs = await _build_replay_cs(cfg, state, proxy, block_store)
    pos = 0

    async def apply_one() -> bool:
        nonlocal pos
        if pos >= len(msgs):
            print("end of WAL")
            return False
        m = msgs[pos]
        pos += 1
        try:
            await cs.replay_one(m)
        except RuntimeError as e:
            # e.g. an EndHeight mid-tail: store/WAL inconsistency —
            # surface it, exactly like crash catchup would
            print(f"replay error at #{pos}: {e}")
            return False
        print(f"#{pos}: {type(m).__name__} -> {_console_rs(cs, 'short')}")
        return True

    lines = _stdin_reader_queue(asyncio.get_running_loop(), prompt="> ")
    while True:
        line = await lines.get()
        if line is None:  # EOF
            break
        tokens = line.split()
        if not tokens:
            continue
        cmd, rest = tokens[0], tokens[1:]
        if cmd == "quit" or cmd == "q":
            break
        elif cmd == "next":
            count = int(rest[0]) if rest and rest[0].isdigit() else 1
            for _ in range(count):
                if not await apply_one():
                    break
        elif cmd == "back":
            count = int(rest[0]) if rest and rest[0].isdigit() else 1
            target = max(0, pos - count)
            await cs.ticker.stop()
            cs = await _build_replay_cs(cfg, state, proxy, block_store)
            pos = 0
            for _ in range(target):
                await apply_one()
            print(f"rewound to #{pos}")
        elif cmd == "rs":
            print(_console_rs(cs, rest[0] if rest else ""))
        elif cmd == "n":
            print(pos)
        else:
            print(f"unknown command {cmd!r}")
    await cs.ticker.stop()


def cmd_debug_dump(args) -> int:
    """Collect a diagnostic bundle from a node's home into a tarball:
    config, genesis, store heights + state summary, a WAL copy, and a
    live /metrics scrape when reachable (reference:
    cmd/tendermint/commands/debug/{dump,io}.go)."""
    import io
    import tarfile
    import urllib.request

    from ..state import StateStore
    from ..store.block_store import BlockStore
    from ..store.kv import open_db

    cfg = _load_home(args.home)
    out_path = os.path.expanduser(args.output)

    def add_bytes(tar, name, data: bytes):
        info = tarfile.TarInfo(name)
        info.size = len(data)
        info.mtime = int(time.time())
        tar.addfile(info, io.BytesIO(data))

    with tarfile.open(out_path, "w:gz") as tar:
        for rel in (
            "config/config.toml",
            "config/genesis.json",
        ):
            path = cfg.base.path(rel)
            if os.path.exists(path):
                tar.add(path, arcname=os.path.basename(path))
        wal_path = cfg.base.path(cfg.consensus.wal_file)
        if os.path.exists(wal_path):
            tar.add(wal_path, arcname="cs.wal")
        # rotated WAL chunks (autofile-group analog) ride along too; a
        # live node may prune a chunk between the listing and the add
        from ..consensus.wal import wal_group_files

        for chunk in wal_group_files(wal_path):
            if chunk != wal_path:
                try:
                    tar.add(
                        chunk,
                        arcname="cs.wal." + chunk.rsplit(".", 1)[-1],
                    )
                except OSError:
                    pass  # pruned mid-collection
        # store summary (opens read-only copies of the DBs)
        summary = {"collected_at": time.time()}
        try:
            db_dir = cfg.base.path(cfg.base.db_dir)
            bdb = open_db("blockstore", cfg.base.db_backend, db_dir)
            sdb = open_db("state", cfg.base.db_backend, db_dir)
            try:
                bs = BlockStore(bdb)
                st = StateStore(sdb).load()
                summary["block_store"] = {
                    "base": bs.base(),
                    "height": bs.height(),
                }
                if st is not None:
                    summary["state"] = {
                        "height": st.last_block_height,
                        "app_hash": st.app_hash.hex(),
                        "validators": st.validators.size(),
                        "chain_id": st.chain_id,
                    }
            finally:
                bdb.close()
                sdb.close()
        except Exception as e:
            summary["store_error"] = repr(e)
        # XLA profiler trace of a representative device batch
        # (SURVEY §5: the debug bundle carries device traces the way
        # the reference's carries pprof profiles)
        if getattr(args, "device_profile", False):
            try:
                summary["device_profile"] = _capture_device_profile(tar)
            except Exception as e:
                add_bytes(
                    tar, "device_profile_error.txt", repr(e).encode()
                )
        add_bytes(
            tar, "summary.json", json.dumps(summary, indent=2).encode()
        )
        # this process's span-trace ring as Chrome-trace JSON (empty
        # traceEvents when tracing was never enabled): in-process
        # embedders and the --device-profile capture above leave spans
        # here the way the reference's bundle carries pprof profiles
        from ..libs import trace as _trace

        add_bytes(tar, "trace.json", _trace.to_chrome_trace().encode())
        # SLO-breach exemplars: each slow request's span tree (empty
        # list when exemplar capture was never enabled) — the flame
        # decomposition behind a p99 outlier, see docs/load.md
        add_bytes(
            tar,
            "slow_requests.json",
            _trace.exemplars_to_json().encode(),
        )
        # consensus flight-recorder timeline (docs/observability.md):
        # the live ring over RPC when the node answers, else the WAL
        # reconstruction — a wedged/dead node still explains itself
        timeline_doc = None
        if getattr(args, "rpc_url", ""):
            try:
                # follow the seq cursor: one page is at most
                # TIMELINE_PAGE_CAP events, the resident ring holds up
                # to consensus_timeline_capacity — the bundle wants
                # all of it (page count bounded by capacity/cap + 1)
                base = args.rpc_url.rstrip("/")
                doc, cursor = None, 0
                for _ in range(64):
                    with urllib.request.urlopen(
                        f"{base}/consensus_timeline?after_seq={cursor}",
                        timeout=5,
                    ) as resp:
                        page = json.loads(resp.read())["result"]
                    if doc is None:
                        doc = page
                    else:
                        doc["events"].extend(page["events"])
                        doc["next_seq"] = page["next_seq"]
                    if not page["events"]:
                        break
                    cursor = page["next_seq"]
                if doc is not None and doc.get("events"):
                    # a disabled or just-reset ring answers with zero
                    # events — the WAL reconstruction below still has
                    # the story, so only a non-empty ring wins
                    doc["source"] = "rpc_ring"
                    timeline_doc = json.dumps(doc).encode()
            except Exception:
                timeline_doc = None  # fall through to the WAL
        if timeline_doc is None:
            try:
                from ..consensus.timeline import (
                    events_from_wal,
                    summarize_heights,
                )

                events = events_from_wal(wal_path)
                timeline_doc = json.dumps(
                    {
                        "source": "wal_reconstruction",
                        "events": events,
                        "heights": summarize_heights(events),
                    }
                ).encode()
            except Exception as e:
                timeline_doc = json.dumps(
                    {"timeline_error": repr(e)}
                ).encode()
        add_bytes(tar, "timeline.json", timeline_doc)
        # profiling plane (libs/profiler.py): the live node's
        # aggregated wall-clock samples over RPC when reachable (paged
        # under PROFILE_PAGE_CAP), else this process's own profiler
        # state — in-process embedders that profiled leave their table
        # here next to trace.json
        profile_doc = None
        if getattr(args, "rpc_url", ""):
            try:
                base = args.rpc_url.rstrip("/")
                with urllib.request.urlopen(
                    f"{base}/profile?action=status", timeout=5
                ) as resp:
                    status = json.loads(resp.read())["result"]
                stacks, cursor = [], 0
                for _ in range(64):
                    with urllib.request.urlopen(
                        f"{base}/profile?action=snapshot&after={cursor}",
                        timeout=5,
                    ) as resp:
                        page = json.loads(resp.read())["result"]
                    stacks.extend(page["stacks"])
                    if not page["stacks"]:
                        break
                    cursor = page["next"]
                if status["stats"].get("samples_total"):
                    # a never-enabled profiler answers with zero
                    # samples — the in-process fallback below may
                    # still have a table
                    profile_doc = json.dumps(
                        {
                            "source": "rpc",
                            "stats": status["stats"],
                            "subsystem_shares": status.get(
                                "subsystem_shares", {}
                            ),
                            "stacks": stacks,
                        }
                    ).encode()
            except Exception:
                profile_doc = None  # fall through to in-process
        if profile_doc is None:
            from ..libs import profiler as _profiler

            profile_doc = _profiler.to_profile_json().encode()
        add_bytes(tar, "profile.json", profile_doc)
        # live metrics scrape, best effort
        if args.metrics_url:
            try:
                with urllib.request.urlopen(
                    args.metrics_url, timeout=5
                ) as resp:
                    add_bytes(tar, "metrics.txt", resp.read())
            except Exception as e:
                add_bytes(
                    tar, "metrics_error.txt", repr(e).encode()
                )
        # live RPC scrapes (reference debug/dump.go dumpDebugData):
        # status, consensus state, net_info
        if getattr(args, "rpc_url", ""):
            for route in ("status", "consensus_state", "net_info"):
                try:
                    with urllib.request.urlopen(
                        args.rpc_url.rstrip("/") + "/" + route, timeout=5
                    ) as resp:
                        add_bytes(tar, f"{route}.json", resp.read())
                except Exception as e:
                    add_bytes(
                        tar, f"{route}_error.txt", repr(e).encode()
                    )
    print(f"wrote debug bundle to {out_path}")
    # kill variant (reference: cmd/tendermint/commands/debug/kill.go —
    # collect the bundle, THEN abort the running node so its final
    # state is captured alongside the crash)
    pid = getattr(args, "kill", 0)
    if pid:
        import signal as _signal

        os.kill(int(pid), _signal.SIGABRT)
        print(f"sent SIGABRT to pid {pid}")
    return 0


def _capture_device_profile(tar, n: int = 256) -> dict:
    """Run one warmed batch through the device verifier under the XLA
    profiler and pack the trace into the bundle (TensorBoard-loadable)."""
    import tempfile

    import jax

    from ..crypto.ed25519 import PrivKeyEd25519
    from ..ops.ed25519_kernel import Ed25519Verifier

    pks, msgs, sigs = [], [], []
    for i in range(n):
        priv = PrivKeyEd25519.from_seed(i.to_bytes(4, "big") + b"\x51" * 28)
        msg = b"debug-profile-%d" % i
        pks.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(priv.sign(msg))
    verifier = Ed25519Verifier()
    t0 = time.perf_counter()
    ok = verifier.verify(pks, msgs, sigs)  # warm-up compiles
    compile_s = time.perf_counter() - t0
    if not bool(ok.all()):
        raise RuntimeError("profile batch failed to verify")
    with tempfile.TemporaryDirectory(prefix="tt-device-profile-") as prof_dir:
        with jax.profiler.trace(prof_dir):
            t0 = time.perf_counter()
            verifier.verify(pks, msgs, sigs)
            run_s = time.perf_counter() - t0
        for root, _dirs, files in os.walk(prof_dir):
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, prof_dir)
                tar.add(
                    full, arcname=os.path.join("device_profile", rel)
                )
    return {
        "backend": jax.default_backend(),
        "batch": n,
        "warmup_s": round(compile_s, 3),
        "profiled_run_s": round(run_s, 4),
    }


def cmd_e2e(args) -> int:
    """Manifest-driven e2e testnets from the command line (reference:
    the test/e2e runner + generator binaries)."""
    from ..e2e import Manifest, generate, run_manifest

    if args.e2e_cmd == "generate":
        if args.manifest:
            print(
                "e2e generate takes no manifest argument",
                file=sys.stderr,
            )
            return 1
        out = os.path.expanduser(args.output_dir)
        os.makedirs(out, exist_ok=True)
        for i, m in enumerate(generate(seed=args.seed, count=args.count)):
            path = os.path.join(out, f"gen-{args.seed}-{i}.toml")
            with open(path, "w") as f:
                f.write(m.to_toml())
            print(path)
        return 0
    # run
    if not args.manifest:
        print("e2e run requires a manifest path", file=sys.stderr)
        return 1
    m = Manifest.from_toml(os.path.expanduser(args.manifest))
    import tempfile

    home = args.home_dir or tempfile.mkdtemp(prefix="tt-e2e-")
    mode = "processes" if args.processes else "in-process"
    print(f"running {m.chain_id}: {len(m.nodes)} nodes ({mode}) -> {home}")
    if args.processes:
        from ..e2e.process_runner import run_manifest_processes

        rep = run_manifest_processes(m, home, timeout=args.timeout)
    else:
        rep = run_manifest(m, home, timeout=args.timeout)
    print(
        json.dumps(
            {
                "ok": rep.ok,
                "reached_height": rep.reached_height,
                "blocks": rep.blocks,
                "block_interval_avg_s": round(rep.interval_avg, 3),
                "block_interval_stddev_s": round(rep.interval_stddev, 3),
                "txs_submitted": rep.txs_submitted,
                "txs_committed": rep.txs_committed,
                "evidence_heights": rep.evidence_heights,
                "state_synced": rep.state_synced,
                "failures": rep.failures,
            },
            indent=2,
        )
    )
    return 0 if rep.ok else 1


def cmd_key_migrate(args) -> int:
    """Translate legacy string-prefixed database keys to the current
    binary layout (reference: cmd/tendermint/commands/key_migrate.go +
    scripts/keymigrate/migrate.go). Resumable: already-migrated keys
    are skipped."""
    from ..store.keymigrate import CONTEXTS, migrate_db
    from ..store.kv import open_db

    cfg = _load_home(args.home)
    try:
        with _ensure_node_stopped(cfg):
            db_dir = cfg.base.path(cfg.base.db_dir)
            total = 0
            # iterate the migrator's own dispatch table so the command
            # cannot drift from it (contexts born in the current layout
            # have no entry and are not opened — open_db would create
            # stray empty database files)
            for i, ctx in enumerate(CONTEXTS):
                db = open_db(ctx, cfg.base.db_backend, db_dir)
                try:
                    n = migrate_db(db, ctx)
                finally:
                    db.close()
                print(
                    f"[{i + 1}/{len(CONTEXTS)}] {ctx}: "
                    f"{n} key(s) migrated"
                )
                total += n
            print(f"completed database migration: {total} key(s)")
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    return 0


def cmd_version(args) -> int:
    print(_version.__version__)
    return 0


class _ensure_node_stopped:
    """Context manager for offline data-dir commands: refuse when a
    RUNNING node holds the advisory LOCK, and hold the lock ourselves
    for the command's duration so a node started mid-command fails
    fast instead of racing the same databases
    (counterpart of node.Node._acquire_data_lock)."""

    def __init__(self, cfg: Config) -> None:
        self.cfg = cfg
        self.lock = os.path.join(
            cfg.base.path(cfg.base.db_dir), "LOCK"
        )
        self._fd: int | None = None

    def __enter__(self) -> "_ensure_node_stopped":
        from ..node.node import acquire_pid_lock

        try:
            self._fd = acquire_pid_lock(self.lock)
        except RuntimeError as e:
            raise RuntimeError(
                f"node appears to be running ({e}); stop it first"
            ) from None
        return self

    def __exit__(self, *exc) -> None:
        if self._fd is not None:
            from ..node.node import release_pid_lock

            release_pid_lock(self.lock, self._fd)
            self._fd = None


def cmd_reindex_event(args) -> int:
    """Rebuild the tx/block event indexes from stored blocks and saved
    ABCI responses — recovery after index corruption or a sink config
    change (reference: cmd/tendermint/commands/reindex_event.go)."""
    cfg = _load_home(args.home)
    try:
        with _ensure_node_stopped(cfg):
            return _reindex_events(cfg, args)
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1


def _reindex_events(cfg: Config, args) -> int:
    from ..state import StateStore
    from ..state.indexer import KVSink, TxResult
    from ..store.block_store import BlockStore
    from ..store.kv import open_db

    db_dir = cfg.base.path(cfg.base.db_dir)
    bdb = open_db("blockstore", cfg.base.db_backend, db_dir)
    sdb = open_db("state", cfg.base.db_backend, db_dir)
    idb = open_db("tx_index", cfg.base.db_backend, db_dir)
    try:
        bs = BlockStore(bdb)
        st = StateStore(sdb)
        sink = KVSink(idb)
        base, tip = bs.base(), bs.height()
        start = args.start_height or base
        end = args.end_height or tip
        if start < base or end > tip or start > end:
            print(
                f"invalid range [{start}, {end}]: stored blocks span "
                f"[{base}, {tip}]",
                file=sys.stderr,
            )
            return 1
        done = skipped = 0
        for height in range(start, end + 1):
            block = bs.load_block(height)
            resp = st.load_abci_responses(height)
            if block is None or resp is None:
                skipped += 1
                continue
            if len(resp.deliver_txs) != len(block.txs):
                # partial/corrupt responses: indexing a truncated zip
                # would silently drop txs while claiming success
                print(
                    f"height {height}: {len(block.txs)} txs but "
                    f"{len(resp.deliver_txs)} saved results; skipped",
                    file=sys.stderr,
                )
                skipped += 1
                continue
            events = list(
                getattr(resp.begin_block_obj, "events", ()) or ()
            )
            events += list(
                getattr(resp.end_block_obj, "events", ()) or ()
            )
            sink.index_block_events(height, events)
            results = [
                TxResult(height=height, index=i, tx=tx, result=r)
                for i, (tx, r) in enumerate(
                    zip(block.txs, resp.deliver_tx_objs)
                )
            ]
            if results:
                sink.index_tx_events(results)
            done += 1
        if done == 0:
            print(
                f"no heights reindexed in [{start}, {end}]: stored "
                "blocks or ABCI responses are missing (pruned?)",
                file=sys.stderr,
            )
            return 1
        print(
            f"reindexed {done} heights in [{start}, {end}]"
            + (f" ({skipped} skipped: missing data)" if skipped else "")
        )
        return 0
    finally:
        bdb.close()
        sdb.close()
        idb.close()


def _parse_tx(s: str) -> bytes:
    """0x-prefixed hex, else the raw string bytes (reference:
    abci/cmd/abci-cli stringOrHexToBytes)."""
    if s.startswith("0x") or s.startswith("0X"):
        return bytes.fromhex(s[2:])
    return s.encode()


async def _abci_exec(client, cmd: str, operand: str, path: str) -> None:
    """One abci-cli style request/response (reference: abci/cmd/
    abci-cli — echo/info/deliver_tx/check_tx/commit/query)."""
    from ..abci import types as T

    def show(code=None, data=None, log="", info=""):
        if code is not None:
            status = "OK" if code == 0 else f"{code}"
            print(f"-> code: {status}")
        if log:
            print(f"-> log: {log}")
        if info:
            print(f"-> info: {info}")
        if data:
            try:
                print(f"-> data: {data.decode()}")
            except UnicodeDecodeError:
                pass
            print(f"-> data.hex: 0x{data.hex().upper()}")

    if cmd == "echo":
        resp = await client.echo(operand)
        print(f"-> data: {resp.message}")
    elif cmd == "info":
        resp = await client.info(T.RequestInfo())
        print(f"-> data: {resp.data}")
        print(f"-> version: {resp.version}")
        print(f"-> last_block_height: {resp.last_block_height}")
        print(f"-> last_block_app_hash: 0x{resp.last_block_app_hash.hex()}")
    elif cmd == "deliver-tx":
        resp = await client.deliver_tx(
            T.RequestDeliverTx(tx=_parse_tx(operand))
        )
        show(resp.code, resp.data, resp.log, resp.info)
    elif cmd == "check-tx":
        resp = await client.check_tx(
            T.RequestCheckTx(tx=_parse_tx(operand))
        )
        show(resp.code, resp.data, resp.log, resp.info)
    elif cmd == "commit":
        resp = await client.commit()
        show(0, resp.data)
    elif cmd == "query":
        resp = await client.query(
            T.RequestQuery(data=_parse_tx(operand), path=path)
        )
        show(resp.code, None, resp.log, resp.info)
        print(f"-> key: {resp.key.decode(errors='replace')}")
        print(f"-> value: {resp.value.decode(errors='replace')}")
    else:
        raise ValueError(f"unknown abci command {cmd!r}")


def cmd_abci(args) -> int:
    """Drive an out-of-process ABCI application over its socket, or
    serve the builtin kvstore app (reference: abci/cmd/ — the abci-cli
    tool with its console and example-app server)."""
    from ..abci.client import SocketClient
    from ..abci.kvstore import KVStoreApplication
    from ..abci.server import SocketServer

    if args.grpc:
        from ..abci.grpc_transport import GRPCClient, GRPCServer

        make_server = GRPCServer
        make_client = GRPCClient
    else:
        make_server = SocketServer
        make_client = SocketClient

    async def serve_kvstore():
        srv = make_server(
            args.addr,
            KVStoreApplication(
                snapshot_interval=args.snapshot_interval
            ),
        )
        await srv.start()
        print(f"kvstore app listening on {args.addr}", flush=True)
        try:
            await asyncio.Event().wait()
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass
        finally:
            await srv.stop()
        return 0

    async def drive():
        client = make_client(args.addr, must_connect=True)
        await client.start()
        try:
            if args.abci_cmd == "console":
                print(
                    "abci console: echo|info|deliver-tx|check-tx|"
                    "commit|query <operand>  (ctrl-d to exit)",
                    flush=True,
                )
                lines = _stdin_reader_queue(asyncio.get_running_loop())
                while True:
                    line = await lines.get()
                    if line is None:
                        break
                    parts = line.strip().split(None, 1)
                    if not parts:
                        continue
                    try:
                        await _abci_exec(
                            client,
                            parts[0],
                            parts[1] if len(parts) > 1 else "",
                            args.path,
                        )
                    except Exception as e:
                        print(f"-> error: {e}", flush=True)
            else:
                try:
                    await _abci_exec(
                        client, args.abci_cmd, args.operand, args.path
                    )
                except ValueError as e:
                    print(f"-> error: {e}", file=sys.stderr)
                    return 1
            return 0
        finally:
            await client.stop()

    if args.abci_cmd == "kvstore":
        return asyncio.run(serve_kvstore())
    return asyncio.run(drive())


# -- parser -----------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tendermint_tpu",
        description="TPU-native BFT consensus node (tendermint-compatible)",
    )
    p.add_argument(
        "--home",
        default=os.environ.get("TMHOME", "~/.tendermint_tpu"),
        help="node home directory",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize a node home directory")
    sp.add_argument(
        "mode",
        nargs="?",
        default="validator",
        choices=["validator", "full", "seed"],
    )
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--moniker", default="anonymous")
    sp.add_argument(
        "--key",
        default="ed25519",
        choices=["ed25519", "secp256k1"],
        help="validator key type (reference: commands/init.go --key)",
    )
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--moniker", default="")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser(
        "signer",
        help="run an external signing process (dials a node's "
        "[priv_validator] listen_addr, serves this home's FilePV)",
    )
    sp.add_argument(
        "--addr",
        default="tcp://127.0.0.1:26659",
        help="socket mode: the node's priv_validator listen address "
        "to DIAL; --grpc mode: the local address this signer LISTENS "
        "on (the node dials grpc://<this>)",
    )
    sp.add_argument(
        "--node-id",
        default="",
        help="socket mode only: expected node identity for the "
        "SecretConnection (empty = accept any)",
    )
    sp.add_argument(
        "--grpc",
        action="store_true",
        help="use the gRPC privval transport instead of the socket one",
    )
    sp.set_defaults(fn=cmd_signer)

    sp = sub.add_parser("gen-validator", help="print a fresh validator key")
    sp.add_argument(
        "--key",
        default="ed25519",
        choices=["ed25519", "secp256k1"],
        help="key type (reference: commands/gen_validator.go --key)",
    )
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("gen-node-key", help="generate a node key")
    sp.set_defaults(fn=cmd_gen_node_key)

    sp = sub.add_parser("show-node-id", help="print this node's p2p ID")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser(
        "show-validator", help="print this node's validator pubkey"
    )
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser(
        "rollback", help="rewind state one height (after an app hash panic)"
    )
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser(
        "unsafe-reset-all", help="wipe data, keep config and keys"
    )
    sp.set_defaults(fn=cmd_reset_unsafe)

    sp = sub.add_parser("testnet", help="write N-validator testnet homes")
    sp.add_argument("--validators", "-v", type=int, default=4)
    sp.add_argument("--output-dir", "-o", default="./testnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser(
        "reindex-event",
        help="rebuild tx/block event indexes from stored blocks",
    )
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser(
        "abci",
        help="abci-cli: drive an ABCI app socket or serve the kvstore",
    )
    sp.add_argument(
        "abci_cmd",
        choices=[
            "kvstore",
            "console",
            "echo",
            "info",
            "deliver-tx",
            "check-tx",
            "commit",
            "query",
        ],
    )
    sp.add_argument("operand", nargs="?", default="")
    sp.add_argument("--addr", default="tcp://127.0.0.1:26658")
    sp.add_argument("--path", default="/store", help="query path")
    sp.add_argument(
        "--snapshot-interval",
        type=int,
        default=0,
        help="kvstore server: take a state snapshot every N heights "
        "(0 disables; needed for state-sync providers)",
    )
    sp.add_argument(
        "--grpc",
        action="store_true",
        help="use the gRPC ABCI transport instead of the socket one",
    )
    sp.set_defaults(fn=cmd_abci)

    sp = sub.add_parser(
        "light", help="run a verifying light-client RPC proxy"
    )
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="full node RPC addr")
    sp.add_argument(
        "--witness", action="append", help="witness RPC addr (repeatable)"
    )
    sp.add_argument("--trust-height", type=int, required=True)
    sp.add_argument("--trust-hash", required=True)
    sp.add_argument(
        "--trust-period", type=float, default=168 * 3600.0, help="seconds"
    )
    sp.add_argument("--sequential", action="store_true")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser(
        "inspect", help="read-only RPC over a stopped node's data"
    )
    sp.add_argument("--laddr", default="tcp://127.0.0.1:26657")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser(
        "replay", help="re-execute stored blocks through a fresh app"
    )
    sp.add_argument(
        "--console",
        action="store_true",
        help="interactive WAL playback after block replay "
        "(next/back/rs/n/quit)",
    )
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser(
        "debug", help="collect a diagnostic bundle into a tarball"
    )
    sp.add_argument("--output", "-o", default="./debug_bundle.tar.gz")
    sp.add_argument(
        "--device-profile",
        action="store_true",
        dest="device_profile",
        help="include an XLA profiler trace of a device verify batch",
    )
    sp.add_argument(
        "--metrics-url",
        default="",
        help="live /metrics endpoint to scrape into the bundle",
    )
    sp.add_argument(
        "--rpc-url",
        default="",
        dest="rpc_url",
        help="live RPC endpoint: status/consensus_state/net_info "
        "scraped into the bundle",
    )
    sp.add_argument(
        "--kill",
        type=int,
        default=0,
        help="after collecting the bundle, SIGABRT this node pid "
        "(the reference's `debug kill`)",
    )
    sp.set_defaults(fn=cmd_debug_dump)

    sp = sub.add_parser(
        "e2e", help="run or generate manifest-driven e2e testnets"
    )
    sp.add_argument("e2e_cmd", choices=["run", "generate"])
    sp.add_argument("manifest", nargs="?", default="")
    sp.add_argument("--home-dir", default="")
    sp.add_argument("--timeout", type=float, default=240.0)
    sp.add_argument("--seed", type=int, default=1)
    sp.add_argument("--count", type=int, default=4)
    sp.add_argument("--output-dir", "-o", default="./e2e-manifests")
    sp.add_argument(
        "--processes",
        action="store_true",
        help="run each node as a separate OS process over TCP with a "
        "socket ABCI app; perturbations use real signals "
        "(SIGKILL/SIGSTOP)",
    )
    sp.set_defaults(fn=cmd_e2e)

    sp = sub.add_parser(
        "key-migrate",
        help="migrate legacy database key formats to the current layout",
    )
    sp.set_defaults(fn=cmd_key_migrate)

    sp = sub.add_parser("version", help="print the version")
    sp.set_defaults(fn=cmd_version)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)
