"""Operator CLI package (reference: cmd/tendermint/)."""

from .commands import build_parser, main  # noqa: F401
