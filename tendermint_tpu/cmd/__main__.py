"""`python -m tendermint_tpu.cmd` entry point."""

import sys

from .commands import main

sys.exit(main())
