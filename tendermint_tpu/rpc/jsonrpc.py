"""Minimal JSON-RPC 2.0 server over HTTP POST, URI GET, and websocket.

reference: rpc/jsonrpc/server/{http_json_handler,http_uri_handler,
ws_handler}.go. Re-designed for asyncio streams: one handler task per
TCP connection, no external HTTP framework. The websocket side
implements the RFC 6455 subset the reference's ws clients use (text
frames, ping/pong, close), because `subscribe` is only meaningful on a
persistent duplex connection (routes.go:30-33).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import struct
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional
from urllib.parse import parse_qsl, urlsplit

from ..crypto import faults
from ..libs import profiler, trace
from ..libs.log import get_logger

__all__ = [
    "RPCError",
    "RPCRequest",
    "JSONRPCServer",
    "WSConn",
    "PARSE_ERROR",
    "INVALID_REQUEST",
    "METHOD_NOT_FOUND",
    "INVALID_PARAMS",
    "INTERNAL_ERROR",
]

PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# Per-connection pipelining depth: how many requests may be in flight
# at once before the read loop stalls (backpressure). Bounds the
# memory a single pipelining client can pin server-side.
HTTP_PIPELINE_DEPTH = 64
WS_PIPELINE_DEPTH = 64


class RPCError(Exception):
    """Carries a JSON-RPC error code + message to the client."""

    def __init__(self, code: int, message: str, data: Optional[str] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data

    def to_obj(self) -> dict:
        err: Dict[str, Any] = {"code": self.code, "message": self.message}
        if self.data is not None:
            err["data"] = self.data
        return err


@dataclass
class RPCRequest:
    """One decoded request, transport-independent."""

    method: str
    params: Dict[str, Any]
    req_id: Any
    # set only for websocket requests: lets handlers (subscribe) push
    # frames outside the request/response cycle
    ws: Optional["WSConn"] = None


# handler(request) -> result object (JSON-encodable)
Handler = Callable[[RPCRequest], Awaitable[Any]]


def _response(req_id: Any, result: Any = None, error: Optional[dict] = None):
    obj: Dict[str, Any] = {"jsonrpc": "2.0", "id": req_id}
    if error is not None:
        obj["error"] = error
    else:
        obj["result"] = result
    return obj


class WSConn:
    """Server side of one websocket connection.

    Owns the write half (single writer task -> no interleaved frames)
    and tracks the client id used for eventbus subscriptions so the
    server can unsubscribe on disconnect (reference:
    rpc/jsonrpc/server/ws_handler.go OnDisconnect).
    """

    def __init__(self, reader, writer, remote: str, metrics=None) -> None:
        self.reader = reader
        self.writer = writer
        self.remote = remote
        self.client_id = f"ws-{remote}"
        self._sendq: asyncio.Queue = asyncio.Queue(maxsize=512)
        self.closed = asyncio.Event()
        self.on_close: Optional[Callable[["WSConn"], None]] = None
        self._metrics = metrics  # RPCMetrics or None

    async def send_json(self, obj: Any) -> None:
        await self.send_text(json.dumps(obj))

    async def send_text(self, text: str) -> None:
        """Enqueue one already-serialized text frame — the fan-out path
        (rpc.core._pump_events) serializes once per event group and
        hands every subscriber the shared string."""
        if self.closed.is_set():
            return
        try:
            self._sendq.put_nowait(("text", text))
        except asyncio.QueueFull:
            # slow client: drop the connection rather than buffer
            # unboundedly (reference pubsub terminates slow subscribers)
            if self._metrics is not None:
                self._metrics.ws_slow_clients_dropped.inc()
            self._close()
            return
        if self._metrics is not None:
            # depth AFTER the enqueue: the subscriber's lag right now —
            # a climbing distribution is the fanout-saturation signal
            self._metrics.ws_send_queue_depth.observe(
                self._sendq.qsize()
            )

    def _close(self) -> None:
        if not self.closed.is_set():
            self.closed.set()
            try:
                # abort the transport so the read loop and the peer see
                # the disconnect immediately (a slow subscriber must be
                # dropped, not silently muted)
                self.writer.close()
            except Exception:
                pass
            if self.on_close is not None:
                self.on_close(self)

    async def _writer_loop(self) -> None:
        closed = asyncio.ensure_future(self.closed.wait())
        get: Optional[asyncio.Future] = None
        try:
            while not self.closed.is_set():
                get = asyncio.ensure_future(self._sendq.get())
                done, _ = await asyncio.wait(
                    [get, closed], return_when=asyncio.FIRST_COMPLETED
                )
                if get not in done:
                    get.cancel()
                    get = None
                    break
                items = [get.result()]
                get = None
                # cork: drain everything already queued into ONE write
                # + drain per wakeup — under fan-out load the queue
                # holds a burst per published event, and per-frame
                # write/drain round-trips dominated the writer
                while True:
                    try:
                        items.append(self._sendq.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                buf = bytearray()
                closing = False
                for kind, payload in items:
                    if kind == "text":
                        buf += _encode_frame(0x1, payload.encode())
                    elif kind == "pong":
                        buf += _encode_frame(0xA, payload)
                    else:  # close
                        buf += _encode_frame(0x8, payload)
                        closing = True
                        break
                self.writer.write(bytes(buf))
                await self.writer.drain()
                if closing:
                    break
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            closed.cancel()
            if get is not None and not get.done():
                # cancelled mid-wait (server stop with a live
                # subscriber): asyncio.wait does NOT cancel its
                # awaitables, so without this the pending Queue.get
                # task survives to interpreter exit as a
                # "Task was destroyed but it is pending!" leak
                # (reproduced; pinned by tests/test_teardown.py)
                get.cancel()
            self._close()


def _encode_frame(opcode: int, payload: bytes) -> bytes:
    """Server->client frame (unmasked, FIN set)."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < (1 << 16):
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


async def _read_frame(
    reader, max_frame: int = 10 << 20
) -> tuple[int, bytes]:
    """Returns (opcode, payload) of one frame (handles masking and
    fragmentation-free messages; continuation frames are concatenated
    by the caller loop). max_frame bounds a hostile/corrupt declared
    length — callers with trusted peers pass a larger cap."""
    h = await reader.readexactly(2)
    opcode = h[0] & 0x0F
    masked = h[1] & 0x80
    n = h[1] & 0x7F
    if n == 126:
        n = struct.unpack(">H", await reader.readexactly(2))[0]
    elif n == 127:
        n = struct.unpack(">Q", await reader.readexactly(8))[0]
    if n > max_frame:
        raise ConnectionError("websocket frame too large")
    mask = await reader.readexactly(4) if masked else b"\x00" * 4
    data = bytearray(await reader.readexactly(n))
    if masked:
        for i in range(n):
            data[i] ^= mask[i % 4]
    return opcode, bytes(data)


class JSONRPCServer:
    """Routes JSON-RPC methods; speaks HTTP/1.1 + websocket upgrade.

    URI GET form: /method?param=value with JSON-encoded values (strings
    may be bare). POST form: JSON-RPC 2.0 single or batch. Websocket
    endpoint at /websocket (reference: rpc/jsonrpc/server).
    """

    def __init__(
        self,
        routes: Dict[str, Handler],
        max_body_bytes: int = 1_000_000,
        metrics=None,
    ) -> None:
        self.routes = routes
        self.max_body_bytes = max_body_bytes
        self.metrics = metrics  # rpc.metrics.RPCMetrics or None
        self.logger = get_logger("rpc.server")
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._ws_conns: set[WSConn] = set()

    async def start(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, host, port
        )

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        # cancel live connection handlers BEFORE wait_closed():
        # wait_closed waits for handlers to finish, and a keep-alive
        # client parked on readline() would never finish on its own
        if self._server is not None:
            self._server.close()
        for ws in list(self._ws_conns):
            ws._close()
        tasks = list(self._conns)
        for task in tasks:
            task.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        self._conns.clear()
        if self._server is not None:
            await self._server.wait_closed()

    # -- connection handling --

    async def _handle_conn(self, reader, writer) -> None:
        task = asyncio.current_task()
        profiler.label_task(task, "rpc:conn")
        self._conns.add(task)
        try:
            await self._serve_http(reader, writer)
        except (
            ConnectionError,
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
        ):
            pass
        except Exception as e:  # pragma: no cover - defensive
            self.logger.error("rpc conn error", err=repr(e))
        finally:
            self._conns.discard(task)
            try:
                writer.close()
            except Exception:
                pass

    async def _serve_http(self, reader, writer) -> None:
        """HTTP/1.1 loop, pipelined: each request is dispatched as its
        own task the moment it is parsed, and a per-connection writer
        queue preserves HTTP/1.1 response order — so one slow handler
        (broadcast_tx_commit waiting a block) no longer head-of-line-
        blocks the requests a pipelining client queued behind it.
        Inflight per connection is bounded by the queue capacity: when
        it fills, the read loop stalls (backpressure) instead of
        buffering unboundedly."""
        resp_q: asyncio.Queue = asyncio.Queue(maxsize=HTTP_PIPELINE_DEPTH)
        wtask = profiler.label_task(
            asyncio.ensure_future(self._http_writer_loop(writer, resp_q)),
            "rpc:http-writer",
        )
        pending: set = set()
        try:
            while True:
                req_line = await reader.readline()
                if not req_line:
                    break
                try:
                    method, target, _version = (
                        req_line.decode("latin-1").strip().split(" ", 2)
                    )
                except ValueError:
                    break
                headers: Dict[str, str] = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode("latin-1").partition(":")
                    headers[k.strip().lower()] = v.strip()

                if headers.get("upgrade", "").lower() == "websocket":
                    # flush the pipeline, retire the writer, then hand
                    # the raw stream over to the websocket server
                    await resp_q.join()
                    resp_q.put_nowait(None)
                    await wtask
                    await self._serve_websocket(reader, writer, headers)
                    return

                body = b""
                n = int(headers.get("content-length", "0") or "0")
                if n > self.max_body_bytes:
                    await resp_q.put((413, b"body too large", "text/plain"))
                    break
                if n:
                    body = await reader.readexactly(n)

                if method == "POST":
                    task = asyncio.ensure_future(
                        self._handle_post_body(body)
                    )
                elif method == "GET":
                    task = asyncio.ensure_future(self._handle_uri(target))
                else:
                    await resp_q.put(
                        (405, b"method not allowed", "text/plain")
                    )
                    break
                profiler.label_task(task, "rpc:http-dispatch")
                pending.add(task)
                task.add_done_callback(pending.discard)
                await resp_q.put(task)  # bounded inflight
                if headers.get("connection", "").lower() == "close":
                    break
            # client finished (EOF / close / protocol error): drain the
            # responses already admitted, then retire the writer
            await resp_q.join()
            resp_q.put_nowait(None)
            await wtask
        finally:
            wtask.cancel()
            for t in list(pending):
                t.cancel()

    async def _http_writer_loop(self, writer, q: asyncio.Queue) -> None:
        """FIFO response writer for one pipelined HTTP connection.
        Consumes (status, body, ctype) tuples or in-flight dispatch
        tasks in request order; a None sentinel retires it. Never stops
        consuming on a broken transport — it keeps draining (discarding)
        so the read loop's bounded put/join can't deadlock."""
        broken = False
        while True:
            item = await q.get()
            try:
                if item is None:
                    return
                if broken:
                    continue
                try:
                    if isinstance(item, tuple):
                        status, body, ctype = item
                    else:
                        resp = await item
                        status = 200
                        # default=str: a handler returning an exotic
                        # object must not kill the connection
                        body = json.dumps(resp, default=str).encode()
                        ctype = "application/json"
                    await self._http_reply(writer, status, body, ctype=ctype)
                except asyncio.CancelledError:
                    raise
                except (ConnectionError, asyncio.IncompleteReadError):
                    broken = True
                except Exception as e:  # pragma: no cover - defensive
                    self.logger.error(
                        "rpc http response error", err=repr(e)
                    )
                    broken = True
            finally:
                q.task_done()

    async def _http_reply(
        self, writer, status: int, body: bytes, ctype: str = "text/plain"
    ) -> None:
        reason = {200: "OK", 405: "Method Not Allowed", 413: "Too Large"}.get(
            status, "Error"
        )
        writer.write(
            (
                f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                "\r\n"
            ).encode()
            + body
        )
        await writer.drain()

    # -- POST / GET dispatch --

    async def _handle_post_body(self, body: bytes):
        try:
            obj = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return _response(
                None, error=RPCError(PARSE_ERROR, "parse error").to_obj()
            )
        if isinstance(obj, list):
            return [await self._dispatch_obj(o) for o in obj]
        return await self._dispatch_obj(obj)

    async def _dispatch_obj(self, obj: Any, ws: Optional[WSConn] = None):
        if not isinstance(obj, dict) or "method" not in obj:
            return _response(
                None,
                error=RPCError(INVALID_REQUEST, "invalid request").to_obj(),
            )
        req_id = obj.get("id")
        params = obj.get("params") or {}
        if isinstance(params, list):
            return _response(
                req_id,
                error=RPCError(
                    INVALID_PARAMS, "positional params not supported"
                ).to_obj(),
            )
        req = RPCRequest(
            method=obj["method"], params=params, req_id=req_id, ws=ws
        )
        return await self._dispatch(req)

    async def _handle_uri(self, target: str):
        parts = urlsplit(target)
        method = parts.path.strip("/")
        if method == "":
            # route listing, like the reference's index page
            return _response(None, result=sorted(self.routes))
        params: Dict[str, Any] = {}
        for k, v in parse_qsl(parts.query):
            try:
                params[k] = json.loads(v)
            except ValueError:
                params[k] = v  # bare string
        return await self._dispatch(
            RPCRequest(method=method, params=params, req_id=-1)
        )

    async def _dispatch(self, req: RPCRequest):
        handler = self.routes.get(req.method)
        m = self.metrics
        if handler is None:
            if m is not None:
                # NOT labeled by method: route labels must stay a
                # server-known set, or a client mints unbounded series
                m.unknown_methods.inc()
            return _response(
                req.req_id,
                error=RPCError(
                    METHOD_NOT_FOUND, f"unknown method {req.method!r}"
                ).to_obj(),
            )
        if m is not None:
            m.requests_total.inc(route=req.method)
            m.inflight.add(1, route=req.method)
        failed = False
        sp = trace.span("rpc_request", method=req.method)
        t0 = time.perf_counter()
        try:
            with sp:
                try:
                    if faults.armed():
                        # chaos seam: `rpc.route` keyed by method — an
                        # injected hang/raise lands INSIDE the timed
                        # region (latency sketch + SLO exemplar see
                        # it), and an injected raise maps to the same
                        # INTERNAL_ERROR a crashing handler would
                        faults.fire("rpc.route", req.method)
                    result = await handler(req)
                except RPCError as e:
                    failed = True
                    return _response(req.req_id, error=e.to_obj())
                except (TypeError, ValueError, KeyError) as e:
                    # int()/decode failures on client-supplied params;
                    # logged so a genuine server bug surfacing here
                    # stays visible
                    failed = True
                    self.logger.info(
                        "rpc invalid params", method=req.method, err=repr(e)
                    )
                    return _response(
                        req.req_id,
                        error=RPCError(INVALID_PARAMS, str(e)).to_obj(),
                    )
                except Exception as e:
                    failed = True
                    self.logger.error(
                        "rpc handler error", method=req.method, err=repr(e)
                    )
                    return _response(
                        req.req_id,
                        error=RPCError(INTERNAL_ERROR, repr(e)).to_obj(),
                    )
            return _response(req.req_id, result=result)
        finally:
            if m is not None:
                dur = time.perf_counter() - t0
                m.inflight.add(-1, route=req.method)
                m.request_latency.observe(dur, route=req.method)
                if failed:
                    m.request_errors.inc(route=req.method)
                slo = m.slo_for(req.method)
                if dur > slo:
                    m.slow_requests.inc(route=req.method)
                    trace.record_slow_request(
                        req.method, dur, slo, root=sp
                    )

    # -- websocket --

    async def _serve_websocket(self, reader, writer, headers) -> None:
        key = headers.get("sec-websocket-key")
        if not key:
            await self._http_reply(writer, 405, b"bad websocket handshake")
            return
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        writer.write(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {accept}\r\n\r\n"
            ).encode()
        )
        await writer.drain()

        peer = writer.get_extra_info("peername")
        remote = f"{peer[0]}:{peer[1]}" if peer else "unknown"
        ws = WSConn(reader, writer, remote, metrics=self.metrics)
        self._ws_conns.add(ws)
        if self.metrics is not None:
            self.metrics.ws_connections.add(1)
        wtask = profiler.label_task(
            asyncio.ensure_future(ws._writer_loop()), "rpc:ws-writer"
        )
        msg = bytearray()
        sem = asyncio.Semaphore(WS_PIPELINE_DEPTH)
        inflight: set = set()
        try:
            while True:
                opcode, payload = await _read_frame(reader)
                if opcode == 0x8:  # close
                    ws._sendq.put_nowait(("close", payload[:2]))
                    break
                if opcode == 0x9:  # ping
                    ws._sendq.put_nowait(("pong", payload))
                    continue
                if opcode in (0x1, 0x2, 0x0):
                    msg.extend(payload)
                    # FIN bit already folded into _read_frame? no —
                    # reference clients don't fragment; treat each
                    # data frame as a complete message.
                    try:
                        obj = json.loads(bytes(msg))
                    except ValueError:
                        await ws.send_json(
                            _response(
                                None,
                                error=RPCError(
                                    PARSE_ERROR, "parse error"
                                ).to_obj(),
                            )
                        )
                        msg.clear()
                        continue
                    msg.clear()
                    # dispatch off the read loop: a slow handler
                    # (broadcast_tx_commit waits a whole block) must
                    # not head-of-line-block the frames behind it;
                    # clients match responses by id. The semaphore
                    # bounds per-connection inflight (backpressure).
                    await sem.acquire()
                    task = profiler.label_task(
                        asyncio.ensure_future(
                            self._ws_dispatch(obj, ws, sem)
                        ),
                        "rpc:ws-dispatch",
                    )
                    inflight.add(task)
                    task.add_done_callback(inflight.discard)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            ws._close()
            self._ws_conns.discard(ws)
            if self.metrics is not None:
                self.metrics.ws_connections.add(-1)
            wtask.cancel()
            for task in list(inflight):
                task.cancel()

    async def _ws_dispatch(self, obj: Any, ws: WSConn, sem) -> None:
        try:
            resp = await self._dispatch_obj(obj, ws=ws)
            await ws.send_json(resp)
        finally:
            sem.release()
