"""JSON-RPC layer: server, route implementations, clients.

reference: rpc/ (jsonrpc machinery + clients), internal/rpc/core
(method implementations), node/node.go:480-540 (server startup).
"""

from .client import (  # noqa: F401
    HTTPClient,
    LocalClient,
    RPCClientError,
    WSClient,
)
from .core import Environment  # noqa: F401
from .jsonrpc import JSONRPCServer, RPCError, RPCRequest  # noqa: F401
from .server import RPCServer  # noqa: F401
