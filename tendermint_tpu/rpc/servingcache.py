"""Per-block serving cache — the memo layer tmcost's first run forced.

The stateless-serving routes pay the same work per request for content
that is immutable per block: `light_blocks` re-loaded and re-encoded
every LightBlock of a page on every request, and proof serving rebuilt
a MerkleMultiTree per call while the tree type had zero in-node users
(the ROADMAP item this PR closes). tmcost's `cost-recompute` rule
flagged both handler sites on its first run; this module is the fix —
and the one place that work is ALLOWED to happen (tmcost exempts
functions in a recognized serving-cache module: their miss path is the
sanctioned home of the expensive call).

Two entry families, both keyed by height:

- ``encoded_light_block(height)`` — the LightBlock proto blob exactly
  as `LightBlock.to_proto()` would produce it (the `light_blocks` page
  is assembled by wrapping cached blobs, byte-identical to
  `LightBlocksResponse.to_proto`, pinned by test).
- ``tx_tree(height)`` — a held `MerkleMultiTree` over the block's
  per-tx hashes (leaves = `tx_hash(tx)`, root == `header.data_hash`),
  serving every `tx_proofs` request for that block with pure aunt
  gathering (PR-11: 0.78 ms vs 11.5 ms rebuilt, K=256).

Safety model (the sigcache mold):

- **Only canonical heights are cached**: a height enters the cache
  only when `load_block_commit(height)` exists — the tip served from
  the seen-commit fallback is assembled fresh every time, so a commit
  that is later replaced by the canonical one can never be served
  stale.
- **Invalidation rides the PR-7 mutation-epoch machinery**: every
  entry set captures the process-wide commit and validator mutation
  epochs (types/commit._MUT_EPOCH, types/validator._VAL_MUT_EPOCH).
  A hit first checks both tokens by identity; ANY in-place mutation of
  a Commit wire field or Validator identity field anywhere in the
  process — the one way store-loaded content could drift from its
  encoding — flushes the whole cache. Stores are append-only for
  committed heights, so nothing else can change a cached block.
- **Bounded**: one LRU per family, default `DEFAULT_CAPACITY` blocks
  (config `[rpc] serving_cache_blocks`; 0 disables). A 150-validator
  LightBlock blob is ~15 KB and a 10k-tx tree ~640 KB of hashes, so
  the defaults top out around a few MB per node.
- **Kill-switched**: `TM_TPU_NO_SERVCACHE=1` (or a `disabled()` scope,
  the bench's A/B arm) makes every lookup a miss and every insert a
  drop — behavior identical to the cache never existing, minus the
  speed.

The cache is event-loop-confined like the Environment that owns it
(one per node); no locking. Counters land on the owning node's
registry via RPCMetrics (servingcache_{hits,misses,evictions}_total).
"""

from __future__ import annotations

import contextlib
import os
from collections import OrderedDict
from typing import Optional

from ..crypto.merkle import MerkleMultiTree
from ..types.commit import _MUT_EPOCH
from ..types.light import LightBlock, SignedHeader
from ..types.tx import tx_hash
from ..types.validator import _VAL_MUT_EPOCH

__all__ = ["DEFAULT_CAPACITY", "ServingCache", "disabled", "enabled"]

DEFAULT_CAPACITY = 64

_force_off = False  # bench A/B arm / tests, same effect as the env gate


def enabled() -> bool:
    """False under TM_TPU_NO_SERVCACHE=1 (or a disabled() scope)."""
    return not (_force_off or os.environ.get("TM_TPU_NO_SERVCACHE"))


@contextlib.contextmanager
def disabled():
    """Scope with the serving cache forced off (bench cold arm, A/B
    tests)."""
    global _force_off
    prev = _force_off
    _force_off = True
    try:
        yield
    finally:
        _force_off = prev


class ServingCache:
    """Per-node bounded cache of per-block serving artifacts."""

    def __init__(
        self,
        block_store,
        state_store,
        capacity: int = DEFAULT_CAPACITY,
        metrics=None,  # RPCMetrics or None
    ) -> None:
        self.block_store = block_store
        self.state_store = state_store
        self.capacity = int(capacity)
        self.metrics = metrics
        # height -> LightBlock proto blob / MerkleMultiTree
        self._blobs: "OrderedDict[int, bytes]" = OrderedDict()
        self._trees: "OrderedDict[int, MerkleMultiTree]" = OrderedDict()
        # the mutation-epoch tokens the resident entries were built
        # under; identity drift on either flushes everything
        self._commit_epoch = _MUT_EPOCH[0]
        self._val_epoch = _VAL_MUT_EPOCH[0]

    # -- lifecycle --

    def _usable(self) -> bool:
        return self.capacity > 0 and enabled()

    def _check_epochs(self) -> None:
        if (
            self._commit_epoch is not _MUT_EPOCH[0]
            or self._val_epoch is not _VAL_MUT_EPOCH[0]
        ):
            # some Commit/Validator was mutated in place somewhere in
            # the process: cached encodings may no longer match live
            # objects — drop everything and re-pin (conservative, two
            # identity compares per request when nothing mutated)
            self._blobs.clear()
            self._trees.clear()
            self._commit_epoch = _MUT_EPOCH[0]
            self._val_epoch = _VAL_MUT_EPOCH[0]

    def clear(self) -> None:
        self._blobs.clear()
        self._trees.clear()

    def entries(self) -> int:
        return len(self._blobs) + len(self._trees)

    def _count(self, name: str, n: int = 1) -> None:
        if self.metrics is not None:
            getattr(self.metrics, "servingcache_" + name).inc(n)

    def _put(self, lru: OrderedDict, height: int, value) -> None:
        lru[height] = value
        lru.move_to_end(height)
        while len(lru) > self.capacity:
            lru.popitem(last=False)
            self._count("evictions")

    def _get(self, lru: OrderedDict, height: int):
        v = lru.get(height)
        if v is not None:
            lru.move_to_end(height)
            self._count("hits")
        else:
            self._count("misses")
        return v

    # -- light blocks --

    def light_block_at(self, height: int) -> Optional[LightBlock]:
        """Assemble the LightBlock at height from the stores (tip falls
        back to the seen commit), or None when any part is missing.
        Always a fresh assembly — the cached artifact is the BLOB.
        This is the cache's OBJECT surface (callers that need the
        decoded form rather than the wire blob); the routes themselves
        serve blobs via encoded_light_block."""
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None and height == self.block_store.height():
            seen = self.block_store.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            return None
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )

    def encoded_light_block(self, height: int) -> Optional[bytes]:
        """The `LightBlock.to_proto()` blob for a height, cached for
        canonical (non-tip-fallback) heights. None when the height
        cannot be fully assembled.

        The miss path does its own assembly rather than delegating to
        light_block_at for two reasons: the canonicity of the FIRST
        commit load doubles as the cacheability signal (a second
        load_block_commit just to decide caching is a full Commit
        decode on a real KV store — code-review finding), and the
        locally-constructed LightBlock keeps the `to_proto` edge
        resolvable so the budget table records the cold-miss vset
        cost instead of a vacuous 'const'."""
        if self._usable():
            self._check_epochs()
            blob = self._get(self._blobs, height)
            if blob is not None:
                return blob
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        canonical = commit is not None
        if commit is None and height == self.block_store.height():
            seen = self.block_store.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            return None
        lb = LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )
        blob = lb.to_proto()
        if self._usable() and canonical:
            self._put(self._blobs, height, blob)
        return blob

    # -- tx proof trees --

    def tx_tree(self, height: int) -> Optional[MerkleMultiTree]:
        """A held MerkleMultiTree over the block's tx hashes: root ==
        header.data_hash (types/tx.txs_hash computes the identical
        tree), every proof request for the block served by aunt
        gathering. None when the block is not stored."""
        if self._usable():
            self._check_epochs()
            tree = self._get(self._trees, height)
            if tree is not None:
                return tree
        block = self.block_store.load_block(height)
        if block is None:
            return None
        tree = MerkleMultiTree.from_byte_slices(
            [tx_hash(tx) for tx in block.txs]
        )
        # cacheability: any height strictly below the tip is immutable
        # (storing block h+1 required h's canonical commit) — a cheap,
        # decode-free check, unlike re-loading the commit just to
        # compare it to None (code-review finding)
        if self._usable() and height < self.block_store.height():
            self._put(self._trees, height, tree)
        return tree
