"""RPC method implementations against a node Environment.

reference: internal/rpc/core/{routes.go:30-73, env.go, blocks.go,
mempool.go, status.go, tx.go, consensus.go, abci.go, events.go,
evidence.go, net.go, health.go}. The Environment holds the same node
internals the reference's does; every public method is one JSON-RPC
route.

JSON conventions (framework-local, documented rather than inherited
from Go's accidents): bytes are lowercase hex strings; transaction
payloads are base64 (they are opaque app data); heights and other
int64s are JSON numbers.
"""

from __future__ import annotations

import asyncio
import base64
import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from ..crypto.keys import PubKey
from ..types.validator import ValidatorSet
from ..eventbus import EventBus
from ..libs import profiler, trace
from ..libs.log import get_logger
from ..mempool import Mempool, MempoolError, TxInfo
from ..pubsub import ERR_TERMINATED, SubscriptionError
from ..state.indexer import EventSink
from ..types import events as tme
from ..types.genesis import GenesisDoc
from ..types.tx import tx_hash
from .jsonrpc import (
    INTERNAL_ERROR,
    INVALID_PARAMS,
    RPCError,
    RPCRequest,
)
from .metrics import RPCMetrics

__all__ = [
    "Environment",
    "GENESIS_CHUNK_SIZE",
    "LIGHT_BLOCKS_PAGE_CAP",
    "PROFILE_PAGE_CAP",
    "TIMELINE_PAGE_CAP",
    "TX_PROOFS_CAP",
]

GENESIS_CHUNK_SIZE = 16 * 1024 * 1024  # reference: env.go:51

# hard server-side page bound for the bulk light_blocks route (the
# reference's BlockchainInfo page size; a 150-validator LightBlock is
# ~15 KB of proto, so a full page stays well under typical client
# frame limits). Clients page past it (light/provider.py light_blocks).
LIGHT_BLOCKS_PAGE_CAP = 20

# hard server-side bound on merkle proofs per tx_proofs request: a
# proof is ~32·log2(N) bytes, and the held tree serves K proofs in
# K·log2(N) gathers, so 100 keeps the worst request under ~1 ms
TX_PROOFS_CAP = 100

# hard server-side page bound for the consensus_timeline route: one
# event is a small flat dict (~120 bytes of JSON), so a full page
# stays ~60 KB; clients resume via the seq cursor (after_seq)
TIMELINE_PAGE_CAP = 512

# hard server-side page bound for the profile route's folded-stack
# snapshot: an aggregated stack entry is ~0.5-1 KB of JSON (the folded
# frame chain dominates), so a full page stays ~a quarter MB; clients
# resume via the offset cursor (after)
PROFILE_PAGE_CAP = 256


def encode(obj: Any) -> Any:
    """Generic domain-object -> JSON-encodable structure."""
    if isinstance(obj, PubKey):
        return {"type": obj.type(), "value": obj.bytes().hex()}
    if isinstance(obj, ValidatorSet):
        return {
            "validators": [encode(v) for v in obj.validators],
            "proposer": (
                encode(obj.get_proposer()) if obj.size() else None
            ),
            "total_voting_power": obj.total_voting_power(),
        }
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        # underscore fields are internal caches (Commit._hash,
        # Commit._sign_templates, Header._hash ...) — never part of the
        # wire shape, and not necessarily JSON-encodable
        return {
            f.name: encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
            if not f.name.startswith("_")
        }
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, (list, tuple)):
        return [encode(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    return obj


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def _decode_tx_param(params: Dict[str, Any]) -> bytes:
    tx = params.get("tx")
    if not isinstance(tx, str):
        raise RPCError(INVALID_PARAMS, "missing tx param (base64 string)")
    try:
        return base64.b64decode(tx, validate=True)
    except Exception:
        raise RPCError(INVALID_PARAMS, "tx is not valid base64")


def _decode_hash_param(params: Dict[str, Any], key: str = "hash") -> bytes:
    h = params.get(key)
    if not isinstance(h, str):
        raise RPCError(INVALID_PARAMS, f"missing {key} param (hex string)")
    try:
        return bytes.fromhex(h)
    except ValueError:
        raise RPCError(INVALID_PARAMS, f"{key} is not valid hex")


class _AdmissionBatcher:
    """Coalesces concurrent broadcast_tx admissions into pipelined
    mempool.check_tx_batch calls.

    Under high-rate ingest, thousands of broadcast_tx requests are in
    flight at once and each serial check_tx pays its own shard-lock
    acquire, ABCI client lock, and event-loop hops. The batcher queues
    (tx, future) pairs and a single drain task admits them in
    tx_batch_size chunks — requests arriving while one batch's app call
    is in flight simply form the next batch, so the coalescing window
    is the natural pipeline depth, not a timer. Per-tx outcomes are
    identical to serial check_tx (dup/full errors come back as the
    exceptions check_tx would have raised)."""

    def __init__(self, mempool, max_batch: int = 64) -> None:
        self._mp = mempool
        self._max = max(1, max_batch)
        # tmlive: bounded=drained every loop tick by _drain; producers
        # are RPC requests already bounded by connection/inflight caps
        self._queue: List[Tuple[bytes, asyncio.Future]] = []
        self._task: Optional[asyncio.Task] = None

    async def check_tx(self, tx: bytes):
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.append((tx, fut))
        if self._task is None or self._task.done():
            self._task = profiler.label_task(
                asyncio.ensure_future(self._drain()),
                "rpc:admission-batch",
            )
        return await fut

    async def _drain(self) -> None:
        # yield once so every admission scheduled this tick lands in
        # the first batch instead of a batch of one
        await asyncio.sleep(0)
        while self._queue:
            batch = self._queue[: self._max]
            del self._queue[: len(batch)]
            try:
                outs = await self._mp.check_tx_batch(
                    [tx for tx, _ in batch], TxInfo()
                )
            except Exception as e:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for (_, fut), out in zip(batch, outs):
                if fut.done():
                    continue
                if isinstance(out, Exception):
                    fut.set_exception(out)
                else:
                    fut.set_result(out)


class Environment:
    """Node internals the RPC methods read (reference: env.go:58-100)."""

    def __init__(
        self,
        *,
        chain_id: str,
        block_store,
        state_store,
        mempool: Optional[Mempool] = None,
        event_bus: Optional[EventBus] = None,
        consensus=None,  # ConsensusState
        consensus_reactor=None,
        peer_manager=None,
        proxy=None,  # AppConns
        genesis: Optional[GenesisDoc] = None,
        evidence_pool=None,
        event_sinks: Optional[List[EventSink]] = None,
        node_info=None,
        privval_pub_key: Optional[PubKey] = None,
        cfg=None,
        metrics: Optional[RPCMetrics] = None,
    ) -> None:
        self.chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.mempool: Optional[Mempool] = mempool
        self.event_bus = event_bus
        self.consensus = consensus
        self.consensus_reactor = consensus_reactor
        self.peer_manager = peer_manager
        self.proxy = proxy
        self.genesis = genesis
        self.evidence_pool: Optional["EvidencePool"] = evidence_pool
        self.event_sinks = event_sinks or []
        self.node_info = node_info
        self.privval_pub_key = privval_pub_key
        self.cfg = cfg
        self.metrics = metrics if metrics is not None else RPCMetrics()
        # per-block serving cache (encoded LightBlock blobs + held
        # tx-proof trees) — the tmcost cost-recompute fix; capacity 0
        # disables (see rpc/servingcache.py for the safety model)
        from .servingcache import DEFAULT_CAPACITY, ServingCache

        # annotated so the analyzers resolve cache-method edges (the
        # budget table must include the cache's cold-miss cost)
        self.serving_cache: ServingCache = ServingCache(
            block_store,
            state_store,
            capacity=(
                cfg.rpc.serving_cache_blocks
                if cfg is not None
                else DEFAULT_CAPACITY
            ),
            metrics=self.metrics,
        )
        self.logger = get_logger("rpc.core")
        # ws client_id -> set of query strings (for unsubscribe_all)
        self._ws_subs: Dict[str, set] = {}
        self._genesis_chunks: Optional[List[bytes]] = None
        self._commit_waiters = 0  # uniquifies broadcast_tx_commit subs
        self._admission: Optional[_AdmissionBatcher] = None

    # -- route table (reference: routes.go:30-73) --

    def routes(self) -> Dict[str, Any]:
        r = {
            "health": self.health,
            "status": self.status,
            "net_info": self.net_info,
            "genesis": self.genesis_route,
            "genesis_chunked": self.genesis_chunked,
            "blockchain": self.blockchain,
            "header": self.header,
            "header_by_hash": self.header_by_hash,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "commit": self.commit,
            "validators": self.validators,
            "consensus_state": self.consensus_state,
            "consensus_timeline": self.consensus_timeline,
            "profile": self.profile,
            "dump_consensus_state": self.dump_consensus_state,
            "consensus_params": self.consensus_params,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "check_tx": self.check_tx,
            "remove_tx": self.remove_tx,
            "unsafe_flush_mempool": self.unsafe_flush_mempool,
            "abci_query": self.abci_query,
            "abci_info": self.abci_info,
            "broadcast_evidence": self.broadcast_evidence,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            "light_block": self.light_block,
            "light_blocks": self.light_blocks,
            "tx_proofs": self.tx_proofs,
            "subscribe": self.subscribe,
            "unsubscribe": self.unsubscribe,
            "unsubscribe_all": self.unsubscribe_all,
        }
        return r

    # -- info routes --

    async def health(self, req: RPCRequest):
        return {}

    async def status(self, req: RPCRequest):
        """reference: internal/rpc/core/status.go:24."""
        latest_height = self.block_store.height()
        latest_meta = (
            self.block_store.load_block_meta(latest_height)
            if latest_height
            else None
        )
        sync_info = {
            "latest_block_hash": (
                latest_meta.block_id.hash.hex() if latest_meta else ""
            ),
            "latest_app_hash": (
                latest_meta.header.app_hash.hex() if latest_meta else ""
            ),
            "latest_block_height": latest_height,
            "latest_block_time": (
                latest_meta.header.time_ns if latest_meta else 0
            ),
            "earliest_block_height": self.block_store.base(),
            "catching_up": (
                self.consensus_reactor.wait_sync
                if self.consensus_reactor is not None
                else False
            ),
        }
        validator_info = {}
        if self.privval_pub_key is not None:
            addr = self.privval_pub_key.address()
            power = 0
            state = self.state_store.load()
            if state is not None:
                _, val = state.validators.get_by_address(addr)
                if val is not None:
                    power = val.voting_power
            validator_info = {
                "address": addr.hex(),
                "pub_key": self.privval_pub_key.bytes().hex(),
                "voting_power": power,
            }
        return {
            "node_info": encode(self.node_info) if self.node_info else {},
            "sync_info": sync_info,
            "validator_info": validator_info,
        }

    async def net_info(self, req: RPCRequest):
        """reference: internal/rpc/core/net.go:16."""
        peers = []
        if self.peer_manager is not None:
            for pid, addr in self.peer_manager.connected_peers():
                peers.append({"node_id": pid, "url": addr})
        return {
            "listening": True,
            "n_peers": len(peers),
            "peers": peers,
        }

    async def genesis_route(self, req: RPCRequest):
        if self.genesis is None:
            raise RPCError(INTERNAL_ERROR, "genesis not available")
        import json as _json

        return {"genesis": _json.loads(self.genesis.to_json())}

    async def genesis_chunked(self, req: RPCRequest):
        """reference: env.go InitGenesisChunks + net.go GenesisChunked."""
        if self.genesis is None:
            raise RPCError(INTERNAL_ERROR, "genesis not available")
        if self._genesis_chunks is None:
            data = self.genesis.to_json().encode()
            self._genesis_chunks = [
                data[i : i + GENESIS_CHUNK_SIZE]
                for i in range(0, len(data), GENESIS_CHUNK_SIZE)
            ] or [b""]
        chunks = self._genesis_chunks
        chunk = int(req.params.get("chunk", 0))
        if not 0 <= chunk < len(chunks):
            raise RPCError(
                INVALID_PARAMS,
                f"chunk {chunk} out of range (total {len(chunks)})",
            )
        return {
            "chunk": chunk,
            "total": len(chunks),
            "data": _b64(chunks[chunk]),
        }

    # -- block routes (reference: internal/rpc/core/blocks.go) --

    def _height_param(
        self, params: Dict[str, Any], default_latest: bool = True
    ) -> int:
        h = params.get("height")
        if h is None:
            if not default_latest:
                raise RPCError(INVALID_PARAMS, "missing height param")
            return self.block_store.height()
        height = int(h)
        base = self.block_store.base()
        top = self.block_store.height()
        if height < base or height > top:
            raise RPCError(
                INVALID_PARAMS,
                f"height {height} not available (base {base}, height {top})",
            )
        return height

    async def blockchain(self, req: RPCRequest):
        """Block metas in [min_height, max_height], newest first
        (reference: blocks.go:26 BlockchainInfo, 20-block page)."""
        top = self.block_store.height()
        base = self.block_store.base()
        max_h = min(int(req.params.get("max_height", top) or top), top)
        min_h = max(int(req.params.get("min_height", base) or base), base)
        min_h = max(min_h, max_h - 19)
        metas = []
        # descending page, count explicitly capped at 20: both bounds
        # are client-chosen ints, so the loop bound must be a clamp
        # expression, not a subtraction of two attacker values
        for off in range(min(max_h - min_h + 1, 20)):
            m = self.block_store.load_block_meta(max_h - off)
            if m is not None:
                metas.append(encode(m))
        return {
            "last_height": top,
            "block_metas": metas,
        }

    async def header(self, req: RPCRequest):
        height = self._height_param(req.params)
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            raise RPCError(INVALID_PARAMS, f"no header at height {height}")
        return {"header": encode(meta.header)}

    async def header_by_hash(self, req: RPCRequest):
        h = _decode_hash_param(req.params)
        meta = self.block_store.load_block_meta_by_hash(h)
        if meta is None:
            return {"header": None}
        return {"header": encode(meta.header)}

    async def block(self, req: RPCRequest):
        height = self._height_param(req.params)
        block = self.block_store.load_block(height)
        meta = self.block_store.load_block_meta(height)
        if block is None or meta is None:
            raise RPCError(INVALID_PARAMS, f"no block at height {height}")
        return {
            "block_id": encode(meta.block_id),
            "block": encode(block),
        }

    async def block_by_hash(self, req: RPCRequest):
        h = _decode_hash_param(req.params)
        block = self.block_store.load_block_by_hash(h)
        if block is None:
            return {"block_id": None, "block": None}
        meta = self.block_store.load_block_meta(block.header.height)
        return {
            "block_id": encode(meta.block_id) if meta else None,
            "block": encode(block),
        }

    async def block_results(self, req: RPCRequest):
        """reference: blocks.go:148 BlockResults."""
        height = self._height_param(req.params)
        resp = self.state_store.load_abci_responses(height)
        if resp is None:
            raise RPCError(
                INVALID_PARAMS, f"no results for height {height}"
            )
        val_updates = resp.end_block_obj.validator_updates if (
            resp.end_block_obj is not None
        ) else []
        # The three tmcost suppressions below are all the same summary
        # imprecision: encode() is one generic recursive encoder, so
        # its cost summary carries a vset term from its ValidatorSet
        # branch — but here every encoded element is a single per-tx
        # result or event, so the real cost is block-linear, not
        # block*vset (docs/static_analysis.md, tmcost limitations).
        return {
            "height": height,
            # tmcost: cost-superlinear-ok — encode(per-tx result) is
            # O(result), the vset term is another branch of encode
            "txs_results": [encode(r) for r in resp.deliver_tx_objs],
            "begin_block_events": (
                # tmcost: cost-superlinear-ok — encode(event) is
                # O(event), the vset term is another branch of encode
                [encode(e) for e in resp.begin_block_obj.events]
                if resp.begin_block_obj is not None
                else []
            ),
            "end_block_events": (
                # tmcost: cost-superlinear-ok — encode(event) is
                # O(event), the vset term is another branch of encode
                [encode(e) for e in resp.end_block_obj.events]
                if resp.end_block_obj is not None
                else []
            ),
            "validator_updates": [encode(v) for v in val_updates],
            "consensus_param_updates": (
                encode(resp.end_block_obj.consensus_param_updates)
                if resp.end_block_obj is not None
                else None
            ),
        }

    async def commit(self, req: RPCRequest):
        height = self._height_param(req.params)
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            raise RPCError(INVALID_PARAMS, f"no block at height {height}")
        commit = self.block_store.load_block_commit(height)
        canonical = commit is not None
        if commit is None and height == self.block_store.height():
            commit = self.block_store.load_seen_commit()
        return {
            "signed_header": {
                "header": encode(meta.header),
                "commit": encode(commit) if commit else None,
            },
            "canonical": canonical,
        }

    async def validators(self, req: RPCRequest):
        """reference: consensus.go:21 Validators (paginated)."""
        height = self._height_param(req.params)
        vals = self.state_store.load_validators(height)
        if vals is None:
            raise RPCError(
                INVALID_PARAMS, f"no validator set at height {height}"
            )
        page = int(req.params.get("page", 1))
        per_page = min(int(req.params.get("per_page", 30)), 100)
        total = vals.size()
        start = (page - 1) * per_page
        if start < 0 or (start >= total and total > 0):
            raise RPCError(INVALID_PARAMS, f"page {page} out of range")
        sel = vals.validators[start : start + per_page]
        return {
            "block_height": height,
            "validators": [encode(v) for v in sel],
            "count": len(sel),
            "total": total,
        }

    async def consensus_params(self, req: RPCRequest):
        height = self._height_param(req.params)
        params = self.state_store.load_params(height)
        if params is None:
            state = self.state_store.load()
            params = state.consensus_params if state else None
        return {
            "block_height": height,
            "consensus_params": encode(params) if params else None,
        }

    async def consensus_state(self, req: RPCRequest):
        """Round-state summary (reference: consensus.go:66)."""
        if self.consensus is None:
            raise RPCError(INTERNAL_ERROR, "consensus not available")
        rs = self.consensus.get_round_state()
        return {
            "round_state": {
                "height": rs.height,
                "round": rs.round,
                "step": int(rs.step),
                "start_time": rs.start_time_ns,
                "proposal_block_hash": (
                    rs.proposal_block.hash().hex()
                    if rs.proposal_block is not None
                    else ""
                ),
                "locked_block_hash": (
                    rs.locked_block.hash().hex()
                    if rs.locked_block is not None
                    else ""
                ),
                "valid_block_hash": (
                    rs.valid_block.hash().hex()
                    if rs.valid_block is not None
                    else ""
                ),
            }
        }

    async def consensus_timeline(self, req: RPCRequest):
        """Flight-recorder page: the node's consensus timeline ring
        (consensus/timeline.py — step transitions, threshold
        crossings, timeouts, stall-resets) as JSON events, oldest
        first. Params: `after_seq` resumes the cursor (events with
        seq > after_seq), `max_events` shrinks — never grows — the
        hard TIMELINE_PAGE_CAP server page bound. `dropped_before` is
        how many events the bounded ring has already evicted; a
        scraper that fell behind sees the gap instead of silence
        (framework route; the reference exposes only the instantaneous
        /consensus_state)."""
        if self.consensus is None:
            raise RPCError(INTERNAL_ERROR, "consensus not available")
        tl = self.consensus.timeline
        after = int(req.params.get("after_seq", 0) or 0)
        cap = TIMELINE_PAGE_CAP
        max_events = int(req.params.get("max_events", 0) or 0)
        if 0 < max_events < cap:
            cap = max_events
        with trace.span("consensus_timeline", after_seq=after):
            events, next_seq, dropped = tl.page(after, cap)
            trace.add_attrs(count=len(events))
        return {
            "node": (
                self.cfg.base.moniker if self.cfg is not None else ""
            ),
            "enabled": tl.enabled,
            "capacity": tl.capacity,
            "events": events,
            "next_seq": next_seq,
            "dropped_before": dropped,
        }

    async def profile(self, req: RPCRequest):
        """The profiling plane over RPC (libs/profiler.py). Params:
        `action` is one of

          status   (default) sampler state + per-subsystem shares
          start    begin sampling (optional `hz`, clamped to [1, 997];
                   optional `reset` drops prior samples first)
          stop     stop and join the sampler thread
          snapshot one page of the aggregated folded stacks, highest
                   count first; `after` resumes the offset cursor and
                   `max_stacks` shrinks — never grows — the hard
                   PROFILE_PAGE_CAP server page bound. Sampling keeps
                   running between pages, so counts may drift across a
                   paged read; page 0's `samples_total` timestamps the
                   read.

        Every answer carries `stats` so a scraper never needs a second
        round-trip to learn the sampler state."""
        action = str(req.params.get("action", "status") or "status")
        if action == "start":
            hz = req.params.get("hz")
            if hz is not None:
                hz = max(1.0, min(997.0, float(hz)))
            if req.params.get("reset"):
                profiler.reset()
            profiler.enable(hz=hz)
            return {"stats": profiler.stats()}
        if action == "stop":
            profiler.disable()
            return {"stats": profiler.stats()}
        if action == "snapshot":
            after = int(req.params.get("after", 0) or 0)
            cap = PROFILE_PAGE_CAP
            max_stacks = int(req.params.get("max_stacks", 0) or 0)
            if 0 < max_stacks < cap:
                cap = max_stacks
            entries = profiler.snapshot()
            page = entries[after:after + cap]
            return {
                "stats": profiler.stats(),
                "stacks": page,
                "next": after + len(page),
                "total_stacks": len(entries),
            }
        if action == "status":
            return {
                "stats": profiler.stats(),
                "subsystem_shares": profiler.subsystem_shares(),
            }
        raise RPCError(
            INVALID_PARAMS,
            f"unknown profile action: {action!r} "
            "(expected status/start/stop/snapshot)",
        )

    async def dump_consensus_state(self, req: RPCRequest):
        """Full round state incl. vote sets (reference: consensus.go:36)."""
        if self.consensus is None:
            raise RPCError(INTERNAL_ERROR, "consensus not available")
        rs = self.consensus.get_round_state()
        votes = []
        if rs.votes is not None:
            for r in range(rs.round + 1):
                prevotes = rs.votes.prevotes(r)
                precommits = rs.votes.precommits(r)
                votes.append(
                    {
                        "round": r,
                        "prevotes": (
                            str(prevotes) if prevotes is not None else ""
                        ),
                        "precommits": (
                            str(precommits)
                            if precommits is not None
                            else ""
                        ),
                    }
                )
        return {
            "round_state": {
                "height": rs.height,
                "round": rs.round,
                "step": int(rs.step),
                "validators": encode(rs.validators),
                "proposal": encode(rs.proposal),
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
                "votes": votes,
                "commit_round": rs.commit_round,
            }
        }

    # -- mempool routes (reference: internal/rpc/core/mempool.go) --

    def _require_mempool(self) -> Mempool:
        if self.mempool is None:
            raise RPCError(INTERNAL_ERROR, "mempool not available")
        return self.mempool

    def _admit_tx(self, tx: bytes):
        """Awaitable CheckTx admission through the coalescing batcher
        when the mempool supports batch admission; serial otherwise
        (custom Mempool implementations keep working)."""
        mp = self._require_mempool()
        if not hasattr(mp, "check_tx_batch"):
            # tmsafe: safe-unvalidated-use-ok — a tx is opaque app
            # bytes with no validate_basic of its own; CheckTx IS the
            # validation (and _decode_tx_param already bounds the
            # base64 payload by the HTTP body limit). One shared
            # admission chokepoint for all three broadcast routes.
            return mp.check_tx(tx, TxInfo())
        if self._admission is None or self._admission._mp is not mp:
            self._admission = _AdmissionBatcher(
                mp,
                max_batch=getattr(
                    getattr(mp, "cfg", None), "tx_batch_size", 64
                ),
            )
        return self._admission.check_tx(tx)

    async def broadcast_tx_async(self, req: RPCRequest):
        """Fire-and-forget (reference: mempool.go:22)."""
        self._require_mempool()
        tx = _decode_tx_param(req.params)

        async def _check():
            try:
                await self._admit_tx(tx)
            except MempoolError as e:
                self.logger.info("async tx rejected", err=str(e))

        profiler.label_task(
            asyncio.ensure_future(_check()), "rpc:broadcast-async-check"
        )
        return {"hash": tx_hash(tx).hex()}

    async def broadcast_tx_sync(self, req: RPCRequest):
        """Wait for CheckTx result (reference: mempool.go:38)."""
        tx = _decode_tx_param(req.params)
        try:
            res = await self._admit_tx(tx)
        except MempoolError as e:
            raise RPCError(INTERNAL_ERROR, f"tx rejected: {e}")
        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "codespace": res.codespace,
            "hash": tx_hash(tx).hex(),
        }

    async def check_tx(self, req: RPCRequest):
        """CheckTx against the query connection without adding to the
        pool (reference: mempool.go:135)."""
        if self.proxy is None:
            raise RPCError(INTERNAL_ERROR, "proxy app not available")
        from ..abci import types as abci

        tx = _decode_tx_param(req.params)
        res = await self.proxy.query.check_tx(abci.RequestCheckTx(tx=tx))
        return encode(res)

    async def remove_tx(self, req: RPCRequest):
        """reference: mempool.go:149 (by tx key = sha256 of tx)."""
        mp = self._require_mempool()
        key = _decode_hash_param(req.params, "tx_key")
        mp.remove_tx_by_key(key)
        return {}

    async def broadcast_tx_commit(self, req: RPCRequest):
        """Subscribe to the tx event, CheckTx, then wait for delivery in
        a block (reference: mempool.go:58-129)."""
        self._require_mempool()
        if self.event_bus is None:
            raise RPCError(INTERNAL_ERROR, "event bus not available")
        tx = _decode_tx_param(req.params)
        txh = tx_hash(tx)
        query = (
            f"{tme.EVENT_TYPE_KEY}='{tme.EventValue.TX}'"
            f" AND {tme.TX_HASH_KEY}='{txh.hex().upper()}'"
        )
        # unique per request: concurrent submissions of the SAME tx must
        # not collide on the (client_id, query) subscription key
        self._commit_waiters += 1
        client_id = (
            f"broadcast_tx_commit-{txh.hex()[:16]}-{self._commit_waiters}"
        )
        try:
            sub = self.event_bus.subscribe(client_id, query, limit=1)
        except SubscriptionError as e:
            raise RPCError(INTERNAL_ERROR, str(e))
        try:
            try:
                check = await self._admit_tx(tx)
            except MempoolError as e:
                raise RPCError(INTERNAL_ERROR, f"tx rejected: {e}")
            result: Dict[str, Any] = {
                "check_tx": encode(check),
                "hash": txh.hex(),
                "height": 0,
                "deliver_tx": None,
            }
            if check.code != 0:
                return result
            timeout = (
                self.cfg.rpc.timeout_broadcast_tx_commit
                if self.cfg is not None
                else 10.0
            )
            try:
                msg = await asyncio.wait_for(sub.next(), timeout)
            except asyncio.TimeoutError:
                raise RPCError(
                    INTERNAL_ERROR,
                    "timed out waiting for tx to be included in a block",
                )
            ev: tme.EventDataTx = msg.data
            result["height"] = ev.height
            result["deliver_tx"] = encode(ev.result)
            return result
        finally:
            self.event_bus.unsubscribe_all(client_id)

    async def unconfirmed_txs(self, req: RPCRequest):
        """reference: mempool.go:160."""
        mp = self._require_mempool()
        limit = int(req.params.get("limit", 30))
        txs = mp.reap_max_txs(limit)
        return {
            "n_txs": len(txs),
            "total": mp.size(),
            "total_bytes": mp.size_bytes(),
            "txs": [_b64(tx) for tx in txs],
        }

    async def num_unconfirmed_txs(self, req: RPCRequest):
        mp = self._require_mempool()
        return {
            "n_txs": mp.size(),
            "total": mp.size(),
            "total_bytes": mp.size_bytes(),
        }

    async def unsafe_flush_mempool(self, req: RPCRequest):
        mp = self._require_mempool()
        mp.flush()
        return {}

    # -- ABCI passthrough (reference: internal/rpc/core/abci.go) --

    async def abci_query(self, req: RPCRequest):
        if self.proxy is None:
            raise RPCError(INTERNAL_ERROR, "proxy app not available")
        from ..abci import types as abci

        data = req.params.get("data", "")
        if not isinstance(data, str):
            raise RPCError(INVALID_PARAMS, "data must be a hex string")
        try:
            data_b = bytes.fromhex(data)
        except ValueError:
            raise RPCError(INVALID_PARAMS, "data is not valid hex")
        res = await self.proxy.query.query(
            abci.RequestQuery(
                data=data_b,
                path=req.params.get("path", ""),
                height=int(req.params.get("height", 0)),
                prove=bool(req.params.get("prove", False)),
            )
        )
        return {"response": encode(res)}

    async def abci_info(self, req: RPCRequest):
        if self.proxy is None:
            raise RPCError(INTERNAL_ERROR, "proxy app not available")
        from ..abci import types as abci

        res = await self.proxy.query.info(abci.RequestInfo())
        return {"response": encode(res)}

    # -- evidence (reference: internal/rpc/core/evidence.go) --

    async def broadcast_evidence(self, req: RPCRequest):
        if self.evidence_pool is None:
            raise RPCError(INTERNAL_ERROR, "evidence pool not available")
        from ..types.evidence import evidence_from_proto

        raw = req.params.get("evidence")
        if not isinstance(raw, str):
            raise RPCError(
                INVALID_PARAMS, "missing evidence param (hex proto)"
            )
        try:
            ev = evidence_from_proto(bytes.fromhex(raw))
            # validate-before-use (tmsafe safe-unvalidated-use): basic
            # shape checks run before the pool is touched
            ev.validate_basic()
        except Exception as e:
            raise RPCError(INVALID_PARAMS, f"invalid evidence: {e}")
        try:
            self.evidence_pool.add_evidence(ev)
        except Exception as e:
            raise RPCError(INTERNAL_ERROR, f"evidence rejected: {e}")
        return {"hash": ev.hash().hex()}

    # -- tx / block search (reference: internal/rpc/core/tx.go,
    #    blocks.go:244 BlockSearch) --

    def _search_sink(self) -> EventSink:
        """First search-capable sink. The reference only serves search
        from its kv sink (the psql sink defers to raw SQL,
        indexer/sink/psql/psql.go:238-256); our SQL sink answers the
        read surface too, so any sink except Null qualifies."""
        for s in self.event_sinks:
            if s.type() in ("kv", "psql"):
                return s
        raise RPCError(
            INTERNAL_ERROR,
            "tx indexing is disabled (no search-capable sink)",
        )

    async def tx(self, req: RPCRequest):
        sink = self._search_sink()
        h = _decode_hash_param(req.params)
        res = sink.get_tx_by_hash(h)
        if res is None:
            raise RPCError(INVALID_PARAMS, f"tx {h.hex()} not found")
        return {
            "hash": h.hex(),
            "height": res.height,
            "index": res.index,
            "tx_result": encode(res.result),
            "tx": _b64(res.tx),
        }

    async def tx_search(self, req: RPCRequest):
        sink = self._search_sink()
        query = req.params.get("query")
        if not isinstance(query, str):
            raise RPCError(INVALID_PARAMS, "missing query param")
        results = sink.search_tx_events(query)
        if bool(req.params.get("order_by") == "desc"):
            results = list(reversed(results))
        page = int(req.params.get("page", 1))
        per_page = min(int(req.params.get("per_page", 30)), 100)
        start = (page - 1) * per_page
        sel = results[start : start + per_page]
        return {
            "txs": [
                {
                    "hash": tx_hash(r.tx).hex(),
                    "height": r.height,
                    "index": r.index,
                    "tx_result": encode(r.result),
                    "tx": _b64(r.tx),
                }
                for r in sel
            ],
            "total_count": len(results),
        }

    async def block_search(self, req: RPCRequest):
        sink = self._search_sink()
        query = req.params.get("query")
        if not isinstance(query, str):
            raise RPCError(INVALID_PARAMS, "missing query param")
        heights = sink.search_block_events(query)
        if req.params.get("order_by") == "desc":
            heights = list(reversed(heights))
        page = int(req.params.get("page", 1))
        per_page = min(int(req.params.get("per_page", 30)), 100)
        start = (page - 1) * per_page
        sel = heights[start : start + per_page]
        blocks = []
        for h in sel:
            meta = self.block_store.load_block_meta(h)
            block = self.block_store.load_block(h)
            if meta is not None and block is not None:
                blocks.append(
                    {
                        "block_id": encode(meta.block_id),
                        "block": encode(block),
                    }
                )
        return {"blocks": blocks, "total_count": len(heights)}

    async def light_block(self, req: RPCRequest):
        """SignedHeader + ValidatorSet as proto hex — the light
        client's HTTP provider surface (reference: light/provider/http
        assembles the same from /commit + /validators; one proto blob
        round-trips exactly). Served from the per-block blob cache:
        the encode is paid once per block, not per request (tmcost
        cost-recompute, first-run finding)."""
        height = self._height_param(req.params)
        blob = self.serving_cache.encoded_light_block(height)
        if blob is None:
            raise RPCError(
                INVALID_PARAMS, f"no light block at height {height}"
            )
        return {"height": height, "light_block": blob.hex()}

    async def light_blocks(self, req: RPCRequest):
        """Bulk stateless serving: consecutive LightBlocks for
        [min_height, max_height] ascending, as one proto-hex
        LightBlocksResponse page. The page is hard-clamped at
        LIGHT_BLOCKS_PAGE_CAP server-side (an optional `max_blocks`
        param may shrink it, never grow it); a height whose parts are
        missing ends the page — a bulk reply never has gaps, so
        bisecting clients can trust consecutive heights. `last_height`
        carries the store tip so a clamped client knows whether to ask
        for the next page (framework route; the reference serves this
        shape one height at a time via /commit + /validators)."""
        from ..encoding.proto import ProtoWriter

        top = self.block_store.height()
        base = self.block_store.base()
        max_h = min(int(req.params.get("max_height", top) or top), top)
        min_h = max(int(req.params.get("min_height", base) or base), base)
        cap = LIGHT_BLOCKS_PAGE_CAP
        max_blocks = int(req.params.get("max_blocks", 0) or 0)
        if 0 < max_blocks < cap:
            cap = max_blocks
        # ascending page, count explicitly capped: both bounds are
        # client-chosen ints, so the loop bound must be a clamp
        # expression, not a subtraction of two attacker values (same
        # rule the blockchain route pins). The page is assembled from
        # per-block cached `LightBlock.to_proto()` blobs (byte-
        # identical to LightBlocksResponse.to_proto, pinned by test) —
        # the per-request re-load + re-encode was tmcost's first-run
        # cost-recompute finding, and the serving cache is the fix
        with trace.span("light_blocks", min_height=min_h):
            w = ProtoWriter()
            count = 0
            for off in range(min(max_h - min_h + 1, cap)):
                blob = self.serving_cache.encoded_light_block(
                    min_h + off
                )
                if blob is None:
                    break
                w.message(1, blob)
                count += 1
            w.int(2, top)
            self.metrics.light_blocks_requests.inc()
            self.metrics.light_blocks_batch_size.observe(count)
            trace.add_attrs(count=count)
            return {
                "count": count,
                "last_height": top,
                "light_blocks": w.finish().hex(),
            }

    async def tx_proofs(self, req: RPCRequest):
        """Merkle inclusion proofs for transactions of one block,
        served from the held per-block MerkleMultiTree (the PR-11
        stateless-serving workhorse, finally wired to a route): pure
        aunt gathering per request, the tree built once per block.
        Params: height (as everywhere), `indices` = list of tx indexes
        (server-clamped at TX_PROOFS_CAP; shrink-only like the
        light_blocks page). Proofs verify against `header.data_hash`
        (root == types/tx.txs_hash), so a stateless client needs only
        a verified header to check them (framework route; the
        reference serves per-tx proofs via /tx?prove=true)."""
        height = self._height_param(req.params)
        raw = req.params.get("indices")
        if not isinstance(raw, list):
            raise RPCError(
                INVALID_PARAMS, "indices must be a list of ints"
            )
        # clamp BEFORE validating: even the type scan must not cost
        # more than the serving bound (excess indices are dropped —
        # shrink-only, like the light_blocks page)
        raw = raw[:TX_PROOFS_CAP]
        if not all(
            isinstance(i, int) and not isinstance(i, bool) for i in raw
        ):
            raise RPCError(
                INVALID_PARAMS, "indices must be a list of ints"
            )
        with trace.span("tx_proofs", height=height):
            tree = self.serving_cache.tx_tree(height)
            if tree is None:
                raise RPCError(
                    INVALID_PARAMS, f"no block at height {height}"
                )
            try:
                # OverflowError too: an index past int64 fails inside
                # numpy's asarray, and it is client input, not a server
                # fault
                proofs = tree.proofs(raw)
            except (ValueError, OverflowError) as e:
                raise RPCError(INVALID_PARAMS, str(e))
            self.metrics.tx_proofs_requests.inc()
            trace.add_attrs(count=len(proofs))
            return {
                "height": height,
                "total": tree.total,
                "root": tree.root.hex(),
                "proofs": [p.to_proto_bytes().hex() for p in proofs],
            }

    # -- subscriptions (websocket only; reference: events.go) --

    _MAX_SUBS_PER_CLIENT = 5

    async def subscribe(self, req: RPCRequest):
        if req.ws is None:
            raise RPCError(
                INVALID_PARAMS, "subscribe requires a websocket connection"
            )
        if self.event_bus is None:
            raise RPCError(INTERNAL_ERROR, "event bus not available")
        query = req.params.get("query")
        if not isinstance(query, str):
            raise RPCError(INVALID_PARAMS, "missing query param")
        ws = req.ws
        # register cleanup BEFORE anything can fail: a client whose only
        # subscribe attempts error out must still be swept on disconnect
        if ws.on_close is None:
            ws.on_close = self._ws_disconnected
        limit = (
            self.cfg.rpc.max_subscriptions_per_client
            if self.cfg is not None
            else self._MAX_SUBS_PER_CLIENT
        )
        subs = self._ws_subs.setdefault(ws.client_id, set())
        if len(subs) >= limit:
            raise RPCError(
                INTERNAL_ERROR, "too many subscriptions for this client"
            )
        if query in subs:
            raise RPCError(INVALID_PARAMS, "already subscribed to query")
        try:
            sub = self.event_bus.subscribe(ws.client_id, query, limit=100)
        except SubscriptionError as e:
            raise RPCError(INTERNAL_ERROR, str(e))
        except ValueError as e:
            raise RPCError(INVALID_PARAMS, f"invalid query: {e}")
        subs.add(query)
        profiler.label_task(
            asyncio.ensure_future(
                self._pump_events(ws, sub, query, req.req_id)
            ),
            "rpc:subscription-pump",
        )
        return {}

    @staticmethod
    def _notification_text(msg, query: str, req_id) -> str:
        """One JSON-RPC notification frame as text.

        The expensive part — encode() of the event payload (a full
        block for NewBlock) plus its json.dumps — is computed once per
        published Message and cached on it, so a thousand subscribers
        sharing the pubsub group's frozen Message each pay only a
        req_id/query splice instead of a full re-serialization (the
        N× redundancy the PR-16 ledger ranked top of the serving side).
        """
        body = getattr(msg, "_rpc_body", None)
        if body is None:
            body = json.dumps(
                {
                    "data": {
                        "type": type(msg.data).__name__,
                        "value": encode(msg.data),
                    },
                    "events": encode(msg.events),
                }
            )[1:-1]  # strip the braces: '"data": ..., "events": ...'
            # cache on the (frozen) Message: a cache write, not a
            # semantic mutation — every reader derives the same bytes
            object.__setattr__(msg, "_rpc_body", body)
        return '{"jsonrpc": "2.0", "id": %s, "result": {"query": %s, %s}}' % (
            json.dumps(req_id),
            json.dumps(query),
            body,
        )

    async def _pump_events(self, ws, sub, query: str, req_id) -> None:
        """Forward matching events as JSON-RPC notifications until the
        subscription dies or the socket closes (reference:
        events.go:50-85)."""
        try:
            while not ws.closed.is_set():
                msg = await sub.next()
                await ws.send_text(
                    self._notification_text(msg, query, req_id)
                )
        except SubscriptionError as e:
            # a subscriber dropped for lagging (queue overflow) is told
            # WHY its feed died — a fleet client (and the load harness)
            # must distinguish "no events matched" from "you were shed"
            # (clean unsubscribes stay silent: the client asked)
            if str(e) == ERR_TERMINATED and not ws.closed.is_set():
                await ws.send_json(
                    {
                        "jsonrpc": "2.0",
                        "id": req_id,
                        "error": RPCError(
                            INTERNAL_ERROR,
                            ERR_TERMINATED,
                            data=query,
                        ).to_obj(),
                    }
                )
            self._ws_subs.get(ws.client_id, set()).discard(query)
        except asyncio.CancelledError:
            pass

    async def unsubscribe(self, req: RPCRequest):
        if req.ws is None or self.event_bus is None:
            raise RPCError(
                INVALID_PARAMS, "unsubscribe requires a websocket connection"
            )
        query = req.params.get("query")
        if not isinstance(query, str):
            raise RPCError(INVALID_PARAMS, "missing query param")
        try:
            self.event_bus.unsubscribe(req.ws.client_id, query)
        except SubscriptionError:
            raise RPCError(INVALID_PARAMS, "subscription not found")
        self._ws_subs.get(req.ws.client_id, set()).discard(query)
        return {}

    async def unsubscribe_all(self, req: RPCRequest):
        if req.ws is None or self.event_bus is None:
            raise RPCError(
                INVALID_PARAMS,
                "unsubscribe_all requires a websocket connection",
            )
        try:
            self.event_bus.unsubscribe_all(req.ws.client_id)
        except SubscriptionError:
            pass  # idempotent: no subscriptions is fine
        self._ws_subs.pop(req.ws.client_id, None)
        return {}

    def _ws_disconnected(self, ws) -> None:
        if self.event_bus is not None:
            try:
                self.event_bus.unsubscribe_all(ws.client_id)
            except SubscriptionError:
                pass  # client already unsubscribed everything
        self._ws_subs.pop(ws.client_id, None)
