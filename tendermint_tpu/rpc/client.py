"""Async JSON-RPC clients: HTTP and websocket.

reference: rpc/jsonrpc/client/{http_json_client,ws_client}.go and
rpc/client/http. Used by tests, the CLI, and the light client's RPC
provider. Raw asyncio streams — the same zero-dependency approach as
the server.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import itertools
import json
import os
import struct
from typing import Any, Dict, Optional

__all__ = ["RPCClientError", "HTTPClient", "LocalClient", "WSClient"]

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class RPCClientError(Exception):
    """JSON-RPC error response, or transport failure."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


def _parse_addr(addr: str) -> tuple[str, int]:
    addr = addr.replace("tcp://", "").replace("http://", "")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class HTTPClient:
    """One JSON-RPC call per HTTP/1.1 request (keep-alive reuse)."""

    def __init__(self, addr: str, timeout: float = 10.0) -> None:
        self.host, self.port = _parse_addr(addr)
        self.timeout = timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()

    async def _connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None

    async def call(self, method: str, **params: Any) -> Any:
        """Returns the JSON-RPC result or raises RPCClientError."""
        async with self._lock:
            try:
                return await asyncio.wait_for(
                    self._call_locked(method, params), self.timeout
                )
            except asyncio.TimeoutError:
                # the request may still be in flight server-side; a
                # reused connection would hand its late response to the
                # NEXT call, so drop the connection
                await self.close()
                raise

    async def _call_locked(self, method: str, params: Dict[str, Any]):
        rid = next(self._ids)
        body = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": rid,
                "method": method,
                "params": params,
            }
        ).encode()
        for attempt in (0, 1):
            if self._writer is None:
                await self._connect()
            try:
                self._writer.write(
                    (
                        f"POST / HTTP/1.1\r\n"
                        f"Host: {self.host}\r\n"
                        "Content-Type: application/json\r\n"
                        f"Content-Length: {len(body)}\r\n\r\n"
                    ).encode()
                    + body
                )
                await self._writer.drain()
                resp = await self._read_response()
                break
            except (ConnectionError, asyncio.IncompleteReadError):
                # server closed the keep-alive conn; retry once fresh
                await self.close()
                if attempt:
                    raise
        if resp.get("id") != rid:
            # desynchronized keep-alive stream (e.g. a stale response
            # from an aborted call): poison the connection
            await self.close()
            raise RPCClientError(
                f"response id {resp.get('id')} != request id {rid}"
            )
        return _unwrap(resp)

    async def _read_response(self) -> Any:
        status_line = await self._reader.readline()
        if not status_line:
            raise ConnectionError("connection closed")
        parts = status_line.decode("latin-1").split(" ", 2)
        status = int(parts[1])
        headers: Dict[str, str] = {}
        while True:
            line = await self._reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0") or "0")
        body = await self._reader.readexactly(n) if n else b""
        if status != 200:
            raise RPCClientError(
                f"http status {status}: {body[:200]!r}", code=status
            )
        return json.loads(body)


def _unwrap(resp: Any) -> Any:
    if "error" in resp:
        err = resp["error"]
        raise RPCClientError(
            f"{err.get('message')} ({err.get('data', '')})",
            code=err.get("code"),
        )
    return resp.get("result")


class WSClient:
    """Websocket JSON-RPC client with server-push support.

    `call` matches responses by id; pushed notifications (subscription
    events, which reuse the subscribe request's id) are delivered via
    `next_event`.
    """

    def __init__(
        self,
        addr: str,
        timeout: float = 10.0,
        max_frame: int = 10 << 20,
    ) -> None:
        """max_frame bounds a hostile server's declared frame length;
        raise it only for a trusted (e.g. local) endpoint whose block
        dumps legitimately exceed 10 MB."""
        self.host, self.port = _parse_addr(addr)
        self.timeout = timeout
        self.max_frame = max_frame
        self._reader = None
        self._writer = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._events: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._recv_task: Optional[asyncio.Task] = None
        self._sub_ids: set = set()

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        key = base64.b64encode(os.urandom(16)).decode()
        self._writer.write(
            (
                "GET /websocket HTTP/1.1\r\n"
                f"Host: {self.host}\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        await self._writer.drain()
        status = await self._reader.readline()
        if b"101" not in status:
            raise RPCClientError(f"websocket handshake failed: {status!r}")
        expect = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        ok = False
        while True:
            line = await self._reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode("latin-1").partition(":")
            if k.strip().lower() == "sec-websocket-accept":
                ok = v.strip() == expect
        if not ok:
            raise RPCClientError("websocket accept mismatch")
        self._recv_task = asyncio.ensure_future(self._recv_loop())

    async def close(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            self._recv_task = None
        if self._writer is not None:
            try:
                self._writer.write(self._frame(0x8, b""))
                await self._writer.drain()
            except ConnectionError:
                pass
            self._writer.close()
            self._writer = None

    def _frame(self, opcode: int, payload: bytes) -> bytes:
        """Client->server frames must be masked (RFC 6455 §5.3)."""
        head = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head += bytes([0x80 | n])
        elif n < (1 << 16):
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        mask = os.urandom(4)
        body = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return head + mask + body

    async def _recv_loop(self) -> None:
        from .jsonrpc import _read_frame  # shared parser

        try:
            while True:
                opcode, payload = await _read_frame(
                    self._reader, max_frame=self.max_frame
                )
                if opcode == 0x8:
                    break
                if opcode == 0x9:  # ping -> pong
                    self._writer.write(self._frame(0xA, payload))
                    await self._writer.drain()
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                obj = json.loads(payload)
                rid = obj.get("id")
                fut = self._pending.pop(rid, None)
                if fut is not None and not fut.done():
                    fut.set_result(obj)
                elif rid in self._sub_ids:
                    try:
                        self._events.put_nowait(obj)
                    except asyncio.QueueFull:
                        pass
        except (
            ConnectionError,
            asyncio.IncompleteReadError,
            asyncio.CancelledError,
            ValueError,
        ):
            pass
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(RPCClientError("connection closed"))
            self._pending.clear()

    async def call(self, method: str, **params: Any) -> Any:
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[rid] = fut
        if method == "subscribe":
            self._sub_ids.add(rid)
        self._writer.write(
            self._frame(
                0x1,
                json.dumps(
                    {
                        "jsonrpc": "2.0",
                        "id": rid,
                        "method": method,
                        "params": params,
                    }
                ).encode(),
            )
        )
        await self._writer.drain()
        resp = await asyncio.wait_for(fut, self.timeout)
        return _unwrap(resp)

    async def next_event(self, timeout: float = 10.0) -> Any:
        """Next pushed subscription event's `result` object."""
        obj = await asyncio.wait_for(self._events.get(), timeout)
        return obj.get("result")


class LocalClient:
    """In-process client: calls the node's RPC handlers directly
    against its Environment — same surface as HTTPClient.call but no
    network hop (reference: rpc/client/local/local.go). Websocket-only
    methods (subscribe/unsubscribe) are not supported here; in-process
    consumers subscribe on the event bus directly."""

    def __init__(self, env) -> None:
        self._routes = env.routes()
        self._ids = itertools.count(1)

    @classmethod
    def from_node(cls, node) -> "LocalClient":
        if node.rpc_env is None:
            raise RPCClientError(
                "node has no RPC environment (rpc.laddr disabled "
                "or node not started)"
            )
        return cls(node.rpc_env)

    async def call(self, method: str, **params: Any) -> Any:
        from .jsonrpc import (
            INTERNAL_ERROR,
            INVALID_PARAMS,
            RPCError,
            RPCRequest,
        )

        handler = self._routes.get(method)
        if handler is None:
            raise RPCClientError(f"unknown method {method!r}")
        if method in ("subscribe", "unsubscribe", "unsubscribe_all"):
            raise RPCClientError(
                f"{method} requires a websocket; use the event bus "
                "for in-process subscriptions"
            )
        req = RPCRequest(
            method=method, params=dict(params), req_id=next(self._ids)
        )
        # mirror the server's error mapping (jsonrpc._dispatch) so a
        # caller written against HTTPClient sees identical failures
        try:
            return await handler(req)
        except RPCError as e:
            raise RPCClientError(e.message, code=e.code) from e
        except (TypeError, ValueError, KeyError) as e:
            raise RPCClientError(str(e), code=INVALID_PARAMS) from e
        except Exception as e:
            raise RPCClientError(repr(e), code=INTERNAL_ERROR) from e
