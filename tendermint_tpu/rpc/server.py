"""RPC server service: binds the route table to the JSON-RPC machinery.

reference: node/node.go:480-540 (startRPC) + rpc/jsonrpc/server.
"""

from __future__ import annotations

from typing import Optional

from ..libs.log import get_logger
from ..libs.service import Service
from .core import Environment
from .jsonrpc import JSONRPCServer

__all__ = ["RPCServer"]


def _split_laddr(laddr: str) -> tuple[str, int]:
    addr = laddr.replace("tcp://", "")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class RPCServer(Service):
    """Serves the Environment's routes over HTTP/WS on cfg.rpc.laddr."""

    def __init__(
        self,
        env: Environment,
        laddr: str = "tcp://127.0.0.1:26657",
        max_body_bytes: int = 1_000_000,
    ) -> None:
        super().__init__(name="rpc", logger=get_logger("rpc"))
        self.env = env
        self.laddr = laddr
        self._srv: Optional[JSONRPCServer] = None
        self._max_body = max_body_bytes

    @property
    def bound_port(self) -> int:
        """Actual listen port (laddr may specify port 0 in tests)."""
        assert self._srv is not None
        return self._srv.bound_port

    async def on_start(self) -> None:
        host, port = _split_laddr(self.laddr)
        self._srv = JSONRPCServer(
            self.env.routes(),
            max_body_bytes=self._max_body,
            metrics=self.env.metrics,
        )
        await self._srv.start(host, port)
        self.logger.info("rpc server listening", addr=f"{host}:{self.bound_port}")

    async def on_stop(self) -> None:
        if self._srv is not None:
            await self._srv.stop()
            self._srv = None
