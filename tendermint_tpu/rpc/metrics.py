"""RPC metrics struct (go-kit pattern, like consensus/metrics.py).

One struct holding the rpc-layer instruments, built against a Registry
and threaded through Environment construction. Node assembly passes a
per-node Registry so in-process localnet nodes keep disjoint series;
constructing without one lands on DEFAULT_REGISTRY (idempotent —
repeated default constructions share instruments).
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["RPCMetrics"]


class RPCMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.light_blocks_requests = r.counter(
            "rpc",
            "light_blocks_requests",
            "Bulk light_blocks requests served.",
        )
        self.light_blocks_batch_size = r.histogram(
            "rpc",
            "light_blocks_batch_size",
            "Light blocks returned per bulk light_blocks request.",
            buckets=(1.0, 2.0, 5.0, 10.0, 20.0, 50.0),
        )
