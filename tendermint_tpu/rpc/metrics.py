"""RPC metrics struct (go-kit pattern, like consensus/metrics.py).

One struct holding the rpc-layer instruments, built against a Registry
and threaded through Environment construction (node assembly passes the
per-node Registry, so in-process localnet nodes keep disjoint series;
constructing without one lands on DEFAULT_REGISTRY — idempotent,
repeated default constructions share instruments).

Every JSON-RPC route gets the same per-route family, recorded by the
transport layer (rpc/jsonrpc.py _dispatch) so HTTP, URI-GET and
websocket requests all land in one place:

    rpc_requests_total{route=}        counter
    rpc_request_errors_total{route=}  counter (RPCError + handler crash)
    rpc_request_latency_seconds{route=,quantile=}  mergeable sketch
    rpc_inflight_requests{route=}     gauge

`route` label values are always server-known route names — an unknown
method increments the unlabeled `rpc_unknown_methods_total` instead,
so a client cannot mint unbounded label cardinality.

The struct also owns the per-route SLO policy: a request slower than
`slo_for(route)` captures a slow-request exemplar (libs/trace.py
`record_slow_request`; see docs/load.md for the policy rationale).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["RPCMetrics", "DEFAULT_SLO_S", "ROUTE_SLO_S"]

# default per-request SLO: anything over this is an outlier worth a
# captured exemplar on an interactive serving path
DEFAULT_SLO_S = 1.0

# per-route overrides for routes that are slow BY DESIGN — their SLO is
# their documented contract, not the interactive default
ROUTE_SLO_S: Dict[str, float] = {
    # waits for the tx to be committed in a block (cfg
    # rpc.timeout_broadcast_tx_commit bounds it at 10 s by default)
    "broadcast_tx_commit": 15.0,
}


class RPCMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.requests_total = r.counter(
            "rpc",
            "requests_total",
            "JSON-RPC requests dispatched, by route.",
            label_names=("route",),
        )
        self.request_errors = r.counter(
            "rpc",
            "request_errors_total",
            "JSON-RPC requests answered with an error, by route.",
            label_names=("route",),
        )
        self.request_latency = r.sketch(
            "rpc",
            "request_latency_seconds",
            "Per-route request latency (mergeable log-bucketed sketch; "
            "1% relative-error bound, see docs/metrics.md).",
            label_names=("route",),
        )
        self.inflight = r.gauge(
            "rpc",
            "inflight_requests",
            "JSON-RPC requests currently executing, by route.",
            label_names=("route",),
        )
        self.unknown_methods = r.counter(
            "rpc",
            "unknown_methods_total",
            "Requests for methods with no route (not labeled: method "
            "names are client-chosen).",
        )
        self.ws_connections = r.gauge(
            "rpc",
            "ws_connections",
            "Live websocket connections.",
        )
        self.ws_send_queue_depth = r.histogram(
            "rpc",
            "ws_send_queue_depth",
            "Websocket subscriber send-queue depth sampled at each "
            "enqueue (the per-subscriber lag signal; the queue cap is "
            "512, overflow drops the subscriber).",
            buckets=(0.0, 1.0, 4.0, 16.0, 64.0, 128.0, 256.0, 512.0),
        )
        self.ws_slow_clients_dropped = r.counter(
            "rpc",
            "ws_slow_clients_dropped_total",
            "Websocket subscribers disconnected because their send "
            "queue overflowed.",
        )
        self.slow_requests = r.counter(
            "rpc",
            "slow_requests_total",
            "Requests that exceeded their per-route SLO threshold "
            "(each also captures a trace exemplar when enabled).",
            label_names=("route",),
        )
        # bulk light_blocks keeps its route-specific instruments
        # (batch size has no generic analog)
        self.light_blocks_requests = r.counter(
            "rpc",
            "light_blocks_requests",
            "Bulk light_blocks requests served.",
        )
        self.light_blocks_batch_size = r.histogram(
            "rpc",
            "light_blocks_batch_size",
            "Light blocks returned per bulk light_blocks request.",
            buckets=(1.0, 2.0, 5.0, 10.0, 20.0, 50.0),
        )
        self.tx_proofs_requests = r.counter(
            "rpc",
            "tx_proofs_requests",
            "tx_proofs requests served (merkle proofs from the held "
            "per-block tree).",
        )
        # per-block serving cache (rpc/servingcache.py): encoded
        # LightBlock blobs + held MerkleMultiTrees
        self.servingcache_hits = r.counter(
            "rpc",
            "servingcache_hits_total",
            "Per-block serving-cache hits (page encode / tree build "
            "skipped).",
        )
        self.servingcache_misses = r.counter(
            "rpc",
            "servingcache_misses_total",
            "Per-block serving-cache misses (artifact assembled from "
            "the stores).",
        )
        self.servingcache_evictions = r.counter(
            "rpc",
            "servingcache_evictions_total",
            "Per-block serving-cache entries dropped by the LRU bound.",
        )
        # SLO policy is per-struct (per-node): harnesses and tests
        # tighten thresholds without touching process-global state
        self.default_slo_s = DEFAULT_SLO_S
        self.slo_s: Dict[str, float] = dict(ROUTE_SLO_S)

    def slo_for(self, route: str) -> float:
        """The SLO threshold (seconds) for one route."""
        return self.slo_s.get(route, self.default_slo_s)
