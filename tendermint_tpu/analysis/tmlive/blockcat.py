"""Blocking-primitive catalog + site discovery for tmlive.

A serving node dies two ways under load: it *stalls* (a blocking call
on the event loop or under a hot lock) or it *ooms* (a shared
container that only grows). This module owns the first half's ground
truth: a reviewed catalog of blocking primitives, each classified
bounded/unbounded, and an AST pass that finds every call site of one
in the package — resolved through the same from-import/alias machinery
tmcheck's call graph uses, so `from time import sleep as nap` or
`import os as _os; _os.fsync(...)` cannot evade the catalog.

Two resolution shapes:

- **Module-function primitives** (`time.sleep`, `os.fsync`,
  `subprocess.run`, `jax.block_until_ready`, `sys.stdin.readline`,
  `urllib.request.urlopen`, `input`): matched against the *external
  dotted name* the call graph already resolves per call site, which
  folds aliases and from-imports back to canonical names.
- **Method primitives** (`Event.wait`, `Lock.acquire`, `Queue.get`,
  `Thread.join`, `Popen.wait/communicate`, socket verbs, file
  `flush`): matched by method name **plus receiver birth** — the
  receiver must resolve to an object created by a cataloged
  *blocking-class constructor* (`threading.Event()`, `queue.Queue()`,
  `socket.socket()`, `subprocess.Popen(...)`, `open(...)`) as a local
  variable, a `self.<attr>` field (birth sites collected across the
  class, base classes included), or a module global. An `asyncio.Event`
  never matches (its ctor module is asyncio), so the package's
  await-based idiom produces no noise, and an *unresolvable* receiver
  produces NO site — like tmcheck's edges, the catalog is deliberately
  under-approximate and docs/static_analysis.md says so.

`await`-wrapped calls and coroutine constructions are excluded up
front: an awaited `.wait()` parks a task, not the thread.

Boundedness is decided per *call site*, not per primitive: `ev.wait()`
is unbounded, `ev.wait(2.0)` bounded; `lock.acquire()` unbounded,
`lock.acquire(timeout=1)` bounded, `lock.acquire(blocking=False)` not
blocking at all; `subprocess.run(cmd)` unbounded,
`subprocess.run(cmd, timeout=30)` bounded; `time.sleep(0.1)` bounded,
`time.sleep(x)` unbounded (nothing proves x small). `os.fsync` has no
timeout form and is always unbounded — a saturated disk parks the
caller indefinitely, which is exactly the stall class the gate exists
for. Buffered `.flush()` is cataloged but classified bounded: it hands
bytes to the page cache; the durability stall lives in fsync.

The harness prefixes below are excluded from *rule* evaluation (their
sites still land in stats): the e2e process runner deliberately blocks
on subprocess lifecycles — it drives a localnet from a test, it is not
the serving path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..tmlint import dotted_name as _dotted
from ..tmcheck.callgraph import FuncInfo, ModuleIndex, Package, _body_walk

__all__ = [
    "BOUNDED",
    "UNBOUNDED",
    "NONBLOCKING",
    "HARNESS_PREFIXES",
    "BlockSite",
    "collect_sites",
]

FuncKey = Tuple[str, str]

BOUNDED = "bounded"
UNBOUNDED = "unbounded"
NONBLOCKING = "nonblocking"  # resolved to a cataloged primitive's
# explicitly non-blocking form (acquire(blocking=False), get_nowait)

# package paths whose blocking sites are catalogued but exempt from the
# serving-path rules: the e2e runners orchestrate OS subprocesses from
# a test-driven event loop — blocking on child lifecycles is their job,
# and nothing in them is reachable from a real node's serving path.
HARNESS_PREFIXES = ("e2e/",)


class BlockSite:
    """One blocking-primitive call site."""

    __slots__ = (
        "key", "path", "lineno", "col", "primitive", "kind", "detail"
    )

    def __init__(self, key, path, lineno, col, primitive, kind, detail):
        self.key = key  # enclosing FuncInfo key
        self.path = path
        self.lineno = lineno
        self.col = col
        self.primitive = primitive  # canonical name, e.g. "time.sleep"
        self.kind = kind  # BOUNDED | UNBOUNDED | NONBLOCKING
        self.detail = detail  # why it got that classification

    def render(self) -> str:
        return (
            f"{self.path}:{self.lineno} {self.primitive} "
            f"[{self.kind}] {self.detail}"
        )


# ---------------------------------------------------------------------------
# the module-function catalog (canonical external dotted name -> classifier)


def _has_timeout_kw(call: ast.Call, *names: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg in names:
            return kw.value
    return None


def _is_none(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _classify_sleep(call: ast.Call):
    arg = call.args[0] if call.args else _has_timeout_kw(call, "secs")
    if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
        return BOUNDED, f"constant {arg.value}s sleep"
    return UNBOUNDED, "sleep duration is not a constant"


def _classify_fsync(call: ast.Call):
    return UNBOUNDED, "fsync has no timeout form; a saturated disk parks the caller"


def _classify_subprocess(call: ast.Call):
    t = _has_timeout_kw(call, "timeout")
    if t is not None and not _is_none(t):
        return BOUNDED, "timeout= passed"
    return UNBOUNDED, "no timeout= on a child-process wait"


def _classify_device_sync(call: ast.Call):
    return (
        UNBOUNDED,
        "device sync point: a wedged claim parks the caller until the "
        "runtime gives up",
    )


def _classify_stdin(call: ast.Call):
    return UNBOUNDED, "waits for operator/peer input"


def _classify_urlopen(call: ast.Call):
    t = _has_timeout_kw(call, "timeout")
    if t is not None and not _is_none(t):
        return BOUNDED, "timeout= passed"
    return UNBOUNDED, "no timeout= on a synchronous HTTP fetch"


# canonical dotted name -> (classifier, note). The note is the reviewed
# rationale --list-rules/docs surface; classification happens per-site.
MODULE_PRIMITIVES = {
    "time.sleep": _classify_sleep,
    "os.fsync": _classify_fsync,
    "os.fdatasync": _classify_fsync,
    "subprocess.run": _classify_subprocess,
    "subprocess.call": _classify_subprocess,
    "subprocess.check_call": _classify_subprocess,
    "subprocess.check_output": _classify_subprocess,
    "jax.block_until_ready": _classify_device_sync,
    "jax.device_get": _classify_device_sync,
    "sys.stdin.readline": _classify_stdin,
    "sys.stdin.read": _classify_stdin,
    "input": _classify_stdin,
    "urllib.request.urlopen": _classify_urlopen,
    "socket.create_connection": _classify_urlopen,  # same timeout= form
}


# ---------------------------------------------------------------------------
# the method catalog: method name -> (blocking classes, classifier)

_THREADING_WAITABLES = {"Event", "Condition", "Barrier"}
_THREADING_LOCKS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore"}
_QUEUE_CLASSES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_SOCKET_CLASSES = {"socket"}
_POPEN_CLASSES = {"Popen"}
_THREAD_CLASSES = {"Thread", "Timer"}
_FILE_CLASSES = {"open"}  # open() births; annotations add Buffered*/TextIO*


def _classify_wait(call: ast.Call):
    arg = call.args[0] if call.args else _has_timeout_kw(call, "timeout")
    if arg is not None and not _is_none(arg):
        return BOUNDED, "timeout passed to wait()"
    return UNBOUNDED, "wait() with no timeout"


def _classify_acquire(call: ast.Call):
    blocking = (
        call.args[0] if call.args else _has_timeout_kw(call, "blocking")
    )
    if isinstance(blocking, ast.Constant) and blocking.value is False:
        return NONBLOCKING, "acquire(blocking=False)"
    timeout = (
        call.args[1]
        if len(call.args) >= 2
        else _has_timeout_kw(call, "timeout")
    )
    if timeout is not None and not _is_none(timeout):
        # acquire(timeout=-1) is the unbounded sentinel
        if isinstance(timeout, ast.Constant) and timeout.value in (-1,):
            return UNBOUNDED, "acquire(timeout=-1) blocks forever"
        return BOUNDED, "timeout passed to acquire()"
    return UNBOUNDED, "acquire() with no timeout"


def _classify_queue_get(call: ast.Call):
    return _classify_block_timeout(call, skip_args=0, what="get")


def _classify_queue_put(call: ast.Call):
    # put(item, block=True, timeout=None): the leading item shifts the
    # positional (block, timeout) pair by one vs get()
    return _classify_block_timeout(call, skip_args=1, what="put")


def _classify_block_timeout(call: ast.Call, skip_args: int, what: str):
    pos = call.args[skip_args:]
    block = pos[0] if pos else _has_timeout_kw(call, "block")
    if isinstance(block, ast.Constant) and block.value is False:
        return NONBLOCKING, f"{what}(block=False)"
    timeout = (
        pos[1] if len(pos) >= 2 else _has_timeout_kw(call, "timeout")
    )
    if timeout is not None and not _is_none(timeout):
        return BOUNDED, "timeout passed"
    return UNBOUNDED, "queue wait with no timeout"


def _classify_popen_wait(call: ast.Call):
    # wait(timeout=None): positional or keyword
    t = call.args[0] if call.args else _has_timeout_kw(call, "timeout")
    if t is not None and not _is_none(t):
        return BOUNDED, "timeout passed"
    return UNBOUNDED, "no timeout on a child-process wait"


def _classify_popen_communicate(call: ast.Call):
    # communicate(input=None, timeout=None): timeout is the SECOND
    # positional
    t = (
        call.args[1]
        if len(call.args) >= 2
        else _has_timeout_kw(call, "timeout")
    )
    if t is not None and not _is_none(t):
        return BOUNDED, "timeout passed"
    return UNBOUNDED, "no timeout on a child-process wait"


def _classify_join(call: ast.Call):
    arg = call.args[0] if call.args else _has_timeout_kw(call, "timeout")
    if arg is not None and not _is_none(arg):
        return BOUNDED, "timeout passed to join()"
    return UNBOUNDED, "join() with no timeout"


def _classify_socket_verb(call: ast.Call):
    # settimeout() state is invisible statically: classify unbounded
    # (documented over-approximation on the rare sync-socket path)
    return UNBOUNDED, "synchronous socket op (settimeout state unknowable)"


def _classify_flush(call: ast.Call):
    return BOUNDED, "buffered flush hands bytes to the page cache; the durability stall is fsync's"


def _classify_nonblocking(call: ast.Call):
    return NONBLOCKING, "explicitly non-blocking form"


# method name -> list of (receiver class names, ctor modules, classifier)
METHOD_PRIMITIVES: Dict[str, List[tuple]] = {
    "wait": [
        (_THREADING_WAITABLES, ("threading",), _classify_wait),
        (_POPEN_CLASSES, ("subprocess",), _classify_popen_wait),
    ],
    "acquire": [(_THREADING_LOCKS, ("threading",), _classify_acquire)],
    "get": [(_QUEUE_CLASSES, ("queue",), _classify_queue_get)],
    "put": [(_QUEUE_CLASSES, ("queue",), _classify_queue_put)],
    "get_nowait": [(_QUEUE_CLASSES, ("queue",), _classify_nonblocking)],
    "put_nowait": [(_QUEUE_CLASSES, ("queue",), _classify_nonblocking)],
    "join": [
        (_THREAD_CLASSES, ("threading",), _classify_join),
        (_QUEUE_CLASSES, ("queue",), _classify_join),
    ],
    "communicate": [
        (_POPEN_CLASSES, ("subprocess",), _classify_popen_communicate)
    ],
    "recv": [(_SOCKET_CLASSES, ("socket",), _classify_socket_verb)],
    "recv_into": [(_SOCKET_CLASSES, ("socket",), _classify_socket_verb)],
    "sendall": [(_SOCKET_CLASSES, ("socket",), _classify_socket_verb)],
    "accept": [(_SOCKET_CLASSES, ("socket",), _classify_socket_verb)],
    "connect": [(_SOCKET_CLASSES, ("socket",), _classify_socket_verb)],
    "flush": [(_FILE_CLASSES, ("", "io"), _classify_flush)],
    "block_until_ready": [
        # any receiver: the method name is jax-unique in this codebase
        (None, None, _classify_device_sync),
    ],
}

# annotation type names unambiguous enough to stand in for a birth site
# when no ctor is visible (Optional[subprocess.Popen] fields etc.)
_ANNOTATION_CLASSES = {
    "Popen": _POPEN_CLASSES,
    "Thread": _THREAD_CLASSES,
    "Timer": _THREAD_CLASSES,
    "BufferedWriter": _FILE_CLASSES,
    "BufferedReader": _FILE_CLASSES,
    "TextIOWrapper": _FILE_CLASSES,
}


# ---------------------------------------------------------------------------
# receiver birth resolution


def _ctor_class(mod: ModuleIndex, value: ast.AST) -> Optional[str]:
    """Canonical "<module>.<Class>" for a blocking-class constructor
    call, resolved through this module's import maps; None otherwise.
    `open(...)` births are returned as ".open"."""
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func)
    if not d:
        return None
    parts = d.split(".")
    if len(parts) == 1:
        name = parts[0]
        if name == "open":
            return ".open"
        entry = mod.from_imports.get(name)
        if entry is not None and entry[1] in (
            "threading", "queue", "socket", "subprocess"
        ):
            return f"{entry[1]}.{entry[2]}"
        return None
    head, cls = parts[0], parts[-1]
    target_mod = mod.import_alias.get(head)
    if target_mod in ("threading", "queue", "socket", "subprocess"):
        return f"{target_mod}.{cls}"
    return None


class _Births:
    """Where blocking-class instances are born: module globals,
    instance fields (per owning class, across the whole package so
    base-class fields resolve), and per-function locals."""

    def __init__(self, pkg: Package) -> None:
        self.pkg = pkg
        self.globals: Dict[Tuple[str, str], str] = {}
        self.fields: Dict[Tuple[str, str, str], str] = {}
        for mod in pkg.modules.values():
            for node in mod.tree.body:
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    born = _ctor_class(mod, node.value) if node.value else None
                    if born:
                        for t in targets:
                            if isinstance(t, ast.Name):
                                self.globals[(mod.path, t.id)] = born
            for cname, rec in mod.classes.items():
                for m in rec["methods"].values():
                    for node in ast.walk(m):
                        if not isinstance(node, ast.Assign):
                            continue
                        born = _ctor_class(mod, node.value)
                        if not born:
                            continue
                        for t in node.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ):
                                self.fields[(mod.path, cname, t.attr)] = born
                # unambiguous annotations fill in for invisible births
                for attr, tname in rec["attrs"].items():
                    if attr.startswith("*"):
                        continue
                    classes = _ANNOTATION_CLASSES.get(tname)
                    if classes is None:
                        continue
                    key = (mod.path, cname, attr)
                    if key not in self.fields:
                        mod_name = (
                            "subprocess"
                            if tname == "Popen"
                            else "threading"
                            if tname in _THREAD_CLASSES
                            else ""
                        )
                        self.fields[key] = f"{mod_name}.{tname}" if mod_name else ".open"

    def local_births(self, mod: ModuleIndex, fn: ast.AST) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in _body_walk(fn):
            if isinstance(node, ast.Assign):
                born = _ctor_class(mod, node.value)
                if born:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = born
        return out

    def field_birth(
        self, mod: ModuleIndex, cname: str, attr: str, _depth: int = 0
    ) -> Optional[str]:
        """Birth class of self.<attr> for `cname`, walking bases."""
        if _depth > 4:
            return None
        found = self.pkg.find_class(mod, cname)
        if found is None:
            return self.fields.get((mod.path, cname, attr))
        owner, rec = found
        got = self.fields.get((owner.path, rec["node"].name, attr))
        if got is not None:
            return got
        for base in rec["bases"]:
            got = self.field_birth(
                owner, base.split(".")[-1], attr, _depth + 1
            )
            if got is not None:
                return got
        return None


# ---------------------------------------------------------------------------
# site discovery


def _match_method(
    births: _Births,
    mod: ModuleIndex,
    fi: FuncInfo,
    call: ast.Call,
    local_births: Dict[str, str],
) -> Optional[Tuple[str, tuple]]:
    """(canonical primitive name, classifier) for a method-shaped
    blocking call whose receiver birth resolves; None otherwise."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    variants = METHOD_PRIMITIVES.get(func.attr)
    if variants is None:
        return None
    recv = func.value
    born: Optional[str] = None
    if isinstance(recv, ast.Name):
        born = local_births.get(recv.id) or births.globals.get(
            (mod.path, recv.id)
        )
    elif (
        isinstance(recv, ast.Attribute)
        and isinstance(recv.value, ast.Name)
        and recv.value.id == "self"
        and fi.class_name
    ):
        born = births.field_birth(mod, fi.class_name, recv.attr)
    for classes, modules, classifier in variants:
        if classes is None:  # receiver-free primitive (block_until_ready)
            return f"*.{func.attr}", classifier
        if born is None:
            continue
        bmod, _, bcls = born.rpartition(".")
        if bcls in classes and (bmod in modules or born == ".open"):
            return f"{born}.{func.attr}", classifier
    return None


def _awaited_positions(fn: ast.AST) -> Set[Tuple[int, int]]:
    """Positions of calls that construct/await coroutines: `await f()`,
    plus calls wrapped in ensure_future/create_task/wait_for (coroutine
    constructions handed to the loop, never executed synchronously)."""
    out: Set[Tuple[int, int]] = set()
    wrappers = {"ensure_future", "create_task", "wait_for", "gather", "shield"}
    for node in _body_walk(fn):
        inner = None
        if isinstance(node, ast.Await):
            inner = node.value
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d.split(".")[-1] in wrappers:
                for arg in node.args:
                    if isinstance(arg, ast.Call):
                        out.add((arg.lineno, arg.col_offset))
        if isinstance(inner, ast.Call):
            out.add((inner.lineno, inner.col_offset))
    return out


def collect_sites(pkg: Package) -> List[BlockSite]:
    """Every blocking-primitive call site in the package (harness
    prefixes included — rule evaluation filters them, stats keep
    them)."""
    births = _Births(pkg)
    sites: List[BlockSite] = []
    for fi in pkg.functions.values():
        mod = pkg.modules[fi.path]
        awaited = _awaited_positions(fi.node)
        local_births = births.local_births(mod, fi.node)
        ext_by_pos = {
            (c.lineno, c.col): c.external
            for c in fi.calls
            if c.external is not None
        }
        for node in _body_walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            pos = (node.lineno, node.col_offset)
            if pos in awaited:
                continue
            primitive = None
            classifier = None
            ext = ext_by_pos.get(pos)
            if ext is not None and ext in MODULE_PRIMITIVES:
                primitive, classifier = ext, MODULE_PRIMITIVES[ext]
            else:
                got = _match_method(births, mod, fi, node, local_births)
                if got is not None:
                    primitive, classifier = got
            if primitive is None:
                continue
            kind, detail = classifier(node)
            sites.append(
                BlockSite(
                    fi.key, fi.path, node.lineno, node.col_offset,
                    primitive, kind, detail,
                )
            )
    sites.sort(key=lambda s: (s.path, s.lineno, s.col))
    return sites
