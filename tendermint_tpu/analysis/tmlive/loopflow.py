"""`live-block-in-main-loop` / `live-unbounded-blocking` — the serving
path never stalls on disk, peer, or device.

Every `async def` in the package shares tmrace's single `main-loop`
identity: RPC and websocket handlers, the consensus receive loop, the
reactors, the mempool — one OS thread runs them all, so ONE unbounded
blocking call anywhere reachable from that identity stalls every
subscriber, every /healthz probe, and every vote in flight. This rule
is the static form of the chaos heartbeat test: prove no unbounded
blocking primitive (blockcat's catalog) is reachable from the
main-loop identity *without an executor hop*.

The executor hop comes for free from the substrate: `run_in_executor`
targets are their own spawned identities in tmrace's root catalog, and
the call graph records no direct edge through the executor — so
reachability from `main-loop` simply never crosses one. Awaited calls
were already excluded at catalog time (an awaited `.wait()` parks a
task, not the thread).

Unbounded sites reachable ONLY from spawned identities (a watchdog
thread parked on its wake Event, a probe thread inside a device call)
are the residual family `live-unbounded-blocking`: blocking there
stalls one worker, not the serving path, but it must still be a
*reviewed* decision — the fix-or-suppress pass is where "blocking is
this thread's job" gets written down next to the code. Sites flagged
by block-under-lock are excluded here (most-specific rule wins; one
site, one finding), as are blockcat's harness prefixes and sites not
reachable from any root at all (cold CLI/utility code — recorded in
stats, not findings).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..tmrace.threadroots import MAIN_IDENTITY, witness_chain

__all__ = ["MAIN_IDENTITY", "pick_rule", "main_witness"]

FuncKey = Tuple[str, str]


def pick_rule(
    identities: Dict[FuncKey, Set[str]],
    key: FuncKey,
    under_lock: bool,
) -> Optional[str]:
    """Most-specific rule for one unbounded blocking site (None when
    the enclosing function is unreachable from every thread root)."""
    if under_lock:
        return "live-block-under-lock"
    ids = identities.get(key, set())
    if MAIN_IDENTITY in ids:
        return "live-block-in-main-loop"
    if ids:
        return "live-unbounded-blocking"
    return None


def main_witness(pkg, parents, identities, key: FuncKey) -> str:
    """Rendered shortest root->site chain, preferring the main-loop
    identity (the one the finding is about)."""
    ids = identities.get(key, set())
    ident = MAIN_IDENTITY if MAIN_IDENTITY in ids else (
        sorted(ids)[0] if ids else None
    )
    if ident is None:
        return ""
    return " -> ".join(witness_chain(pkg, parents, ident, key))
