"""`live-grow-unbounded` — every shared container on the serving path
must have a boundedness story.

The OOM twin of the stall rules: a node serving millions of users dies
just as dead from a dict that gains an entry per request as from a
blocked event loop. This pass enumerates every *shared* container —
module globals and instance fields born as list/dict/set/deque (or
annotated as one) — that some root-reachable function grows
(`append`/`add`/`extend`/`insert`/`update`/`setdefault`/`[k] = v`/
`+=`), and demands a boundedness proof:

- **ring** — born `deque(maxlen=...)`: structurally bounded, done;
- **rotation / eviction / reset** — the same container identity has a
  shrink site anywhere in the package: `pop`/`popitem`/`popleft`/
  `remove`/`discard`/`clear`/`del x[k]`/plain reassignment (the
  sigcache two-generation rotate, PR-7's epoch-invalidated memo
  rebuilds, registry eviction, per-height resets all look like this);
- **reviewed annotation** — `# tmlive: bounded=<reason>` on the birth
  line or the grow site, for containers whose bound is a protocol or
  configuration fact the AST cannot see (a registry keyed by a fixed
  instrument-name set, a map capped by max-peers config).

Anything else is an OOM-at-scale finding. The structural recognizers
are deliberately generous — ANY shrink site anywhere counts, because
the gate's job is the container that *only ever grows*; a wrong or
insufficient eviction policy is a review problem, not a grep problem.
Per-site `# tmlive: grow-ok — why` suppressions exist for the rare
intentional case, same style as every other analyzer in the family.

Import-time grows (module-body statements) and grows inside
`__init__`/`__new__` on the object's OWN fields are construction, not
growth, and are skipped.

Receiver resolution covers bare names (scope-correct: function-local
bindings shadow), `self.<attr>` fields (owner-class attribution,
base classes walked), from-imported globals born in another module,
and module-attr receivers through import aliases/from-imports
(`sigcache._gen0.add(k)`). Receivers the resolver cannot type —
containers passed as arguments, elements pulled out of other
containers, dynamic attribute chains — produce NO grow site: like
blockcat and tmcheck's edges, the pass is deliberately
under-approximate and docs/static_analysis.md says so.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..tmlint import dotted_name as _dotted
from ..tmcheck.callgraph import FuncInfo, ModuleIndex, Package, _body_walk

__all__ = ["Container", "GrowSite", "collect_growth"]

FuncKey = Tuple[str, str]

_GROW_METHODS = {
    "append", "add", "extend", "insert", "update", "setdefault",
    "appendleft", "push",
}
_SHRINK_METHODS = {
    "pop", "popitem", "popleft", "remove", "discard", "clear",
    "difference_update", "truncate",
}

_CONTAINER_CTORS = {"list", "dict", "set", "frozenset", "deque",
                    "defaultdict", "OrderedDict", "Counter"}
_CONTAINER_ANNOTATIONS = {
    "List", "Dict", "Set", "MutableMapping", "DefaultDict", "Deque",
    "list", "dict", "set",
}


def _container_birth(mod: ModuleIndex, value: Optional[ast.AST]):
    """("kind", ring: bool) when `value` births a container: a literal
    [] / {} / set() / comprehension, or a ctor call (deque with a
    non-None maxlen is a ring). None otherwise."""
    if value is None:
        return None
    if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return (type(value).__name__.lower(), False)
    if isinstance(value, ast.Call):
        name = _dotted(value.func).split(".")[-1]
        if name in _CONTAINER_CTORS:
            ring = False
            if name == "deque":
                for kw in value.keywords:
                    if kw.arg == "maxlen" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None
                    ):
                        ring = True
            return (name, ring)
    return None


class Container:
    """One shared container identity."""

    __slots__ = (
        "var", "path", "lineno", "kind", "ring", "grows", "shrinks",
        "bounded_reason",
    )

    def __init__(self, var, path, lineno, kind, ring) -> None:
        self.var = var  # ("g", path, name) | ("f", path, class, attr)
        self.path = path  # birth path (for the annotation lookup)
        self.lineno = lineno  # birth line
        self.kind = kind
        self.ring = ring
        self.grows: List[GrowSite] = []
        self.shrinks: List[Tuple[str, int]] = []
        self.bounded_reason: Optional[str] = None

    def render_var(self) -> str:
        if self.var[0] == "g":
            return f"module global `{self.var[2]}`"
        return f"shared field `{self.var[2]}.{self.var[3]}`"


class GrowSite:
    __slots__ = ("key", "path", "lineno", "col", "what")

    def __init__(self, key, path, lineno, col, what) -> None:
        self.key = key
        self.path = path
        self.lineno = lineno
        self.col = col
        self.what = what  # rendered op, e.g. "`_REGISTRY[name] = ...`"


def _class_attrs_with_containers(mod: ModuleIndex, cname: str, rec):
    """(attr -> (kind, ring, birth lineno)) for fields born as
    containers in this class's methods or annotated as one."""
    out: Dict[str, Tuple[str, bool, int]] = {}
    for m in rec["methods"].values():
        for node in ast.walk(m):
            if not isinstance(node, ast.Assign):
                continue
            birth = _container_birth(mod, node.value)
            if birth is None:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.setdefault(t.attr, (*birth, node.lineno))
    for item in rec["node"].body:
        if isinstance(item, ast.AnnAssign) and isinstance(
            item.target, ast.Name
        ):
            base = item.annotation
            if isinstance(base, ast.Subscript):
                base = base.value
            name = _dotted(base).split(".")[-1]
            if name in _CONTAINER_ANNOTATIONS:
                out.setdefault(
                    item.target.id, (name.lower(), False, item.lineno)
                )
    return out


def _refs_target(value: ast.AST, t: ast.AST) -> bool:
    """Does `value` reference the same identity `t` names?"""
    if isinstance(t, ast.Name):
        return any(
            isinstance(n, ast.Name) and n.id == t.id
            for n in ast.walk(value)
        )
    return any(
        isinstance(n, ast.Attribute)
        and n.attr == t.attr
        and isinstance(n.value, ast.Name)
        and n.value.id == "self"
        for n in ast.walk(value)
    )


def _additive_rebuild(value: ast.AST, t: ast.AST) -> bool:
    """True for the strictly-additive reassignment shapes — a spread of
    the old contents plus new elements (`{**X, k: v}`, `[*X, e]`,
    `{*X, e}`) or a concat/union (`X + [...]`, `X | {...}`). These are
    growth, not eviction, and must not count as a reset site."""
    if isinstance(value, ast.Dict):
        return any(
            k is None and _refs_target(v, t)
            for k, v in zip(value.keys, value.values)
        )
    if isinstance(value, (ast.List, ast.Set, ast.Tuple)):
        return any(
            isinstance(e, ast.Starred) and _refs_target(e.value, t)
            for e in value.elts
        )
    if isinstance(value, ast.BinOp) and isinstance(
        value.op, (ast.Add, ast.BitOr)
    ):
        return _refs_target(value.left, t) or _refs_target(value.right, t)
    return False


def collect_growth(pkg: Package, attribution) -> Dict[tuple, Container]:
    """All shared containers with their grow/shrink sites.
    `attribution` is tmrace's lockset._Attribution (owner-class
    resolution, so a subclass's `self.items.append` lands on the base
    class's container identity)."""
    containers: Dict[tuple, Container] = {}

    # -- births --
    for mod in pkg.modules.values():
        for node in mod.tree.body:
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            birth = _container_birth(mod, node.value)
            if birth is None and isinstance(node, ast.AnnAssign):
                base = node.annotation
                if isinstance(base, ast.Subscript):
                    base = base.value
                nm = _dotted(base).split(".")[-1]
                if nm in _CONTAINER_ANNOTATIONS:
                    birth = (nm.lower(), False)
            if birth is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    var = ("g", mod.path, t.id)
                    containers.setdefault(
                        var,
                        Container(var, mod.path, node.lineno, *birth),
                    )
        for cname, rec in mod.classes.items():
            for attr, (kind, ring, ln) in _class_attrs_with_containers(
                mod, cname, rec
            ).items():
                owner = attribution.owner(mod, cname, attr) or (
                    mod.path, cname
                )
                var = ("f", owner[0], owner[1], attr)
                containers.setdefault(
                    var, Container(var, mod.path, ln, kind, ring)
                )

    # -- grow/shrink sites --
    for fi in pkg.functions.values():
        mod = pkg.modules[fi.path]
        globals_here = {
            v[2] for v in containers if v[0] == "g" and v[1] == fi.path
        }
        is_init = fi.qualname.split(".")[-1] in ("__init__", "__new__")
        # scope-correct name resolution, same discipline tmrace's
        # lockset walker uses: a plain `X = ...` (or arg/for/with
        # binding) WITHOUT `global X` makes X a local — its grows must
        # not count against the module container and, critically, its
        # assignment must not register as a fake "reset" that proves a
        # genuinely unbounded global bounded
        declared_global: Set[str] = set()
        bound: Set[str] = set()
        for node in _body_walk(fi.node):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        args = fi.node.args
        for a in (
            list(args.args)
            + list(args.posonlyargs)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            bound.add(a.arg)
        def binding_names(t: ast.AST):
            # only targets that BIND a name: `X = ...` binds X, but
            # `X[k] = ...` / `X.attr = ...` mutate without binding —
            # their receiver must stay resolvable as the module global
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from binding_names(e)
            elif isinstance(t, ast.Starred):
                yield from binding_names(t.value)

        for node in _body_walk(fi.node):
            tgts: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                tgts = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                tgts = [node.target]
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                tgts = [node.target]
            elif isinstance(node, ast.withitem) and node.optional_vars:
                tgts = [node.optional_vars]
            elif isinstance(node, ast.comprehension):
                tgts = [node.target]
            for t in tgts:
                bound.update(binding_names(t))
        shadowed = bound - declared_global

        def var_of(recv: ast.AST) -> Optional[tuple]:
            if isinstance(recv, ast.Name) and recv.id not in shadowed:
                if recv.id in globals_here:
                    return ("g", fi.path, recv.id)
                # from-imported container global born in ANOTHER
                # module: `from ..crypto.sigcache import _gen0;
                # _gen0.add(k)` must grow sigcache's identity
                entry = mod.from_imports.get(recv.id)
                if entry is not None and entry[0] is not None:
                    target = pkg.module_for_dotted(entry[0])
                    if target is not None:
                        v = ("g", target.path, entry[2])
                        if v in containers:
                            return v
                return None
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
            ):
                head = recv.value.id
                if head == "self" and fi.class_name:
                    owner = attribution.owner(
                        mod, fi.class_name, recv.attr
                    )
                    if owner is None:
                        owner = (fi.path, fi.class_name)
                    v = ("f", owner[0], owner[1], recv.attr)
                    return v if v in containers else None
                # module-attr receiver: `sigcache._gen0.add(k)` /
                # `trace._ring.append(e)` through an imported module
                target = None
                entry = mod.from_imports.get(head)
                if entry is not None and entry[0] is not None:
                    base = (
                        entry[0] + "." + entry[2]
                        if entry[0]
                        else entry[2]
                    )
                    target = pkg.module_for_dotted(base)
                else:
                    alias = mod.import_alias.get(head)
                    if alias is not None:
                        prefix = pkg.pkg_name + "."
                        if alias.startswith(prefix):
                            target = pkg.module_for_dotted(
                                alias[len(prefix):]
                            )
                if target is not None:
                    v = ("g", target.path, recv.attr)
                    if v in containers:
                        return v
            return None

        def record(var, node, what, grow: bool):
            c = containers.get(var)
            if c is None:
                return
            if grow:
                if is_init and var[0] == "f":
                    return  # construction, not growth
                c.grows.append(
                    GrowSite(fi.key, fi.path, node.lineno,
                             node.col_offset, what)
                )
            else:
                c.shrinks.append((fi.path, node.lineno))

        for node in _body_walk(fi.node):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                m = node.func.attr
                if m in _GROW_METHODS or m in _SHRINK_METHODS:
                    var = var_of(node.func.value)
                    if var is not None:
                        recv = _dotted(node.func.value) or "<recv>"
                        record(
                            var, node, f"`{recv}.{m}(...)`",
                            m in _GROW_METHODS,
                        )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        var = var_of(t.value)
                        if var is None:
                            continue
                        recv = _dotted(t.value) or "<recv>"
                        if isinstance(t.slice, ast.Slice):
                            # slice assignment replaces content: reset
                            record(var, node, "", False)
                        else:
                            record(
                                var, node, f"`{recv}[...] = ...`", True
                            )
                    elif isinstance(t, ast.Name) or (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        # plain reassignment of the identity = reset
                        # (rotation / epoch rebuild / filtered copy);
                        # augmented assign (`x += [..]`) is growth —
                        # and so is an ADDITIVE self-rebuild
                        # (`X = {**X, k: v}` / `X = X + [e]` /
                        # `X = X | {e}`): growth spelled as assignment
                        # must not double as its own boundedness proof.
                        # A comprehension referencing X (a filtered
                        # copy) stays a reset — that IS eviction.
                        var = var_of(t)
                        if var is None:
                            continue
                        nm = (
                            t.id
                            if isinstance(t, ast.Name)
                            else f"self.{t.attr}"
                        )
                        if isinstance(node, ast.AugAssign):
                            record(var, node, f"`{nm} += ...`", True)
                        elif _additive_rebuild(node.value, t):
                            record(
                                var, node,
                                f"`{nm} = ...{nm}...` additive rebuild",
                                True,
                            )
                        elif not (is_init and var[0] == "f"):
                            # the birth assignment in __init__ is
                            # construction, not an eviction/reset site
                            record(var, node, "", False)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        var = var_of(t.value)
                        if var is not None:
                            record(var, node, "", False)
    return containers
